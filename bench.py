"""Benchmark: MNIST CNN training throughput, images/sec/chip (+ MFU).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "mfu": ..., "backend": ..., "device_kind": ..., ...}

``value`` is this framework's jitted scan-epoch training throughput.
``mfu`` is model-FLOPs utilization: (FLOPs/step x steps/sec) / chip peak
FLOPs, with FLOPs/step taken from the compiled program's own cost analysis
(falling back to an analytic count for the 2-conv CNN) and the peak from the
device kind's bf16 spec (the CNN computes in bfloat16, models/cnn.py).

``vs_baseline`` compares against the only baseline measurable here: the
reference implementation's approach — a PyTorch per-batch train loop with
the same CNN and optimizer — on the hardware the reference can use in this
environment (CPU; the reference repo is CUDA-only and publishes no numbers
of its own, see BASELINE.md). The ``baseline`` field names this so the ratio
is not mistaken for a like-for-like chip comparison.

Robustness (round-1 postmortem: BENCH_r01.json was rc=1/parsed=null because
one TPU-init failure escaped as a traceback; round-2: both TPU children
timed out compiling from scratch against a wedged chip link and the round's
artifact ended up CPU-only): the accelerator bench runs in a CHILD process
with a timeout and a three-level degradation ladder —

1. a cheap PROBE child first (per-step jit, batch 256 — seconds of compile,
   not minutes), then the full 50-step scan bench; if the scan fails but
   the probe produced a number, the probe's throughput is reported with
   ``"mode": "probe"`` so a half-healthy link still yields a TPU number;
2. every child shares a persistent XLA compilation cache
   (``BENCH_COMPILE_CACHE``, default ``<repo>/.xla_cache`` — the same dir
   ``tools/tpu_watch.sh`` pre-warms), so a recovered chip skips the
   compile minutes that blew round 2's timeouts;
3. if no live TPU attempt succeeds, the freshest watcher capture
   (``tools/captured/bench.json``, written by ``tools/tpu_watch.sh`` the
   moment the chip answers mid-session) is emitted with its capture
   timestamp and ``"source": "watcher_capture"`` — a mid-session TPU
   measurement becomes end-of-round evidence automatically;
4. only then the CPU-backend fallback (honestly labelled
   ``"backend": "cpu"`` with the TPU errors attached); if even that fails
   the parent still exits 0 with an ``{"error": ...}`` JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BATCH = 2048  # throughput peak on v5e: ~430k img/s at 2048-4096, +22% over 1024
TORCH_STEPS = 8

# ViT bench mode (--vit): the CNN headline is HBM-bound at 1.9
# MFLOP/image, so its MFU says nothing about the MXU path. This config is
# the end-to-end MXU-bound twin: patch 1 -> T=784 tokens/image, width 512
# (head_dim 128 = the MXU/flash tile), depth 6, remat — ~111
# GFLOP/image model FLOPs, the regime where honest MFU is meaningful.
VIT_BATCH = 128
VIT_CFG = dict(patch_size=1, embed_dim=512, depth=6, num_heads=4)

# Per-chip peak dense bf16 FLOPs by TPU generation (public spec sheets).
_PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# Analytic fallback: forward FLOPs/image for models/cnn.py (2 MACs per
# multiply-add), x3 for a training step (fwd + ~2x in bwd).
_CNN_FWD_FLOPS = (
    2 * 28 * 28 * 32 * 9 * 1  # conv1
    + 2 * 28 * 28 * 64 * 9 * 32  # conv2
    + 2 * (64 * 14 * 14) * 128  # fc1
    + 2 * 128 * 10  # fc2
)
_CNN_STEP_FLOPS_PER_IMAGE = 3 * _CNN_FWD_FLOPS


def _peak_flops(device_kind: str):
    fake = os.environ.get("BENCH_FAKE_PEAK_FLOPS")
    if fake:  # test-only: lets the hermetic CPU suite exercise the
        return float(fake)  # MFU math and the impossibility guard
    kind = device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def _fake_bounds() -> dict:
    """Test-only physical-bound overrides present in the environment.
    They must never silently shape a real capture: every child refuses
    to run on a real TPU with them set (``_refuse_fakes_on_tpu``) and
    stamps them into its output otherwise."""
    return {k: os.environ[k]
            for k in ("BENCH_FAKE_PEAK_FLOPS", "BENCH_FAKE_HBM_BW")
            if os.environ.get(k)}


def _refuse_fakes_on_tpu(result: dict, platform: str):
    """Returns an error dict when a test-only bound override leaked into
    a real TPU run (the capture would carry a valid-looking sync marker
    with bounds computed against a fake peak); stamps the overrides into
    ``result`` on non-TPU backends so a test run can never pass as
    evidence. Returns None when the run may proceed."""
    fakes = _fake_bounds()
    if not fakes:
        return None
    if platform == "tpu":
        return {"ok": False,
                "error": f"test-only bound overrides set on a real TPU "
                         f"run: {sorted(fakes)}"}
    result["fake_bounds"] = fakes
    return None


def configure_jax(jax_module, force_cpu: bool = False) -> None:
    """Shared jax prologue for every bench entry point (this file's
    children and tools/bench_kernels.py): honor an explicit CPU request
    despite accelerator plugins that force-write ``jax_platforms`` on
    import (same workaround as tests/conftest.py), and enable the
    persistent compile cache shared with tools/tpu_watch.sh — a chip that
    recovered mid-session already has that cache warm, so the driver's
    end-of-round run spends its timeout measuring, not compiling
    (round-2 postmortem).

    The cache config itself goes through the ONE shared wiring every
    entry point uses (``utils/compile_cache.configure``, same as
    ``cli.run``); ``BENCH_COMPILE_CACHE`` acts as the bench-level flag
    (set-but-empty = explicitly disabled, as the hermetic tests use).
    """
    if force_cpu or os.environ.get("JAX_PLATFORMS") == "cpu":
        jax_module.config.update("jax_platforms", "cpu")
    from pytorch_distributed_mnist_tpu.utils.compile_cache import configure

    configure(os.environ.get("BENCH_COMPILE_CACHE"))


def _warmup_and_time(run_fn, st, expected_count, reps: int):
    """Shared timing protocol: one compile/warmup pass synced by a full
    host read of the metric count, then best-of-``reps`` with the same
    host-read sync per rep — identical for every measured path (CNN
    primary, secondaries, ViT) so the numbers stay comparable. The host
    read is the sync point: ``block_until_ready`` alone proved
    insufficient on the proxied chip link (round-3 kernels postmortem)."""
    st, m = run_fn(st)
    float(m.count)  # full host roundtrip: remote execution definitely done
    t_best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        st, m = run_fn(st)
        assert float(m.count) == expected_count
        t_best = min(t_best, time.perf_counter() - t0)
    return st, t_best


def _vit_model_flops_per_image(t: int, c: int, depth: int, patch: int,
                               num_classes: int = 10,
                               mlp_ratio: int = 4) -> float:
    """Analytic MODEL FLOPs per image for one ViT training step (fwd +
    2x bwd), matmuls only — the MFU convention. Per block: qkv 6TC² +
    out-proj 2TC² + MLP 4·r·TC² + attention QKᵀ/PV 4T²C. Remat
    recompute is deliberately NOT credited: MFU counts useful model
    FLOPs, so a rematerialized run reports the lower honest figure."""
    per_block = (8 + 4 * mlp_ratio) * t * c * c + 4 * t * t * c
    embed = 2 * t * (patch * patch) * c
    head = 2 * c * num_classes
    return 3.0 * (depth * per_block + embed + head)


def child_bench_vit(steps: int, reps: int) -> dict:
    """End-to-end ViT training throughput + honest MFU (``--vit``).

    Same machinery as the CNN scan-epoch bench — create_train_state,
    make_train_epoch, metric-count host sync — on the MXU-bound
    VIT_CFG. Primary path: Pallas flash attention; secondary: the same
    model with dense XLA attention (the baseline ratio). CPU fallback
    shrinks to a smoke-test shape with dense f32 attention (flash off
    TPU is interpret-mode — a meaningless thing to time).
    """
    if os.environ.get("BENCH_FORCE_CPU"):
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    configure_jax(jax, force_cpu=bool(os.environ.get("BENCH_FORCE_CPU")))

    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_mnist_tpu.data.mnist import (
        normalize_images,
        synthetic_dataset,
    )
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_epoch

    n_chips = jax.device_count()
    device = jax.devices()[0]
    fake_stamp: dict = {}
    refused = _refuse_fakes_on_tpu(fake_stamp, device.platform)
    if refused:
        return refused
    mesh = make_mesh(("data",)) if n_chips > 1 else None
    on_tpu = device.platform == "tpu"
    # Test-only: drive the exact TPU branch (flash attention + remat +
    # bf16 + dense secondary) at tiny shapes on CPU (flash falls back to
    # interpret mode), so a latent bug there surfaces in the hermetic
    # suite instead of burning a rare chip-recovery window. Labelled in
    # the output via the shrunken model_config + backend "cpu".
    smoke = bool(os.environ.get("BENCH_VIT_TPU_SMOKE")) and not on_tpu
    flash_path = on_tpu or smoke
    if on_tpu:
        batch, cfg = VIT_BATCH, dict(VIT_CFG)
        dtype = jnp.bfloat16
    elif smoke:
        batch = 8
        cfg = dict(patch_size=7, embed_dim=32, depth=1, num_heads=2)
        dtype = jnp.bfloat16
    else:
        batch = 32
        cfg = dict(patch_size=4, embed_dim=64, depth=2, num_heads=4)
        dtype = jnp.float32
    t_seq = (28 // cfg["patch_size"]) ** 2
    flops_per_image = _vit_model_flops_per_image(
        t_seq, cfg["embed_dim"], cfg["depth"], cfg["patch_size"])

    images, labels = synthetic_dataset(batch, seed=0)
    x = normalize_images(images)
    y = labels.astype(np.int32)
    batches = {
        "image": jnp.broadcast_to(jnp.asarray(x), (steps,) + x.shape),
        "label": jnp.broadcast_to(jnp.asarray(y), (steps,) + y.shape),
    }

    from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

    def measure(attn_fn, program):
        model = get_model(
            "vit", attention_fn=attn_fn, remat=flash_path,
            compute_dtype=dtype, **cfg)
        state = create_train_state(model, jax.random.key(0))
        epoch_fn = make_train_epoch(mesh)
        with compile_log.measure(program):
            compiled = epoch_fn.lower(state, batches).compile()
        state, best = _warmup_and_time(
            lambda st: compiled(st, batches), state, batch * steps, reps)
        del state
        return best

    flash_s = measure(flash_attention if flash_path else None,
                      "vit_epoch_flash" if flash_path else "vit_epoch_dense")
    peak = _peak_flops(device.device_kind)
    img_per_sec = batch * steps / flash_s / n_chips
    mfu = (flops_per_image * img_per_sec / peak) if peak else None
    if mfu is not None and mfu > 1.0:
        # Same physical bound as tools/bench_kernels.py: >100% of peak
        # means the sync failed; the number must not survive as evidence.
        return {"ok": False,
                "error": f"impossible ViT MFU {mfu:.3g} (>100% of peak): "
                         f"device sync did not wait for execution"}
    result = {
        "ok": True,
        "images_per_sec_per_chip": img_per_sec,
        "steps_per_sec": steps / flash_s,
        "global_batch": batch,
        "n_chips": n_chips,
        "backend": device.platform,
        "device_kind": device.device_kind,
        "seq_len": t_seq,
        "model_config": cfg,
        "attention": "flash" if flash_path else "dense",
        "remat": flash_path,
        "model_flops_per_image": flops_per_image,
        "peak_flops_per_chip": peak,
        "mfu": mfu,
        "sync": "host_read",
    }
    result.update(fake_stamp)
    if flash_path:
        # Baseline ratio: byte-identical model/step with dense XLA
        # attention. Secondary — a failure here never harms the primary.
        try:
            dense_s = measure(None, "vit_epoch_dense")
            dense_mfu = (flops_per_image * batch * steps
                         / dense_s / n_chips / peak) if peak else None
            if dense_mfu is not None and dense_mfu > 1.0:
                # The dense twin is the DENOMINATOR of the headline
                # flash_over_dense ratio; an early-sync dense time would
                # publish a garbage speedup under a valid-looking flash
                # line. Record the violation, never the ratio.
                result["dense_attn_error"] = (
                    f"impossible dense ViT MFU {dense_mfu:.3g} (>100% "
                    f"of peak): device sync did not wait for execution")
            else:
                result["images_per_sec_per_chip_dense_attn"] = (
                    batch * steps / dense_s / n_chips)
                result["flash_over_dense_speedup"] = dense_s / flash_s
                result["dense_attn_mfu"] = dense_mfu
        except Exception as exc:  # noqa: BLE001
            result["dense_attn_error"] = repr(exc)
    result["compile_stats"] = compile_log.stats()
    return result


def child_bench(steps: int, reps: int, probe: bool = False) -> dict:
    """Run the accelerator bench on whatever backend the env selects.

    ``probe`` selects the cheap path: small batch, per-step jit (a program
    that compiles in seconds), no fused-kernel secondary — the canary that
    tells a flaky chip link apart from a dead one and still produces an
    honest throughput number when the full scan bench can't finish.
    """
    if os.environ.get("BENCH_FORCE_CPU"):
        # The env var must be set before jax imports; the config write-back
        # in configure_jax handles plugins that override it at import.
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    configure_jax(jax, force_cpu=bool(os.environ.get("BENCH_FORCE_CPU")))

    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_mnist_tpu.data.mnist import (
        normalize_images,
        synthetic_dataset,
    )
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import (
        make_train_epoch,
        make_train_step,
    )

    n_chips = jax.device_count()
    device = jax.devices()[0]
    fake_stamp: dict = {}
    refused = _refuse_fakes_on_tpu(fake_stamp, device.platform)
    if refused:
        return refused
    mesh = make_mesh(("data",)) if n_chips > 1 else None
    # Stepwise = time the per-batch jitted step instead of the scan epoch:
    # the CPU fallback needs it (XLA:CPU pessimizes convs inside scanned
    # while-bodies ~30x), and the probe wants it (seconds of compile).
    stepwise = device.platform == "cpu" or probe
    if device.platform == "cpu":
        # Fallback mode: bf16 conv is emulated (and awful) on CPU; use f32
        # and a smaller batch so the fallback finishes in seconds, not
        # minutes. The TPU path keeps the bf16 MXU configuration. The
        # forced-secondaries test mode shrinks further: its scan-epoch
        # programs hit XLA:CPU's pathological conv-in-loop path, and it
        # only needs to prove the plumbing, not measure.
        batch = 64 if os.environ.get("BENCH_FORCE_SECONDARIES") else 256
        model = get_model("cnn", compute_dtype=jnp.float32)
    elif probe:
        batch = 256
        model = get_model("cnn")
    else:
        batch = BATCH
        model = get_model("cnn")
    state = create_train_state(model, jax.random.key(0))

    images, labels = synthetic_dataset(batch, seed=0)
    x = normalize_images(images)
    y = labels.astype(np.int32)
    batches = {
        "image": jnp.broadcast_to(x, (steps,) + x.shape),
        "label": jnp.broadcast_to(y, (steps,) + y.shape),
    }

    from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

    # AOT-compile the measured program ONCE (timed + cache-accounted per
    # program in compile_log) and drive the timing loop with the compiled
    # executable directly. One compile serves both the cost analysis and
    # the measurement — the program never re-lowers into a cache fetch of
    # its own just-written entry (an in-process read-after-write some
    # jaxlib CPU runtimes handle unsoundly; see docs/DESIGN.md).
    if stepwise:
        # On TPU the scan epoch is the whole point: one device program per
        # epoch, no host round-trips through the tunnel. The stepwise path
        # exists for the CPU fallback and the probe (see above).
        one = {"image": jnp.asarray(x), "label": jnp.asarray(y)}
        step_fn = make_train_step(mesh)
        with compile_log.measure("train_step"):
            compiled = step_fn.lower(state, one).compile()

        def run_pass(state):
            m = None
            for _ in range(steps):
                state, m = compiled(state, one)
            return state, m

        per_step_scale = 1.0
    else:
        epoch_fn = make_train_epoch(mesh)
        with compile_log.measure("train_epoch"):
            compiled = epoch_fn.lower(state, batches).compile()

        def run_pass(state):
            return compiled(state, batches)

        per_step_scale = float(steps)

    flops_per_step = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        total = float(cost.get("flops", 0.0))
        if total > 0:
            flops_per_step = total / per_step_scale
    except Exception:
        pass
    if not flops_per_step:
        flops_per_step = float(_CNN_STEP_FLOPS_PER_IMAGE * batch)

    expected = batch * (1 if stepwise else steps)
    state, best = _warmup_and_time(run_pass, state, expected, reps)

    steps_per_sec = steps / best
    peak = _peak_flops(device.device_kind)
    mfu = (flops_per_step * steps_per_sec / n_chips / peak) if peak else None
    if mfu is not None and mfu > 1.0:
        # Same physical bound as tools/bench_kernels.py: >100% of peak
        # means the sync failed; the number must not survive as evidence.
        return {"ok": False,
                "error": f"impossible CNN MFU {mfu:.3g} (>100% of peak): "
                         f"device sync did not wait for execution"}
    result = {
        "ok": True,
        "images_per_sec_per_chip": batch * steps / best / n_chips,
        "steps_per_sec": steps_per_sec,
        "global_batch": batch,
        "n_chips": n_chips,
        "backend": device.platform,
        "device_kind": device.device_kind,
        "flops_per_step": flops_per_step,
        "peak_flops_per_chip": peak,
        "mfu": mfu,
    }
    result.update(fake_stamp)
    if probe:
        result["mode"] = "probe"
    if os.environ.get("BENCH_FORCE_SECONDARIES"):
        # Test-only mode (shrunken batch, CPU secondaries): label the
        # line so it can never pass silently as a comparable measurement.
        result["forced_secondaries"] = True

    # Secondaries normally run on accelerator only; BENCH_FORCE_SECONDARIES
    # exists so the hermetic suite can pin their plumbing on CPU (a broken
    # secondary otherwise surfaces only as a *_error field during the
    # chip's rare capture windows — how the fused-path TypeError hid).
    secondaries = (device.platform != "cpu"
                   or bool(os.environ.get("BENCH_FORCE_SECONDARIES")))
    if secondaries and not probe \
            and not os.environ.get("BENCH_SKIP_INDEXED"):
        # Secondary: the device-gather input path (--epoch-gather device)
        # on a real permuted dataset — the dataset resident in HBM, each
        # scan tick jnp.take-ing its rows. Unlike the primary (which
        # re-feeds one broadcast batch), this measures the throughput a
        # real epoch with fresh indices sees. Extra fields only.
        try:
            from pytorch_distributed_mnist_tpu.train.steps import (
                make_train_epoch_indexed,
            )

            n = steps * batch
            imgs, labs = synthetic_dataset(n, seed=1)
            data = {"image": jnp.asarray(normalize_images(imgs)),
                    "label": jnp.asarray(labs.astype(np.int32))}
            perm = np.random.default_rng(0).permutation(n).astype(np.int32)
            ticks = {"idx": jnp.asarray(perm.reshape(steps, batch)),
                     "mask": jnp.ones((steps, batch), jnp.float32)}
            epoch_ix_fn = make_train_epoch_indexed(mesh)
            state_ix = create_train_state(model, jax.random.key(0))
            # Host snapshot of the fresh init: the sorted-ticks twin below
            # must start from IDENTICAL values, and the compiled
            # executable validates pytree statics strictly — a second
            # create_train_state would carry a fresh optax closure and be
            # rejected; np.copy of the same tree keeps treedef and values.
            import jax.tree_util as jtu

            init_ix = jtu.tree_map(np.asarray, state_ix)
            with compile_log.measure("train_epoch_indexed"):
                epoch_ix = epoch_ix_fn.lower(state_ix, data, ticks).compile()
            state_ix, best_ix = _warmup_and_time(
                lambda st: epoch_ix(st, data, ticks), state_ix,
                batch * steps, reps)
            result["images_per_sec_per_chip_device_gather"] = (
                batch * steps / best_ix / n_chips)
            # Hypothesis probe for the round-3 10%-slower finding: the
            # random-row gather's HBM locality. Same batch MEMBERSHIP
            # (identical loss/grad up to fp reduction order), indices
            # sorted within each tick — if this closes the gap, the
            # fix is sort-in-sampler; if not, the gather itself is the
            # cost and the north-star default should flip to host.
            ticks_sorted = {
                "idx": jnp.asarray(np.sort(
                    perm.reshape(steps, batch), axis=1)),
                "mask": jnp.ones((steps, batch), jnp.float32)}
            state_ix2 = jtu.tree_map(np.copy, init_ix)
            state_ix2, best_ix2 = _warmup_and_time(
                lambda st: epoch_ix(st, data, ticks_sorted), state_ix2,
                batch * steps, reps)
            result["images_per_sec_per_chip_device_gather_sorted"] = (
                batch * steps / best_ix2 / n_chips)
            # Free the ~320 MB resident dataset before the next secondary
            # measures: dead bench arrays must not skew its HBM headroom.
            del data, ticks, ticks_sorted, state_ix, state_ix2
        except Exception as exc:  # noqa: BLE001 - secondary only
            result["device_gather_error"] = repr(exc)

    if secondaries and not probe \
            and not os.environ.get("BENCH_SKIP_FUSED"):
        # Secondary measurement: the all-first-party-kernel path (Pallas
        # fused cross-entropy + fused Adam). Extra fields only — any
        # failure here is recorded and cannot harm the primary number.
        # Passing the mesh embeds the loss kernel in the GSPMD program
        # via its nested shard_map (per-device batch shards, no gather) —
        # the same path `--loss fused` takes on a multi-chip run.
        try:
            from pytorch_distributed_mnist_tpu.ops.loss import set_loss_impl

            set_loss_impl("fused", mesh=mesh)
            try:
                state_f = create_train_state(
                    model, jax.random.key(0), optimizer="adam_pallas")
                epoch_f_fn = make_train_epoch(mesh)
                with compile_log.measure("train_epoch_fused"):
                    epoch_f = epoch_f_fn.lower(state_f, batches).compile()
                state_f, best_f = _warmup_and_time(
                    lambda st: epoch_f(st, batches), state_f,
                    batch * steps, reps)
                result["images_per_sec_per_chip_fused_kernels"] = (
                    batch * steps / best_f / n_chips)
            finally:
                set_loss_impl("xla")
        except Exception as exc:  # noqa: BLE001 - secondary must not fail the bench
            result["fused_kernels_error"] = repr(exc)
    # Per-program compile observability: wall ms, XLA compiles, and
    # persistent-cache hit/miss for every program measured above — the
    # cold-vs-warm compile evidence BENCH_r*.json tracks across rounds.
    result["compile_stats"] = compile_log.stats()
    return result


def _run_child(env_extra: dict, steps: int, reps: int, timeout: float):
    env = dict(os.environ, **env_extra)
    # Test-only mode must be an explicit opt-in per child, never inherited
    # from an ambient shell export (it shrinks the batch and runs the
    # CPU-pathological scan secondaries — a contaminated primary number).
    if "BENCH_FORCE_SECONDARIES" not in env_extra:
        env.pop("BENCH_FORCE_SECONDARIES", None)
    if "BENCH_VIT" not in env_extra:  # mode is per-child, never ambient
        env.pop("BENCH_VIT", None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             str(steps), str(reps)],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"child timed out after {timeout:.0f}s"
    child_error = None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            if result.get("ok"):
                return result, None
            if child_error is None and result.get("error"):
                child_error = result["error"]  # the child's own diagnosis
    if child_error is not None:
        return None, f"rc={proc.returncode}: {child_error}"
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)


def _read_tpu_capture(env_var: str):
    """Shared reader for watcher capture files (both consumers below):
    resolve the path (``env_var`` overrides; set-but-empty = explicitly
    disabled), parse the LAST line as JSON, and validate it is a dict
    that really ran on TPU with a nonzero value. Returns
    ``(captured, path, mtime)`` or ``None`` — never raises: a corrupt or
    truncated capture must degrade, not crash the always-emit-JSON
    contract of ``main``."""
    repo = os.path.dirname(os.path.abspath(__file__))
    if env_var in os.environ:
        path = os.environ[env_var]
        if not path:
            return None
    else:
        path = os.path.join(repo, "tools", "captured", "bench.json")
    try:
        with open(path) as f:
            captured = json.loads(f.read().strip().splitlines()[-1])
        mtime = os.path.getmtime(path)
    except (OSError, IndexError, UnicodeDecodeError, json.JSONDecodeError):
        return None
    if not isinstance(captured, dict):  # e.g. a truncated write leaving
        return None                     # 'null' — still valid JSON
    if captured.get("backend") != "tpu" or not captured.get("value"):
        return None
    return captured, path, mtime


def _mtime_iso(mtime: float) -> str:
    """File-mtime fallback provenance for legacy captures without an
    embedded ``measured_at`` — one formatter for both consumers."""
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(mtime))


def _load_watcher_capture() -> dict | None:
    """Freshest mid-session TPU capture from tools/tpu_watch.sh, if any.

    The watcher polls the flaky chip link all session and runs this very
    benchmark the moment the chip answers; its output (the full formatted
    JSON line) is the round's evidence when the end-of-round live attempt
    hits a wedged link again. Only a capture that actually ran on TPU
    qualifies — a CPU-fallback capture is no better than a live CPU run.
    BENCH_CAPTURE_PATH overrides the path; tpu_watch_r5.sh sets it EMPTY
    so bench.py can never re-emit the watcher's own file.
    """
    repo = os.path.dirname(os.path.abspath(__file__))
    loaded = _read_tpu_capture("BENCH_CAPTURE_PATH")
    if loaded is None:
        return None
    captured, _, mtime = loaded
    # Freshness: only a capture from THIS round is evidence. The round
    # boundary markers are the driver's own artifacts (VERDICT.md /
    # BENCH_r*.json, written at round start); a stale capture restored by
    # git checkout shares their checkout mtime, while a live watcher write
    # during the session is strictly newer. Round 1 (no markers) accepts
    # any capture. BENCH_CAPTURE_PATH set => caller controls provenance
    # explicitly (tests), skip the bound.
    if "BENCH_CAPTURE_PATH" not in os.environ:
        import glob
        markers = glob.glob(os.path.join(repo, "BENCH_r*.json"))
        markers += [p for p in (os.path.join(repo, "VERDICT.md"),)
                    if os.path.exists(p)]
        marker_mtime = max(
            (os.path.getmtime(m) for m in markers if os.path.exists(m)),
            default=0.0)
        if mtime <= marker_mtime + 60.0:
            return None
    captured["source"] = "watcher_capture"
    if "measured_at" not in captured:
        # Legacy capture without an embedded measurement time; file mtime
        # is the best remaining provenance (weaker: a rewrite or git
        # checkout restamps it, which is why new lines embed measured_at).
        captured["capture_timestamp"] = _mtime_iso(mtime)
    return captured


def _last_valid_tpu_capture() -> dict | None:
    """Provenance pointer for chip-dead rounds (round-4 VERDICT weak #5).

    The freshness gate in ``_load_watcher_capture`` is right to refuse a
    prior round's capture as THIS round's measurement — but the resulting
    CPU-fallback artifact then looks like a 0.58x regression to anyone
    reading only ``BENCH_r*.json``. This returns a small, clearly
    non-headline pointer to the newest watcher capture that really ran on
    TPU, regardless of age: value + when it was measured + the commit
    that recorded it. Attached ONLY to lines whose own backend is not
    ``tpu`` (see ``main``); never a substitute for a fresh measurement.
    BENCH_LAST_CAPTURE_PATH overrides the path (empty = disabled; the r5
    watcher sets it empty so a capture never points at its predecessor).
    """
    repo = os.path.dirname(os.path.abspath(__file__))
    loaded = _read_tpu_capture("BENCH_LAST_CAPTURE_PATH")
    if loaded is None:
        return None
    captured, path, mtime = loaded
    pointer = {
        "value": captured["value"],
        "unit": captured.get("unit", "images/sec/chip"),
        "measured_at": captured.get("measured_at"),
        "note": "newest real-TPU capture on record; NOT this round's "
                "measurement (this round's line ran on the backend above)",
    }
    if pointer["measured_at"] is None:
        # Legacy capture without an embedded time: file mtime is the best
        # remaining provenance (weaker — a git checkout restamps it).
        pointer["measured_at"] = _mtime_iso(mtime)
        pointer["measured_at_source"] = "file_mtime"
    try:
        commit = subprocess.run(
            ["git", "log", "-1", "--format=%h", "--", path],
            capture_output=True, text=True, timeout=10, cwd=repo,
        ).stdout.strip()
        if commit:
            pointer["commit"] = commit
    except (OSError, subprocess.SubprocessError):
        pass
    return pointer


def bench_accelerator() -> dict:
    """Probe -> scan -> watcher capture -> CPU fallback; never raises."""
    os.environ.setdefault(
        "BENCH_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".xla_cache"))
    errors = []

    def _tpu_only(result, err, label):
        """A child that silently fell back to the CPU backend (plugin init
        failure with JAX_PLATFORMS unset) is NOT a TPU measurement — it
        must not shadow the watcher-capture fallback below."""
        if result is None:
            return None, err
        backend = result.get("backend")
        if backend != "tpu":
            return None, f"{label} ran on backend {backend!r}, not tpu"
        return result, None

    # Level 1: cheap probe — small batch, per-step jit, seconds of compile.
    # Tells a dead link apart from a slow one, and its number stands in if
    # the scan bench can't finish.
    probe, err = _run_child({"BENCH_PROBE": "1"}, steps=8, reps=2,
                            timeout=360.0)
    probe, err = _tpu_only(probe, err, "probe")
    if probe is None:
        errors.append(f"tpu probe: {err}")

    # Level 2: the real measurement — 50-step scan epoch. A live probe
    # means the link is up and the compile cache is warming, so it earns a
    # retry; a dead probe gets one shot in case the probe failure was
    # program-specific.
    timeouts = (600.0, 720.0) if probe else (480.0,)
    for attempt, timeout in enumerate(timeouts):
        result, err = _run_child({}, steps=50, reps=3, timeout=timeout)
        result, err = _tpu_only(result, err, "scan bench")
        if result:
            return result
        errors.append(f"tpu attempt {attempt + 1}: {err}")
        if attempt + 1 < len(timeouts):  # backoff only between retries
            time.sleep(15 * (attempt + 1))

    if probe:
        probe["tpu_error"] = "; ".join(errors)
        return probe

    # Level 3: a mid-session watcher capture is real TPU evidence; emit it
    # (timestamped, labelled) rather than degrade to CPU.
    captured = _load_watcher_capture()
    if captured is not None:
        return {"ok": True, "captured": captured,
                "live_errors": "; ".join(errors)}

    # Level 4: CPU. This environment has a single host core; keep the CPU
    # fallback tiny so it finishes inside the timeout (it exists to produce
    # an honest number, not a fast one).
    result, err = _run_child(
        {"BENCH_FORCE_CPU": "1"}, steps=4, reps=2, timeout=900.0
    )
    if result:
        result["tpu_error"] = "; ".join(errors)
        return result
    errors.append(f"cpu fallback: {err}")
    return {"ok": False, "error": "; ".join(errors)}


VIT_STEPS = 20


def bench_vit_accelerator() -> dict:
    """TPU ViT child -> CPU smoke fallback; never raises. No watcher-
    capture level here: tools/tpu_watch_r4.sh captures the ViT line to
    its own file (bench_vit.json) directly."""
    os.environ.setdefault(
        "BENCH_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".xla_cache"))
    errors = []
    result, err = _run_child({"BENCH_VIT": "1"}, steps=VIT_STEPS, reps=3,
                             timeout=1800.0)
    if result:
        return result  # honestly labelled by its own "backend" field
    errors.append(f"tpu vit: {err}")
    result, err = _run_child({"BENCH_VIT": "1", "BENCH_FORCE_CPU": "1"},
                             steps=2, reps=1, timeout=900.0)
    if result:
        result["tpu_error"] = "; ".join(errors)
        return result
    errors.append(f"cpu vit fallback: {err}")
    return {"ok": False, "error": "; ".join(errors)}


def main_vit() -> None:
    """The ``--vit`` output line: end-to-end MXU-bound perf evidence the
    CNN headline can't provide (VERDICT round-3 weak item 6)."""
    result = bench_vit_accelerator()
    out = {
        "metric": "mnist_vit_train_images_per_sec_per_chip",
        "unit": "images/sec/chip",
        "baseline": "same ViT/train-step with dense XLA attention "
                    "(flash_over_dense_speedup is the vs_baseline ratio)",
    }
    if result.get("ok"):
        out["value"] = round(result["images_per_sec_per_chip"], 1)
        speedup = result.get("flash_over_dense_speedup")
        out["vs_baseline"] = round(speedup, 3) if speedup else None
        mfu = result.get("mfu")
        out["mfu"] = round(mfu, 4) if mfu is not None else None
        for key in ("backend", "device_kind", "n_chips", "global_batch",
                    "steps_per_sec", "seq_len", "model_config", "attention",
                    "remat", "model_flops_per_image", "peak_flops_per_chip",
                    "images_per_sec_per_chip_dense_attn", "dense_attn_error",
                    "sync", "compile_stats", "tpu_error"):
            if result.get(key) is not None:
                val = result[key]
                out[key] = round(val, 2) if isinstance(val, float) else val
    else:
        out["value"] = 0.0
        out["vs_baseline"] = 0.0
        out["error"] = result.get("error", "unknown failure")
    out["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out))
    if not result.get("ok"):
        # Same convention as tools/bench_kernels.py / tools/sweep_flash.py
        # (round-4 advisor): a fully failed run never exits 0, so rc-gated
        # consumers (tools/tpu_watch_r5.sh run_capture) reject the line
        # without having to parse it.
        sys.exit(1)


SERVE_REQUESTS = 2000
SERVE_CONCURRENCY = 16


def _probe_xla_flags(candidate: str) -> bool:
    """Whether this jaxlib's XLA accepts ``candidate`` as ``XLA_FLAGS``.
    XLA ABORTS the process on an unknown flag at backend init
    (parse_flags_from_env is fatal — same pattern as tests/conftest.py),
    so every flag append below probes in a throwaway child first. ONE
    copy of the probe: the make_cpu_client surface has moved across
    jaxlibs before, and three drifting copies of this block is how that
    breaks silently."""
    probe = ("import os; os.environ['XLA_FLAGS'] = %r; "
             "from jaxlib import xla_client; xla_client.make_cpu_client()"
             % candidate)
    try:
        return subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, timeout=120
        ).returncode == 0
    except (OSError, subprocess.SubprocessError):
        return False


def _default_backend_is_cpu() -> bool:
    """Whether jax would select the CPU backend, probed in a throwaway
    child — an accelerator-less box auto-selects CPU without any env
    declaration, and THIS process must not init jax before XLA_FLAGS is
    final."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return False
    return probe.returncode == 0 and probe.stdout.strip() == "cpu"


def _run_is_cpu_bound() -> bool:
    """ONE copy of the is-this-run-CPU decision the CPU-isolation
    helpers share: an explicit env declaration short-circuits the child
    probe; otherwise the default backend decides."""
    return (os.environ.get("JAX_PLATFORMS") == "cpu"
            or bool(os.environ.get("BENCH_FORCE_CPU"))
            or _default_backend_is_cpu())


def _ensure_cpu_eigen_isolation() -> bool:
    """Append ``--xla_cpu_multi_thread_eigen=false`` to ``XLA_FLAGS`` so
    one XLA:CPU execution stops grabbing the whole host Eigen threadpool
    (one "chip" != the whole host); returns whether the isolation is
    active so the JSON lines can record the measurement environment
    honestly. Must run before the first jax device query — XLA_FLAGS are
    read once, at backend init. No-op on real accelerators (the flag
    only gates the CPU backend's intra-op pool)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_cpu_multi_thread_eigen" in flags:
        return "xla_cpu_multi_thread_eigen=false" in flags
    candidate = (flags + " --xla_cpu_multi_thread_eigen=false").strip()
    supported = _probe_xla_flags(candidate)
    if supported:
        os.environ["XLA_FLAGS"] = candidate
    return supported


def _isolate_cpu_serve_devices() -> bool:
    """Make the forced-multi-device CPU backend behave like N chips.

    With ``--xla_force_host_platform_device_count=N`` (the CI stand-in
    for an N-chip host), a SINGLE XLA:CPU execution still grabs the whole
    host Eigen threadpool — so the N "devices" the replica pool fans out
    across contend for every core and the scaling/pipelining measurement
    measures only that contention. Eigen isolation pins each execution to
    one thread, which is exactly the resource model the forced device
    count is simulating.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        return False  # single-device CPU or a real backend: nothing to fix
    return _ensure_cpu_eigen_isolation()


def main_serve() -> None:
    """``--mode serve``: the serving trajectory's BENCH line.

    Drives the real serving stack — bucketed AOT
    :class:`InferenceEngine` + :class:`MicroBatcher` — in process
    (closed-loop worker threads submitting straight to the batcher, no
    sockets), so the line measures micro-batching + device forward
    throughput/latency rather than Python's HTTP server. Emits ONE JSON
    line: requests/sec headline, p50/p95/p99 latency, the batch-size
    histogram, and the zero-steady-state-recompiles invariant checked
    via ``CompileLog``. Never raises; failures become an ``error`` line
    (the always-emit-JSON contract the training bench follows).

    The multi-chip data plane rides the same line:

    - ``replica_scaling``: requests/sec through an :class:`EnginePool`
      at 1, 2, ..., ``n_devices`` replicas (pipelined dispatch, window
      replicas+1), each point re-checking zero steady-state recompiles
      PER REPLICA via the per-replica ``CompileLog`` program names;
    - ``pipeline_speedup``: the full pool driven with the in-flight
      window at replicas+1 vs 1 — window 1 serializes every batch's
      host-side staging behind the previous batch's result fetch AND
      caps the fleet at one busy replica, so this is the pipelining
      win the PR claims (>1.0 on any backend with real parallelism).
      Pool drives use fixed 8-row exact-bucket requests (batch
      formation pinned — see ``pool_stacks``) and the ratio is the
      median of interleaved paired drives, so CPU-share drift on a
      shared CI box cancels instead of deciding the sign.

    The SHARDED plane (``serve/programs.py``) gets its own ``sharded``
    block: for each registered mode (tensor x vit, expert x moe_mlp),
    the ABBA-paired sharded-vs-replicated throughput ratio at the SAME
    chip count, a mesh-scaling curve at fixed chips (mesh 1 = the
    replicated fleet, up to one all-chip mesh group), and per
    bucket x mode zero-recompile verdicts that fail the bench loudly.
    On a CPU world the block carries the BENCH_r05-style fallback
    caveat: host-thread collectives say nothing about ICI, so only the
    schema and the recompile verdicts are meaningful there.

    The MPMD pipeline plane (``serve/pipeline.py``) gets the
    ``pipeline_serving`` block: one chain of per-chip stage programs
    driven with the in-flight window >= stages vs window 1
    (``stage_overlap_speedup``, ABBA-paired — the win of stage k
    computing batch N while stage k+1 computes batch N-1), per-stage
    synchronous step walls + occupancy (where the pipe's clock is set),
    and per bucket x stage zero-recompile verdicts that fail the bench
    loudly. Same CPU caveat discipline: host-thread transfers say
    nothing about ICI hop costs.

    The WHOLE-PROGRAM plane (ISSUE 16) gets the ``whole_program``
    block: one fused engine on the MFU-honest ViT config serving BOTH
    routes — raw uint8 through the fused bucket programs (in-XLA
    normalize, staging donated) vs host-normalized float32 through the
    split ones — with the ABBA-paired fused-over-split ratio, the
    host-work collapse in ms/request, staged H2D bytes per request
    (float32 vs raw uint8), forward-only MFU, the donated-staging
    retirement counts, and zero-recompile verdicts across both planes
    that fail the bench loudly. On TPU a median paired speedup below
    1.0 also fails the line; on CPU it is caveated instead (no MXU, no
    real H2D hop).

    In CI this runs on CPU with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=4``.
    """
    out = {
        "metric": "mnist_serve_requests_per_sec",
        "unit": "requests/sec",
        "baseline": "same engine, batching disabled (bucket-1 program "
                    "per request): vs_baseline is the micro-batching "
                    "speedup",
    }
    try:
        # Must run before the first jax device query: XLA_FLAGS are read
        # once, at backend init.
        cpu_isolated = _isolate_cpu_serve_devices()

        import jax

        configure_jax(jax, force_cpu=bool(os.environ.get("BENCH_FORCE_CPU")))

        import threading

        from pytorch_distributed_mnist_tpu.data.mnist import synthetic_dataset
        from pytorch_distributed_mnist_tpu.models import get_model
        from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher
        from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
        from pytorch_distributed_mnist_tpu.train.state import create_train_state
        from pytorch_distributed_mnist_tpu.utils.profiling import (
            ServeLog,
            compile_log,
        )

        device = jax.devices()[0]
        import jax.numpy as jnp

        # Same backend policy as the training bench: bf16 MXU path on
        # TPU, f32 on the CPU fallback.
        model = get_model(
            "cnn", **({} if device.platform == "tpu"
                      else {"compute_dtype": jnp.float32}))
        state = create_train_state(model, jax.random.key(0))
        serve_log = ServeLog()
        engine = InferenceEngine(model.apply, state.params,
                                 serve_log=serve_log)
        compile_log.reset()
        t0 = time.perf_counter()
        engine.warmup()
        warmup_s = time.perf_counter() - t0
        totals_after_warmup = dict(compile_log.stats()["totals"])

        images, _ = synthetic_dataset(64, seed=0)
        stacks = [engine.preprocess(images[i:i + 1]) for i in range(16)]
        # Pool drives use 8-row exact-bucket requests with max_batch=8:
        # one request == one bucket-8 batch, every time. Single-row
        # coalescing would couple batch FORMATION with the in-flight
        # window (a serialized window backs the queue up into larger,
        # better-packed batches), turning the pipeline on/off ratio into
        # a batch-size-efficiency measurement; fixed-shape requests pin
        # the device work per request so the ratio isolates pipelining.
        pool_stacks = [engine.preprocess(images[i:i + 8]) for i in range(8)]

        requests = int(os.environ.get("BENCH_SERVE_REQUESTS",
                                      SERVE_REQUESTS))
        concurrency = int(os.environ.get("BENCH_SERVE_CONCURRENCY",
                                         SERVE_CONCURRENCY))

        drive_errors: list = []

        def drive(batcher, requests_n: int, req_stacks=None) -> float:
            req_stacks = stacks if req_stacks is None else req_stacks
            counter = {"next": 0}
            lock = threading.Lock()

            def worker():
                while True:
                    with lock:
                        i = counter["next"]
                        if i >= requests_n:
                            return
                        counter["next"] = i + 1
                    try:
                        batcher.predict(req_stacks[i % len(req_stacks)])
                    except Exception as exc:  # noqa: BLE001
                        # A silently-dead worker would let the drive
                        # finish with unserved requests counted into the
                        # headline; collect and fail the line instead.
                        drive_errors.append(repr(exc))

            threads = [threading.Thread(target=worker, daemon=True)
                       for _ in range(concurrency)]
            t = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            return time.perf_counter() - t

        with MicroBatcher(engine.predict, max_batch=engine.max_batch,
                          max_wait_s=0.002, max_queue=4 * concurrency,
                          serve_log=serve_log) as batcher:
            drive(batcher, max(64, requests // 10))  # warm the path E2E
            serve_log.reset()
            # Best-of-2 (BASELINE.md timing protocol): one descheduled
            # burst on a shared CI box halves a single drive's apparent
            # throughput. The ServeLog keeps both drives' samples; the
            # headline uses the cleaner wall.
            wall = min(drive(batcher, requests) for _ in range(2))

        totals_after_load = dict(compile_log.stats()["totals"])
        zero_recompiles = (
            totals_after_load["backend_compiles"]
            == totals_after_warmup["backend_compiles"])
        snap = serve_log.snapshot()

        # Baseline twin: batching off — every request runs the bucket-1
        # program alone through a max_batch=1 batcher.
        with MicroBatcher(engine.predict, max_batch=1, max_wait_s=0.0,
                          max_queue=4 * concurrency) as batcher:
            baseline_wall = min(drive(batcher, requests)
                                for _ in range(2))

        # -- multi-chip data plane: replica scaling + pipelined dispatch.
        from pytorch_distributed_mnist_tpu.serve.pool import EnginePool

        def _serve_program_compiles() -> dict:
            return {name: rec["backend_compiles"]
                    for name, rec in compile_log.stats()["programs"].items()
                    if name.startswith("serve_forward_")}

        def _recompile_delta(before: dict, after: dict) -> dict:
            """Per-program compile-count changes across one drive (empty
            == the zero-steady-state-recompiles invariant held)."""
            return {name: (count, after[name])
                    for name, count in before.items()
                    if after[name] != count}

        def drive_pool(pool, window: int, requests_n: int,
                       reps: int = 3, fixed_shape: bool = False) -> float:
            """Best-of-``reps`` wall seconds for ``requests_n`` requests
            (the BASELINE.md timing protocol: best-of filters scheduler
            noise on a shared-core CI box, where one descheduled burst
            can halve a single drive's apparent throughput).
            ``fixed_shape`` drives the 8-row exact-bucket requests with
            ``max_batch=8`` — one request == one bucket-8 batch, every
            time — instead of realistic single-row coalescing."""
            req_stacks = pool_stacks if fixed_shape else stacks
            with MicroBatcher(
                    None, max_batch=8 if fixed_shape else pool.max_batch,
                    max_wait_s=0.002, max_queue=4 * concurrency,
                    dispatch_fn=pool.dispatch,
                    complete_fn=lambda h: pool.predict_complete(h)[0],
                    max_inflight=window) as pool_batcher:
                drive(pool_batcher, max(64, requests_n // 10),
                      req_stacks)  # warm E2E
                return min(drive(pool_batcher, requests_n, req_stacks)
                           for _ in range(reps))

        def drive_pool_interleaved(pool, windows, requests_n: int,
                                   reps: int = 5) -> dict:
            """``reps`` fixed-shape drives per window, INTERLEAVED in
            time with ABBA ordering (w0w1, w1w0, w0w1, ...): on a
            shares-throttled CI box the available CPU drifts with
            invisible neighbors, so the honest window-vs-window
            comparison pairs drives that ran next to each other — and
            alternating which window goes first cancels first-mover and
            linear-drift bias. Returns {window: [wall, ...]} in rep
            order."""
            walls = {w: [] for w in windows}
            for rep in range(reps):
                order = windows if rep % 2 == 0 else tuple(reversed(windows))
                for window in order:
                    walls[window].append(
                        drive_pool(pool, window=window,
                                   requests_n=requests_n, reps=1,
                                   fixed_shape=True))
            return walls

        n_devices = jax.device_count()
        # A quarter of the headline count per pool drive: the pool
        # section runs ~15 drives (3 scaling points x best-of-3 + 6
        # interleaved pipeline drives), so full-size drives would
        # quintuple the bench's wall time; 500-request drives keep the
        # ratio's sign stable (measured) at a bounded cost.
        pool_requests = int(os.environ.get("BENCH_SERVE_POOL_REQUESTS",
                                           max(400, requests // 4)))
        points = sorted({n for n in (1, 2, n_devices)
                         if 1 <= n <= n_devices})
        replica_scaling = []
        recompiled_replicas: list = []
        pipeline_speedup = 0.0
        pipeline_pairs: list = []
        for n in points:
            pool = EnginePool(model.apply, state.params,
                              devices=jax.local_devices()[:n])
            pool.warmup()
            before = _serve_program_compiles()
            pool_wall = drive_pool(pool, window=n + 1,
                                   requests_n=pool_requests)
            if n == n_devices:
                # Full pool: pipeline on (window n+1) vs off (window 1 —
                # strict dispatch->complete alternation, one busy
                # replica), on the FIXED-SHAPE drive so batch formation
                # cannot couple with the window (a serialized window
                # backs the queue up into larger, better-packed batches,
                # which would measure packing, not pipelining). The
                # speedup is the MEDIAN of the per-rep paired ratios
                # from interleaved drives: adjacent pairs see the same
                # neighbor load, so the ratio survives the CPU-share
                # drift that best-of-each-side would turn into noise.
                walls = drive_pool_interleaved(
                    pool, windows=(n + 1, 1), requests_n=pool_requests)
                pipeline_pairs = [round(off / on, 3) for on, off
                                  in zip(walls[n + 1], walls[1])]
                ratios = sorted(pipeline_pairs)
                pipeline_speedup = ratios[len(ratios) // 2]
            delta = _recompile_delta(before, _serve_program_compiles())
            if delta:
                recompiled_replicas.append(delta)
            replica_scaling.append({
                "replicas": n,
                "requests_per_sec": round(pool_requests / pool_wall, 1),
                "zero_steady_state_recompiles": not delta,
            })

        # -- sharded serving (serve/programs.py): per-mode paired
        # comparison vs replicated on the SAME chip count, and the
        # mesh-scaling curve. Fixed-shape 8-row drives throughout (the
        # pipeline block's reasoning: pin batch formation so the ratio
        # measures the data plane, not packing).
        sharded_requests = int(os.environ.get(
            "BENCH_SERVE_SHARDED_REQUESTS", pool_requests))
        sharded_block: dict = {}
        sharded_recompiles: list = []
        if n_devices < 2:
            sharded_block["skipped"] = (
                "single-device world: a serving mesh needs >= 2 chips")
        else:
            from pytorch_distributed_mnist_tpu.serve.programs import (
                get_serve_mode,
                registered_mode_models,
                validate_serve_mode,
            )

            # The LIVE registry, not a hardcoded list: a mode added via
            # register_serve_mode joins the comparison and the recompile
            # verdict automatically (the server's extension contract).
            for mode, model_name in registered_mode_models():
                if get_serve_mode(mode).engine_factory is not None:
                    # Non-SPMD modes (MPMD pipeline) are not a mesh
                    # lowering; they measure in their own block below.
                    continue
                shard_model = get_model(
                    model_name, **({} if device.platform == "tpu"
                                   else {"compute_dtype": jnp.float32}))
                shard_state = create_train_state(shard_model,
                                                 jax.random.key(0))
                # Mesh-scaling curve at FIXED chip count: mesh 1 is the
                # replicated plane (n_devices one-chip replicas), the
                # largest VALID point one spanning mesh group. A mesh a
                # sharded weight dim doesn't divide (e.g. more chips
                # than the MoE has experts) is dropped point-by-point;
                # a mode with no valid sharded point becomes a labeled
                # skip, not a traceback that loses the whole bench line.
                mesh_points, skip_reason = [1], None
                for mesh in sorted({2, n_devices}):
                    if n_devices % mesh:
                        continue
                    try:
                        validate_serve_mode(mode, model_name, mesh,
                                            shard_state.params)
                        mesh_points.append(mesh)
                    except ValueError as exc:
                        skip_reason = str(exc)
                if len(mesh_points) == 1:
                    sharded_block[mode] = {"model": model_name,
                                           "skipped": skip_reason}
                    continue
                full_mesh = mesh_points[-1]
                pools = {}
                for mesh in mesh_points:
                    if mesh == 1:
                        pools[mesh] = EnginePool(
                            shard_model.apply, shard_state.params,
                            devices=jax.local_devices()[:n_devices],
                            buckets=(1, 8))
                    else:
                        pools[mesh] = EnginePool(
                            shard_model.apply, shard_state.params,
                            devices=jax.local_devices()[:n_devices],
                            buckets=(1, 8), serve_mode=mode,
                            mesh_size=mesh, model_name=model_name)
                    pools[mesh].warmup()
                # Snapshot EVERY serve program (not just @{mode} names):
                # the replicated baseline leg drives @r{i} programs, and
                # a recompile stalling THAT side would silently skew
                # vs_replicated in the sharded mode's favor.
                before_mode = _serve_program_compiles()
                mesh_scaling = []
                for mesh in mesh_points:
                    groups = n_devices // mesh
                    wall_m = drive_pool(pools[mesh], window=groups + 1,
                                        requests_n=sharded_requests,
                                        reps=1, fixed_shape=True)
                    mesh_scaling.append({
                        "mesh_devices": mesh,
                        "mesh_groups": groups,
                        "requests_per_sec": round(
                            sharded_requests / wall_m, 1),
                    })
                # ABBA-paired sharded (full mesh, 1 group) vs replicated
                # (mesh 1, n one-chip replicas), each at its natural
                # window; adjacent pairs see the same neighbor load, so
                # the ratio survives CPU-share drift (PR 4 methodology).
                walls = {"sharded": [], "replicated": []}
                for rep in range(4):
                    order = (("sharded", "replicated") if rep % 2 == 0
                             else ("replicated", "sharded"))
                    for leg in order:
                        pool_leg = (pools[full_mesh] if leg == "sharded"
                                    else pools[1])
                        window = (n_devices // full_mesh + 1
                                  if leg == "sharded"
                                  else n_devices + 1)
                        walls[leg].append(drive_pool(
                            pool_leg, window=window,
                            requests_n=sharded_requests, reps=1,
                            fixed_shape=True))
                pairs = [round(r / s, 3) for s, r in
                         zip(walls["sharded"], walls["replicated"])]
                vs_replicated = sorted(pairs)[len(pairs) // 2]
                # Per-bucket x mode recompile verdict: every serve
                # program alive in this block — the @{mode}[.g{i}] mesh
                # programs AND the replicated baseline's @r{i} ones —
                # must show zero compiles across every drive above; a
                # violation fails the whole bench line (exit 1), same
                # as the replicated planes.
                delta_mode = _recompile_delta(
                    before_mode, _serve_program_compiles())
                if delta_mode:
                    sharded_recompiles.append({mode: delta_mode})
                full_rps = next(
                    pt["requests_per_sec"] for pt in mesh_scaling
                    if pt["mesh_devices"] == full_mesh)
                sharded_block[mode] = {
                    "model": model_name,
                    "mesh_devices": full_mesh,
                    "requests_per_sec": full_rps,
                    "vs_replicated": vs_replicated,
                    "pairs": pairs,
                    "mesh_scaling": mesh_scaling,
                    "zero_steady_state_recompiles": not delta_mode,
                }
            sharded_block["requests"] = sharded_requests
            if device.platform != "tpu":
                sharded_block["caveat"] = (
                    "CPU fallback (the BENCH_r05 convention): mesh "
                    "collectives run over host threads, not ICI, so the "
                    "sharded-vs-replicated sign is not meaningful here — "
                    "only the schema and the zero-recompile verdicts are")

        # -- MPMD pipeline serving (serve/pipeline.py): the stage-overlap
        # measurement. ONE chain of per-chip stage programs, driven with
        # the in-flight window >= stages (the pipe fills: stage k runs
        # batch N while stage k+1 runs batch N-1) vs window 1 (strict
        # dispatch->complete alternation: every batch pays the full
        # chain serially). A single chain on purpose — a multi-chain
        # pool at window>1 would conflate chain fan-out with stage
        # overlap. ABBA-paired interleaved drives, median paired ratio
        # (PR 4 methodology); fixed-shape 8-row requests pin batch
        # formation. Per-stage synchronous step walls + occupancy say
        # WHERE the pipe's clock is set (the bottleneck stage reads 1.0).
        pipeline_block: dict = {}
        pipeline_recompiles: list = []
        if n_devices < 2:
            pipeline_block["skipped"] = (
                "single-device world: a pipeline chain needs >= 2 chips")
        else:
            from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
                split_vit_params,
            )
            from pytorch_distributed_mnist_tpu.utils.profiling import (
                stage_occupancy,
            )

            pp_model = get_model(
                "vit", **({} if device.platform == "tpu"
                          else {"compute_dtype": jnp.float32}))
            # depth must divide the stage count; the default ViT (depth
            # 2) pins the chain at 2 stages regardless of chip count.
            pp_stages = 2
            pp_params = split_vit_params(
                create_train_state(pp_model, jax.random.key(0)).params)
            pp_pool = EnginePool(
                pp_model.apply, pp_params,
                devices=jax.local_devices()[:pp_stages], buckets=(1, 8),
                serve_mode="pipeline", mesh_size=pp_stages,
                model_name="vit", model=pp_model)
            pp_pool.warmup()
            before_pp = _serve_program_compiles()
            window = pp_stages + 1
            walls = drive_pool_interleaved(
                pp_pool, windows=(window, 1), requests_n=pool_requests)
            pp_pairs = [round(off / on, 3) for on, off
                        in zip(walls[window], walls[1])]
            ratios = sorted(pp_pairs)
            overlap_speedup = ratios[len(ratios) // 2]
            delta_pp = _recompile_delta(before_pp,
                                        _serve_program_compiles())
            if delta_pp:
                pipeline_recompiles.append(delta_pp)
            stage_ms = pp_pool.replicas[0].engine.stage_step_ms(8)
            pipeline_block = {
                "model": "vit",
                "stages": pp_stages,
                "chains": 1,
                "window": window,
                "requests": pool_requests,
                "stage_overlap_speedup": overlap_speedup,
                "pairs": pp_pairs,
                "requests_per_sec": round(
                    pool_requests / min(walls[window]), 1),
                "stage_step_ms": stage_ms,
                "stage_occupancy": stage_occupancy(stage_ms),
                "zero_steady_state_recompiles": not delta_pp,
            }
            if device.platform != "tpu":
                pipeline_block["caveat"] = (
                    "CPU fallback (the BENCH_r05 convention): "
                    "host-thread transfers say nothing about ICI, so "
                    "the inter-stage hop cost is not the chip's — only "
                    "the overlap schema and the zero-recompile verdicts "
                    "are meaningful here")

        # -- precision sweep (serve/programs.py precision plane): for
        # each registered quantized precision, the ABBA-paired
        # throughput ratio vs f32 at the SAME chip (PR 4 pairing:
        # adjacent pairs see the same neighbor load, median paired
        # ratio), the eval-batch argmax-agreement + accuracy delta vs
        # f32, and per bucket x mode x precision zero-recompile
        # verdicts that fail the whole bench line (exit 1). The eval
        # batch is the synthetic stand-in (CI has no MNIST files on
        # disk); with a real checkpoint the same fields measure the
        # real test set via the serving stack.
        import numpy as np

        from pytorch_distributed_mnist_tpu.serve.programs import (
            get_serve_mode,
            registered_mode_models,
            serve_precisions,
            validate_serve_mode,
        )

        precision_requests = int(os.environ.get(
            "BENCH_SERVE_PRECISION_REQUESTS", max(200, pool_requests // 2)))
        precision_block: dict = {"requests": precision_requests,
                                 "eval_set": "synthetic(512)"}
        precision_recompiles: list = []
        quantized = [p for p in serve_precisions() if p != "f32"]
        eval_images, eval_labels = synthetic_dataset(512, seed=1)
        ref_logits = engine.logits(eval_images)
        ref_pred = np.argmax(ref_logits, axis=-1)
        acc_f32 = float((ref_pred == eval_labels).mean())
        precision_block["f32_accuracy"] = round(acc_f32, 4)

        def drive_engine(eng, requests_n: int, req_stacks=None) -> float:
            """One fixed-shape closed-loop drive through a fresh
            batcher (8-row exact-bucket requests, max_batch=8 — the
            pool blocks' reasoning: pin batch formation so the ratio
            measures the forward programs, not packing)."""
            req_stacks = pool_stacks if req_stacks is None else req_stacks
            with MicroBatcher(eng.predict, max_batch=8,
                              max_wait_s=0.002,
                              max_queue=4 * concurrency) as b:
                drive(b, max(32, requests_n // 10), req_stacks)  # warm
                return drive(b, requests_n, req_stacks)

        for prec in quantized:
            prec_engine = InferenceEngine(
                model.apply, state.params, precision=prec, name=prec)
            prec_engine.warmup()
            before_prec = _serve_program_compiles()
            lo = prec_engine.logits(eval_images)
            pred = np.argmax(lo, axis=-1)
            walls_p = {"prec": [], "f32": []}
            for rep in range(4):
                order = (("prec", "f32") if rep % 2 == 0
                         else ("f32", "prec"))
                for leg in order:
                    eng = prec_engine if leg == "prec" else engine
                    walls_p[leg].append(
                        drive_engine(eng, precision_requests))
            pairs_p = [round(f / p, 3) for p, f in
                       zip(walls_p["prec"], walls_p["f32"])]
            ratio = sorted(pairs_p)[len(pairs_p) // 2]
            delta_prec = _recompile_delta(before_prec,
                                          _serve_program_compiles())
            if delta_prec:
                precision_recompiles.append({prec: delta_prec})
            acc_p = float((pred == eval_labels).mean())
            precision_block[prec] = {
                "vs_f32": ratio,
                "pairs": pairs_p,
                "requests_per_sec": round(
                    precision_requests / min(walls_p["prec"]), 1),
                "argmax_agreement_vs_f32": round(
                    float((pred == ref_pred).mean()), 4),
                "accuracy": round(acc_p, 4),
                "accuracy_delta_vs_f32": round(acc_p - acc_f32, 4),
                "max_logit_delta_vs_f32": round(
                    float(np.abs(lo - ref_logits).max()), 5),
                "zero_steady_state_recompiles": not delta_prec,
            }

        # Per bucket x MODE x precision recompile verdicts: every
        # registered mode (the LIVE registry, SPMD and engine-factory
        # alike) x every quantized precision gets a small pool drive on
        # 2 chips; any steady-state compile fails the bench. Skipped
        # combos are labeled, never silently dropped.
        mode_verdicts: dict = {}
        if n_devices >= 2:
            from pytorch_distributed_mnist_tpu.serve.programs import (
                make_serve_template,
            )

            for mode, model_name in registered_mode_models():
                vmodel = get_model(
                    model_name, **({} if device.platform == "tpu"
                                   else {"compute_dtype": jnp.float32}))
                # The registry's template hook owns the mode's param
                # LAYOUT (pipeline restores onto the stage-stacked
                # tree) — never a hardcoded per-mode transform here.
                vparams = make_serve_template(
                    mode, vmodel, jax.random.key(0)).params
                for prec in quantized:
                    key = f"{mode}.{prec}"
                    try:
                        if get_serve_mode(mode).engine_factory is None:
                            validate_serve_mode(mode, model_name, 2,
                                                vparams)
                        vpool = EnginePool(
                            vmodel.apply, vparams,
                            devices=jax.local_devices()[:2],
                            buckets=(1, 8), serve_mode=mode, mesh_size=2,
                            model_name=model_name, model=vmodel,
                            precision=prec)
                        vpool.warmup()
                    except ValueError as exc:
                        # An unservable combo (e.g. an extension mode a
                        # 2-chip mesh can't host) is a labeled skip,
                        # never a traceback that loses the bench line.
                        mode_verdicts[key] = {"model": model_name,
                                              "skipped": str(exc)}
                        continue
                    before_mv = _serve_program_compiles()
                    drive_pool(vpool, window=2, requests_n=64, reps=1,
                               fixed_shape=True)
                    delta_mv = _recompile_delta(
                        before_mv, _serve_program_compiles())
                    if delta_mv:
                        precision_recompiles.append({key: delta_mv})
                    mode_verdicts[key] = {
                        "model": model_name,
                        "zero_steady_state_recompiles": not delta_mv,
                    }
        else:
            mode_verdicts["skipped"] = (
                "single-device world: mode x precision pools need >= 2 "
                "chips")
        precision_block["modes"] = mode_verdicts
        if device.platform != "tpu":
            precision_block["caveat"] = (
                "CPU fallback (the BENCH_r05 convention): host int8/bf16 "
                "arithmetic says little about the TPU MXU or ICI, so "
                "the per-precision throughput sign is not the chip's — "
                "only the schema, the accuracy/agreement deltas, and "
                "the zero-recompile verdicts are meaningful here")

        # -- whole-program fused serving (ISSUE 16): the fused plane
        # stages RAW uint8 bytes and runs ONE XLA program per bucket —
        # in-XLA normalize (+ activation quantize) fused ahead of the
        # forward, staging buffer DONATED — where the split plane
        # normalizes on the host and stages float32. Measured on an
        # MFU-honest config (the ViT: its matmul FLOPs the analytic
        # helper counts honestly; CNN conv FLOPs would be a made-up
        # number): the ABBA-paired fused-vs-split throughput ratio on
        # the SAME engine (only the input dtype differs, so params and
        # placement cannot skew the pair), the host-work collapse
        # (per-request preprocess wall), H2D bytes per request (staged
        # float32 vs raw uint8), and zero-recompile verdicts across
        # BOTH planes that fail the bench line (exit 1).
        from pytorch_distributed_mnist_tpu.data.mnist import (
            normalize_images,
        )

        fused_requests = int(os.environ.get(
            "BENCH_SERVE_FUSED_REQUESTS", max(200, pool_requests // 2)))
        fused_recompiles: list = []
        wp_failures: list = []
        wp_model = get_model(
            "vit", **({} if device.platform == "tpu"
                      else {"compute_dtype": jnp.float32}))
        wp_state = create_train_state(wp_model, jax.random.key(0))
        wp_engine = InferenceEngine(wp_model.apply, wp_state.params,
                                    buckets=(1, 8), fuse=True, name="wp")
        wp_engine.warmup()
        raw_stacks = [np.ascontiguousarray(images[i:i + 8])
                      for i in range(8)]
        wp_float_stacks = [normalize_images(s) for s in raw_stacks]

        # Host-work collapse: what the fused plane removes from the
        # host per request is the float conversion — raw bytes ride
        # straight into uint8 staging (the copy happens on both planes).
        host_reps = 50
        t0 = time.perf_counter()
        for r in range(host_reps):
            wp_engine.preprocess(raw_stacks[r % 8])  # raw passthrough
        fused_host_ms = (time.perf_counter() - t0) / host_reps * 1e3
        t0 = time.perf_counter()
        for r in range(host_reps):
            normalize_images(raw_stacks[r % 8])  # split plane host work
        split_host_ms = (time.perf_counter() - t0) / host_reps * 1e3

        # H2D bytes per 8-row request, from the ACTUAL staging pools
        # (the split pool's dtype is the precision plane's choice, the
        # fused pool always stages raw bytes).
        split_pool_ = wp_engine._staging
        fused_pool_ = wp_engine._fused_staging
        split_bytes = int(np.prod((8,) + split_pool_.input_shape)
                          ) * split_pool_.dtype.itemsize
        fused_bytes = int(np.prod((8,) + fused_pool_.input_shape)
                          ) * fused_pool_.dtype.itemsize

        before_wp = _serve_program_compiles()
        walls_wp = {"fused": [], "split": []}
        for rep in range(4):
            order = (("fused", "split") if rep % 2 == 0
                     else ("split", "fused"))
            for leg in order:
                leg_stacks = (raw_stacks if leg == "fused"
                              else wp_float_stacks)
                walls_wp[leg].append(
                    drive_engine(wp_engine, fused_requests, leg_stacks))
        pairs_wp = [round(s / f, 3) for f, s in
                    zip(walls_wp["fused"], walls_wp["split"])]
        fused_speedup = sorted(pairs_wp)[len(pairs_wp) // 2]
        delta_wp = _recompile_delta(before_wp, _serve_program_compiles())
        if delta_wp:
            fused_recompiles.append(delta_wp)
        speedup_holds = fused_speedup >= 1.0
        if device.platform == "tpu" and not speedup_holds:
            # On the chip the fusion must pay for itself; on the CPU
            # fallback the sign is caveated, not enforced.
            wp_failures.append(
                f"whole-program fusion slower than split on TPU: median "
                f"paired speedup {fused_speedup} < 1.0")

        # MFU at the fused drive's rate: forward-only model FLOPs (the
        # training helper counts fwd + 2x bwd, hence /3), matmuls only,
        # against the chip's peak — None off-TPU, where there is no
        # honest peak to divide by.
        wp_tokens = (28 // wp_model.patch_size) ** 2
        serve_flops_per_image = _vit_model_flops_per_image(
            wp_tokens, wp_model.embed_dim, wp_model.depth,
            wp_model.patch_size) / 3.0
        fused_rps = fused_requests / min(walls_wp["fused"])
        peak = _peak_flops(device.device_kind)
        mfu = (round(fused_rps * 8 * serve_flops_per_image / peak, 5)
               if peak else None)

        whole_program_block: dict = {
            "model": "vit",
            "requests": fused_requests,
            "images_per_request": 8,
            "fused_over_split_speedup": fused_speedup,
            "speedup_holds": speedup_holds,
            "pairs": pairs_wp,
            "requests_per_sec": round(fused_rps, 1),
            "host_preprocess_ms_per_request": {
                "split": round(split_host_ms, 4),
                "fused": round(fused_host_ms, 4),
            },
            "h2d_bytes_per_request": {
                "split": split_bytes,
                "fused": fused_bytes,
                "ratio": round(split_bytes / fused_bytes, 2),
            },
            "model_flops_per_image": serve_flops_per_image,
            "mfu": mfu,
            "donated_staging_retired": wp_engine.fused_staging_retired(),
            "zero_steady_state_recompiles": not delta_wp,
        }
        if device.platform != "tpu":
            whole_program_block["caveat"] = (
                "CPU fallback (the BENCH_r05 convention): host matmuls "
                "say nothing about the MXU and there is no real H2D "
                "hop, so the fused-vs-split sign is not the chip's and "
                "MFU is unreportable — only the schema, the host-work "
                "collapse, the staged-bytes ratio, and the "
                "zero-recompile verdicts are meaningful here")

        # -- overload (ISSUE 15): goodput vs offered load, 1x..10x of
        # measured capacity, through the PRIORITY batcher (shed policy
        # attached, mixed interactive/batch/best_effort traffic).
        # Shed-not-collapse, measured not asserted: the block FAILS the
        # bench (exit 1) when goodput at 10x drops below 70% of the
        # curve's peak (the classic signature of queueing collapse —
        # capacity spent on requests nobody will wait for) or when
        # interactive p99 is not strictly below batch p99 under
        # overload (the whole point of priority ordering + per-class
        # watermarks). Open-loop on purpose: a closed-loop driver slows
        # with the server and cannot overload anything.
        import random as _random

        from pytorch_distributed_mnist_tpu.serve.control import (
            AutoScaler,
            ShedPolicy,
        )

        overload_seconds = float(os.environ.get(
            "BENCH_OVERLOAD_SECONDS", "2.0"))
        overload_points = [int(t) for t in os.environ.get(
            "BENCH_OVERLOAD_POINTS", "1,2,5,10").split(",") if t.strip()]
        overload_mix = (("interactive", 0.6), ("batch", 0.9),
                       ("best_effort", 1.0))  # cumulative
        overload_failures: list = []
        capacity_rps = requests / wall  # the headline closed-loop rate
        overload_block: dict = {
            "capacity_rps": round(capacity_rps, 1),
            "seconds_per_point": overload_seconds,
            "mix": {"interactive": 0.6, "batch": 0.3, "best_effort": 0.1},
            "watermarks": dict(ShedPolicy().watermarks),
            "points": [],
        }

        def _drive_open(mult: int) -> dict:
            """One open-loop point: offer ``mult`` x capacity for
            ``overload_seconds`` straight into a fresh priority
            batcher, then drain. Per-class completions/sheds/latency
            come from the drive's own ServeLog."""
            olog = ServeLog(window_s=30.0)
            rng = _random.Random(1000 + mult)
            rate = capacity_rps * mult
            pendings = []
            offered = 0
            # max_batch BELOW max_queue on purpose: a saturated queue
            # must drain over several engine batches for priority order
            # to mean anything — at max_batch >= max_queue the whole
            # queue rides one forward and every class shares one wall.
            with MicroBatcher(engine.predict, max_batch=16,
                              max_wait_s=0.002, max_queue=64,
                              serve_log=olog,
                              shed_policy=ShedPolicy()) as ob:
                t_start = time.perf_counter()
                i = 0
                while True:
                    t_next = t_start + i / rate
                    now = time.perf_counter()
                    if t_next - t_start >= overload_seconds:
                        break
                    if t_next - now > 1e-3:
                        time.sleep(t_next - now)
                    r = rng.random()
                    klass = next(k for k, cum in overload_mix
                                 if r <= cum)
                    offered += 1
                    try:
                        pendings.append(ob.submit(
                            stacks[i % len(stacks)], klass=klass))
                    except Exception:  # noqa: BLE001 - shed IS the point
                        pass
                    i += 1
                for p in pendings:
                    p.event.wait(30.0)
            snap = olog.snapshot()
            classes = {
                klass: {
                    "completed": rec["requests"],
                    "shed": rec["shed"],
                    "p50_ms": rec["latency_ms"]["p50"],
                    "p99_ms": rec["latency_ms"]["p99"],
                }
                for klass, rec in snap.get("classes", {}).items()
            }
            return {
                "offered_x": mult,
                "offered_rps": round(offered / overload_seconds, 1),
                "completed": snap["requests"],
                "shed": snap["rejected"],
                "goodput_rps": round(snap["requests"] / overload_seconds,
                                     1),
                "classes": classes,
            }

        for mult in overload_points:
            overload_block["points"].append(_drive_open(mult))
        peak_goodput = max(pt["goodput_rps"]
                           for pt in overload_block["points"])
        top = overload_block["points"][-1]
        overload_block["peak_goodput_rps"] = peak_goodput
        overload_block["goodput_at_top_fraction_of_peak"] = round(
            top["goodput_rps"] / max(peak_goodput, 1e-9), 3)
        goodput_holds = top["goodput_rps"] >= 0.7 * peak_goodput
        overload_block["goodput_holds_at_overload"] = goodput_holds
        if not goodput_holds:
            overload_failures.append(
                f"goodput collapsed under overload: "
                f"{top['goodput_rps']} rps at "
                f"{top['offered_x']}x vs peak {peak_goodput} rps "
                f"(< 70%)")
        inter = top["classes"].get("interactive", {})
        batch_c = top["classes"].get("batch", {})
        tail_ordered = (inter.get("completed", 0) > 0
                        and batch_c.get("completed", 0) > 0
                        and inter["p99_ms"] < batch_c["p99_ms"])
        overload_block["interactive_p99_below_batch_p99"] = tail_ordered
        if not tail_ordered:
            overload_failures.append(
                f"priority inversion under overload: interactive p99 "
                f"{inter.get('p99_ms')}ms vs batch p99 "
                f"{batch_c.get('p99_ms')}ms at {top['offered_x']}x "
                f"(interactive must stay strictly below, with both "
                f"classes completing)")

        # Autoscaler actuation verdict: a real controller drives the
        # pool's resize path up then down (synthetic breach/calm
        # samples — this is the ACTUATION under test, not the sensor),
        # and the steady state AFTER the resizes must not recompile:
        # the acceptance criterion "zero steady-state recompiles across
        # autoscaler resizes".
        autoscale_block: dict = {}
        if n_devices >= 2:
            as_pool = EnginePool(model.apply, state.params,
                                 devices=jax.local_devices()[:1])
            as_pool.warmup()
            feed = {"p95_ms": 0.0, "queue_depth": 0}
            scaler = AutoScaler(
                as_pool, lambda: dict(feed), slo_p95_ms=50.0,
                queue_high=48, max_devices=2, cooldown_s=0.0,
                down_after=2, interval_s=60.0)
            feed["p95_ms"] = 500.0  # breach: scale 1 -> 2
            up = scaler.tick()
            feed["p95_ms"] = 1.0  # sustained calm: scale 2 -> 1
            scaler.tick()
            down = scaler.tick()
            resized_ok = (up is not None and "error" not in up
                          and down is not None and "error" not in down
                          and as_pool.n_devices == 1)
            before_as = _serve_program_compiles()
            drive_pool(as_pool, window=2, requests_n=64, reps=1,
                       fixed_shape=True)
            delta_as = _recompile_delta(before_as,
                                        _serve_program_compiles())
            autoscale_block = {
                "resizes": [up, down],
                "actuated": resized_ok,
                "zero_steady_state_recompiles_across_resizes":
                    not delta_as,
            }
            if not resized_ok:
                overload_failures.append(
                    f"autoscaler actuation failed: up={up} down={down} "
                    f"pool at {as_pool.n_devices} device(s)")
            if delta_as:
                overload_failures.append(
                    f"steady-state serving recompiled across "
                    f"autoscaler resizes: {delta_as}")
        else:
            autoscale_block["skipped"] = (
                "single-device world: an autoscaler resize needs >= 2 "
                "chips")
        overload_block["autoscale"] = autoscale_block
        if device.platform != "tpu":
            overload_block["caveat"] = (
                "CPU fallback (the BENCH_r05 convention): absolute "
                "capacity is the host's, not the chip's — the CURVE "
                "shape (goodput held at 10x, interactive < batch p99) "
                "and the recompile verdicts are the meaningful part "
                "here")
        if os.environ.get("BENCH_OVERLOAD_INJECT_FAIL"):
            # Test hook: pin the fails-loudly path without needing a
            # real collapse (mirrors BENCH_ZERO_INJECT_RECOMPILE).
            overload_failures.append(
                "BENCH_OVERLOAD_INJECT_FAIL set: injected overload "
                "verdict failure")
            overload_block["goodput_holds_at_overload"] = False

        # -- fleet (ISSUE 17): the federation tier's own cost and
        # behavior — two real loopback backends behind a real router,
        # all in-process, driven over real HTTP. Three verdicts:
        # (1) router overhead: ABBA-paired direct-vs-routed closed-loop
        #     drives (the BENCH_r04 pairing discipline — alternation
        #     cancels thermal/scheduler drift), reported as the paired
        #     median p50/p99 ratio;
        # (2) goodput at ~10x measured fleet capacity offered open-loop
        #     THROUGH the router (the ISSUE 15 overload methodology one
        #     tier up): the router must shed/refuse, never collapse —
        #     goodput at the top point holds >= 70% of the curve's
        #     peak, the same rule the single-process block enforces
        #     (96% measured there at seed time);
        # (3) zero steady-state recompiles across every routed drive
        #     (the backends share this process's compile log, so a
        #     per-backend recompile shows up in the delta).
        import shutil as _shutil
        import tempfile as _tempfile
        import urllib.request as _urlreq

        from pytorch_distributed_mnist_tpu.serve.router import (
            build_parser as _router_parser,
        )
        from pytorch_distributed_mnist_tpu.serve.router import create_router
        from pytorch_distributed_mnist_tpu.serve.server import (
            build_parser as _serve_parser,
        )
        from pytorch_distributed_mnist_tpu.serve.server import create_server
        from pytorch_distributed_mnist_tpu.train.checkpoint import (
            save_checkpoint,
        )
        from tools.loadgen import _make_images, run_closed, run_open, \
            zipf_cum
        from tools.loadgen import report as _loadgen_report

        def _drive_closed(url, n, conc, *, seed):
            t_d = time.perf_counter()
            col = run_closed(url, n, conc, bodies, timeout=30.0,
                             seed=seed)
            return _loadgen_report(col, time.perf_counter() - t_d,
                                   "closed")

        fleet_failures: list = []
        fleet_block: dict = {"backends": 2}
        fleet_seconds = float(os.environ.get("BENCH_FLEET_SECONDS", "1.0"))
        fleet_pairs = int(os.environ.get("BENCH_FLEET_PAIRS", "3"))
        fleet_reqs = int(os.environ.get("BENCH_FLEET_REQUESTS", "40"))
        fleet_dirs: list = []
        fleet_servers: list = []
        fleet_router = None

        def _boot_httpd(httpd):
            th = threading.Thread(target=httpd.serve_forever, daemon=True)
            th.start()
            host, port = httpd.server_address[:2]
            return {"httpd": httpd, "thread": th,
                    "url": f"http://{host}:{port}",
                    "name": f"{host}:{port}"}

        def _stop_httpd(srv):
            srv["httpd"].shutdown()
            srv["httpd"].ctx.close()
            srv["httpd"].server_close()
            srv["thread"].join(10.0)

        def _router_json(path):
            with _urlreq.urlopen(fleet_router["url"] + path,
                                 timeout=10) as r:
                return json.loads(r.read())

        try:
            # Linear backends on purpose: the block measures ROUTING
            # (the wire + the routing tier), not model capacity, and
            # linear keeps the two extra engines' compiles cheap.
            fleet_model = get_model("linear", compute_dtype=jnp.float32)
            fleet_state = create_train_state(fleet_model,
                                             jax.random.key(7))
            for i in range(2):
                d = _tempfile.mkdtemp(prefix=f"bench-fleet-b{i}-")
                fleet_dirs.append(d)
                save_checkpoint(fleet_state, epoch=0, best_acc=0.0,
                                is_best=False, directory=d,
                                process_index=0)
                fleet_servers.append(_boot_httpd(create_server(
                    _serve_parser().parse_args([
                        "--checkpoint-dir", d, "--model", "linear",
                        "--dtype", "f32", "--host", "127.0.0.1",
                        "--port", "0", "--buckets", "1,8",
                        "--max-wait-ms", "2", "--max-queue", "256",
                        "--poll-interval", "0.5"]))))
            fleet_router = _boot_httpd(create_router(
                _router_parser().parse_args([
                    "--backends",
                    ",".join(s["name"] for s in fleet_servers),
                    "--host", "127.0.0.1", "--port", "0",
                    "--health-interval", "0.2",
                    "--connect-timeout", "2.0"])))
            deadline = time.perf_counter() + 30.0
            while time.perf_counter() < deadline:
                try:
                    if _router_json("/healthz").get("routable") == 2:
                        break
                except OSError:
                    pass
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    "router never saw both backends routable")

            bodies = _make_images(8, 8, seed=5)
            # loadgen appends /predict itself: base URLs here.
            direct_url = fleet_servers[0]["url"]
            routed_url = fleet_router["url"]
            # Warm every program (both backends, both buckets) and the
            # routed path before anything is measured.
            for url in (direct_url, routed_url, routed_url):
                warm = _drive_closed(url, 16, 4, seed=1)
                if warm["ok"] != 16:
                    raise RuntimeError(
                        f"fleet warmup failed against {url}: {warm}")
            before_fleet = _serve_program_compiles()

            # (1) Router overhead, ABBA-paired: per pair one direct and
            # one routed drive, order alternating; the overhead ratio
            # is the median of per-pair routed/direct p50 (and p99).
            pair_rows = []
            for pair in range(fleet_pairs):
                order = [("direct", direct_url), ("routed", routed_url)]
                if pair % 2:
                    order.reverse()
                row = {}
                for label, url in order:
                    rep = _drive_closed(url, fleet_reqs, 4,
                                        seed=100 + pair)
                    if rep["ok"] != fleet_reqs:
                        fleet_failures.append(
                            f"overhead drive ({label}, pair {pair}) "
                            f"lost requests: {rep}")
                    row[label] = rep["latency_ms"]
                pair_rows.append(row)

            def _median(vals):
                vals = sorted(vals)
                mid = len(vals) // 2
                return (vals[mid] if len(vals) % 2
                        else 0.5 * (vals[mid - 1] + vals[mid]))

            overhead = {
                "pairs": fleet_pairs,
                "direct_p50_ms": _median(
                    [r["direct"]["p50"] for r in pair_rows]),
                "routed_p50_ms": _median(
                    [r["routed"]["p50"] for r in pair_rows]),
                "direct_p99_ms": _median(
                    [r["direct"]["p99"] for r in pair_rows]),
                "routed_p99_ms": _median(
                    [r["routed"]["p99"] for r in pair_rows]),
                "p50_overhead_ratio": round(_median(
                    [r["routed"]["p50"] / max(r["direct"]["p50"], 1e-9)
                     for r in pair_rows]), 3),
                "p99_overhead_ratio": round(_median(
                    [r["routed"]["p99"] / max(r["direct"]["p99"], 1e-9)
                     for r in pair_rows]), 3),
            }
            fleet_block["router_overhead"] = overhead

            # (2) Goodput through the router: closed-loop capacity
            # first, then open-loop points at 1x and ~10x (offered rate
            # clamped so the thread-per-request client stays honest —
            # the EFFECTIVE multiple is recorded, not the target).
            cap = _drive_closed(routed_url, 3 * fleet_reqs, 8, seed=7)
            fleet_capacity = max(cap["throughput_rps"], 1e-9)
            goodput_points = []
            for mult in (1, 10):
                rate = min(fleet_capacity * mult, 1500.0)
                col = run_open(routed_url, rate, fleet_seconds, bodies,
                               timeout=10.0, seed=40 + mult)
                rep = _loadgen_report(col, fleet_seconds, "open")
                goodput_points.append({
                    "offered_x": round(rate / max(fleet_capacity, 1e-9),
                                       2),
                    "offered_rps": round(rate, 1),
                    "completed": rep["ok"],
                    "shed": rep["rejected"],
                    "not_launched": rep["not_launched"],
                    "goodput_rps": round(rep["ok"] / fleet_seconds, 1),
                })
                if rep["transport_errors"] or rep["conn_refused"]:
                    fleet_failures.append(
                        f"requests dropped on the floor at "
                        f"{mult}x through the router: {rep}")
            peak_fleet = max(pt["goodput_rps"] for pt in goodput_points)
            top_fleet = goodput_points[-1]
            goodput_frac = round(
                top_fleet["goodput_rps"] / max(peak_fleet, 1e-9), 3)
            fleet_block["goodput"] = {
                "capacity_rps": round(fleet_capacity, 1),
                "points": goodput_points,
                "peak_goodput_rps": peak_fleet,
                "goodput_at_top_fraction_of_peak": goodput_frac,
                "single_process_fraction_of_peak": overload_block.get(
                    "goodput_at_top_fraction_of_peak"),
            }
            goodput_holds_fleet = (
                top_fleet["goodput_rps"] >= 0.7 * peak_fleet)
            fleet_block["goodput"]["holds_at_overload"] = \
                goodput_holds_fleet
            if not goodput_holds_fleet:
                fleet_failures.append(
                    f"fleet goodput collapsed through the router: "
                    f"{top_fleet['goodput_rps']} rps at "
                    f"{top_fleet['offered_x']}x vs peak {peak_fleet} "
                    f"rps (< 70%)")

            # (3) No routed drive recompiled a backend program.
            delta_fleet = _recompile_delta(before_fleet,
                                           _serve_program_compiles())
            fleet_block["zero_steady_state_recompiles_per_backend"] = \
                not delta_fleet
            if delta_fleet:
                fleet_failures.append(
                    f"steady-state serving recompiled behind the "
                    f"router: {delta_fleet}")

            stats = _router_json("/stats")
            fleet_block["router_stats"] = {
                "routable": sum(1 for row in stats.get("backends", [])
                                if row.get("routable")),
                "failovers": stats.get("fleet", {}).get("failovers"),
                "retries": stats.get("fleet", {}).get("retries"),
            }
        except Exception as exc:  # noqa: BLE001 - the block fails loudly, the bench still emits JSON
            fleet_failures.append(f"fleet block crashed: {exc!r}")
        finally:
            if fleet_router is not None:
                try:
                    _stop_httpd(fleet_router)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            for srv in fleet_servers:
                try:
                    _stop_httpd(srv)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            for d in fleet_dirs:
                _shutil.rmtree(d, ignore_errors=True)
        if device.platform != "tpu":
            fleet_block["caveat"] = (
                "CPU fallback (the BENCH_r05 convention): absolute "
                "overhead and capacity are the host's loopback stack, "
                "not a real fabric — the RATIOS (routed vs direct, "
                "goodput held at the top point) and the recompile "
                "verdict are the meaningful part here")
        if os.environ.get("BENCH_FLEET_INJECT_FAIL"):
            # Test hook: pin the fails-loudly path (mirrors
            # BENCH_OVERLOAD_INJECT_FAIL).
            fleet_failures.append(
                "BENCH_FLEET_INJECT_FAIL set: injected fleet verdict "
                "failure")
        fleet_block["ok"] = not fleet_failures

        # -- economics (ISSUE 19): the request-path economics layer's
        # own cost and behavior — one loopback backend with the
        # response cache + cost-priced admission on, driven with
        # Zipf-duplicate traffic (the key-reuse workload the cache
        # exists for). Three verdicts:
        # (1) a client-observed cache hit is ~free next to compute:
        #     hit p99 <= 0.1x miss p99 on TPU (on CPU loopback the
        #     HTTP stack dominates BOTH sides, so the enforced bar
        #     relaxes to hit p99 < miss p99 and the 0.1x number is
        #     reported with the BENCH_r05 caveat);
        # (2) goodput at ~10x offered load holds >= 96% of the curve's
        #     peak (the PR 14 single-process bar, which the cache
        #     should now CLEAR rather than approach: duplicates are
        #     answered from memory, not shed);
        # (3) zero steady-state recompiles across every economics
        #     drive — a cache hit never touches a chip, so it can
        #     never compile anything.
        # The collapse ratio (followers joined / requests served) and
        # the server's measured per-bucket cost table ride along as
        # report-only provenance.
        economics_failures: list = []
        economics_block: dict = {}
        econ_seconds = float(os.environ.get("BENCH_ECONOMICS_SECONDS",
                                            "1.0"))
        econ_reqs = int(os.environ.get("BENCH_ECONOMICS_REQUESTS", "200"))
        econ_dir = None
        econ_server = None
        try:
            econ_model = get_model("linear", compute_dtype=jnp.float32)
            econ_state = create_train_state(econ_model,
                                            jax.random.key(9))
            econ_dir = _tempfile.mkdtemp(prefix="bench-economics-")
            save_checkpoint(econ_state, epoch=0, best_acc=0.0,
                            is_best=False, directory=econ_dir,
                            process_index=0)
            econ_server = _boot_httpd(create_server(
                _serve_parser().parse_args([
                    "--checkpoint-dir", econ_dir, "--model", "linear",
                    "--dtype", "f32", "--host", "127.0.0.1",
                    "--port", "0", "--buckets", "1,8",
                    "--max-wait-ms", "2", "--max-queue", "256",
                    "--poll-interval", "5", "--price-admission"])))
            econ_url = econ_server["url"]

            def _econ_json(path):
                with _urlreq.urlopen(econ_url + path, timeout=10) as r:
                    return json.loads(r.read())

            # Warm the PROGRAMS with a disjoint body set (different
            # seed -> different bytes -> different cache keys), so the
            # measured drive sees warm compiles but a COLD cache: its
            # misses are pure compute, not compile.
            warm_bodies = _make_images(4, 8, seed=11)
            col = run_closed(econ_url, 16, 4, warm_bodies, timeout=30.0,
                             seed=1)
            warm_rep = _loadgen_report(col, 1.0, "closed")
            if warm_rep["ok"] != 16:
                raise RuntimeError(
                    f"economics warmup failed: {warm_rep}")
            before_econ = _serve_program_compiles()

            # (1) The Zipf-duplicate drive: 16 templates, exponent 1.1
            # — the head template dominates, every template's first
            # touch is a measured miss (compute), every repeat a hit.
            econ_bodies = _make_images(16, 8, seed=9)
            econ_zipf = zipf_cum(16, 1.1)
            t_e = time.perf_counter()
            col = run_closed(econ_url, econ_reqs, 8, econ_bodies,
                             timeout=30.0, seed=17, zipf=econ_zipf)
            zipf_rep = _loadgen_report(col, time.perf_counter() - t_e,
                                       "closed")
            cc = zipf_rep.get("cache_client", {})
            hit_p99 = cc.get("hit_latency_ms", {}).get("p99", 0.0)
            miss_p99 = cc.get("miss_latency_ms", {}).get("p99", 0.0)
            if zipf_rep["ok"] != econ_reqs:
                economics_failures.append(
                    f"zipf drive lost requests: {zipf_rep}")
            if not cc.get("hits") or not cc.get("misses"):
                economics_failures.append(
                    f"zipf drive never split hit/miss "
                    f"(cache inactive?): {cc}")
            hit_ratio = round(hit_p99 / max(miss_p99, 1e-9), 3)
            on_tpu = device.platform == "tpu"
            hit_bar = 0.1 if on_tpu else 1.0
            hit_cheap = hit_p99 <= hit_bar * miss_p99
            economics_block["zipf_drive"] = {
                "requests": econ_reqs,
                "zipf_exponent": 1.1,
                "templates": 16,
                "hit_rate": cc.get("hit_rate"),
                "hit_p99_ms": hit_p99,
                "miss_p99_ms": miss_p99,
                "hit_over_miss_p99": hit_ratio,
                "enforced_bar": hit_bar,
                "hit_is_cheap": hit_cheap,
            }
            if not hit_cheap:
                economics_failures.append(
                    f"cache hits are not cheap: hit p99 {hit_p99}ms vs "
                    f"miss p99 {miss_p99}ms (ratio {hit_ratio} > "
                    f"{hit_bar})")

            # (2) Goodput at 10x offered, cache warm: duplicates come
            # back from memory, so the top point should HOLD the PR 14
            # 96%-of-peak single-process bar, not merely approach it.
            t_cap = time.perf_counter()
            cap = run_closed(econ_url, 3 * econ_reqs // 2, 8,
                             econ_bodies, timeout=30.0, seed=23,
                             zipf=econ_zipf)
            cap_wall = max(time.perf_counter() - t_cap, 1e-9)
            econ_capacity = max(cap.status.get(200, 0) / cap_wall, 1e-9)
            econ_points = []
            for mult in (1, 10):
                rate = min(econ_capacity * mult, 1500.0)
                col = run_open(econ_url, rate, econ_seconds,
                               econ_bodies, timeout=10.0,
                               seed=60 + mult, zipf=econ_zipf)
                rep = _loadgen_report(col, econ_seconds, "open")
                econ_points.append({
                    "offered_x": round(rate / econ_capacity, 2),
                    "offered_rps": round(rate, 1),
                    "completed": rep["ok"],
                    "shed": rep["rejected"],
                    "not_launched": rep["not_launched"],
                    "hit_rate": rep.get("cache_client", {})
                    .get("hit_rate"),
                    "goodput_rps": round(rep["ok"] / econ_seconds, 1),
                })
                if rep["transport_errors"] or rep["conn_refused"]:
                    economics_failures.append(
                        f"requests dropped on the floor at {mult}x "
                        f"on the cached path: {rep}")
            peak_econ = max(pt["goodput_rps"] for pt in econ_points)
            top_econ = econ_points[-1]
            econ_frac = round(
                top_econ["goodput_rps"] / max(peak_econ, 1e-9), 3)
            economics_block["goodput"] = {
                "capacity_rps": round(econ_capacity, 1),
                "points": econ_points,
                "peak_goodput_rps": peak_econ,
                "goodput_at_top_fraction_of_peak": econ_frac,
                "single_process_fraction_of_peak": overload_block.get(
                    "goodput_at_top_fraction_of_peak"),
                "holds_at_overload": econ_frac >= 0.96,
            }
            if econ_frac < 0.96:
                economics_failures.append(
                    f"cached-path goodput fell below the 96%-of-peak "
                    f"bar at {top_econ['offered_x']}x: "
                    f"{top_econ['goodput_rps']} rps vs peak "
                    f"{peak_econ} rps ({econ_frac})")

            # (3) Zero recompiles + the report-only provenance: the
            # collapse ratio and the measured per-bucket cost table.
            delta_econ = _recompile_delta(before_econ,
                                          _serve_program_compiles())
            economics_block["zero_steady_state_recompiles"] = \
                not delta_econ
            if delta_econ:
                economics_failures.append(
                    f"steady-state serving recompiled on the cached "
                    f"path: {delta_econ}")
            stats = _econ_json("/stats")
            served = max(stats.get("requests", 0), 1)
            collapsed = stats.get("cache", {}).get("collapsed", 0)
            economics_block["collapse_ratio"] = round(
                collapsed / served, 4)
            economics_block["server_cache"] = stats.get("cache")
            economics_block["cost_model"] = stats.get("cost_model")
        except Exception as exc:  # noqa: BLE001 - the block fails loudly, the bench still emits JSON
            economics_failures.append(f"economics block crashed: {exc!r}")
        finally:
            if econ_server is not None:
                try:
                    _stop_httpd(econ_server)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            if econ_dir is not None:
                _shutil.rmtree(econ_dir, ignore_errors=True)
        if device.platform != "tpu":
            economics_block["caveat"] = (
                "CPU fallback (the BENCH_r05 convention): the HTTP "
                "loopback stack dominates both the hit and the miss "
                "path, so the 0.1x hit-vs-compute bar is reported but "
                "only hit < miss is enforced — the hit rate, goodput "
                "fraction and recompile verdict are the meaningful "
                "part here")
        if os.environ.get("BENCH_ECONOMICS_INJECT_FAIL"):
            # Test hook: pin the fails-loudly path (mirrors
            # BENCH_FLEET_INJECT_FAIL).
            economics_failures.append(
                "BENCH_ECONOMICS_INJECT_FAIL set: injected economics "
                "verdict failure")
        economics_block["ok"] = not economics_failures

        value = requests / wall
        out.update({
            "value": round(value, 1),
            "vs_baseline": round(value / (requests / baseline_wall), 3),
            "requests": requests,
            "concurrency": concurrency,
            "p50_ms": snap["latency_ms"]["p50"],
            "p95_ms": snap["latency_ms"]["p95"],
            "p99_ms": snap["latency_ms"]["p99"],
            "batch_histogram": snap["batch_histogram"],
            "buckets": list(engine.buckets),
            "rejected": snap["rejected"],
            "warmup_compile_s": round(warmup_s, 3),
            "zero_steady_state_recompiles": zero_recompiles,
            "replica_scaling": replica_scaling,
            "sharded": sharded_block,
            "pipeline_serving": pipeline_block,
            "precision_sweep": precision_block,
            "whole_program": whole_program_block,
            "overload": overload_block,
            "fleet": fleet_block,
            "economics": economics_block,
            "pipeline_speedup": round(pipeline_speedup, 3),
            "pipeline_pairs": pipeline_pairs,
            "pool_requests": pool_requests,
            "pool_images_per_request": 8,
            "cpu_serve_devices_isolated": cpu_isolated,
            "zero_steady_state_recompiles_per_replica":
                not recompiled_replicas,
            "backend": device.platform,
            "device_kind": device.device_kind,
            "n_chips": jax.device_count(),
            "compile_stats": compile_log.stats(),
        })
        # The measured drives really served every request (phantom
        # completions would inflate the headline), and nothing failed.
        served_all = snap["requests"] == 2 * requests  # best-of-2 drives
        ok = (zero_recompiles and not drive_errors and served_all
              and not recompiled_replicas and not sharded_recompiles
              and not pipeline_recompiles and not precision_recompiles
              and not fused_recompiles and not wp_failures
              and not overload_failures and not fleet_failures
              and not economics_failures)
        if overload_failures:
            out["error"] = ("overload block failed: "
                            + "; ".join(overload_failures))
        elif fleet_failures:
            out["error"] = ("fleet block failed: "
                            + "; ".join(fleet_failures))
        elif economics_failures:
            out["error"] = ("economics block failed: "
                            + "; ".join(economics_failures))
        elif fused_recompiles:
            out["error"] = ("steady-state WHOLE-PROGRAM serving "
                            "recompiled (fused plane): "
                            f"{fused_recompiles}")
        elif wp_failures:
            out["error"] = ("whole-program block failed: "
                            + "; ".join(wp_failures))
        elif not zero_recompiles:
            out["error"] = ("steady-state serving recompiled: "
                            f"{totals_after_warmup} -> {totals_after_load}")
        elif recompiled_replicas:
            out["error"] = ("steady-state pool serving recompiled: "
                            f"{recompiled_replicas}")
        elif sharded_recompiles:
            out["error"] = ("steady-state SHARDED serving recompiled "
                            f"(per bucket x mode): {sharded_recompiles}")
        elif pipeline_recompiles:
            out["error"] = ("steady-state MPMD pipeline serving "
                            "recompiled (per bucket x stage): "
                            f"{pipeline_recompiles}")
        elif precision_recompiles:
            out["error"] = ("steady-state QUANTIZED serving recompiled "
                            "(per bucket x mode x precision): "
                            f"{precision_recompiles}")
        elif drive_errors:
            out["error"] = (f"{len(drive_errors)} requests failed during "
                            f"the drive: {drive_errors[:3]}")
        elif not served_all:
            out["error"] = (f"served {snap['requests']} of {2 * requests} "
                            f"requests across the measured drives")
    except Exception as exc:  # noqa: BLE001 - bench must always emit JSON
        out.update({"value": 0.0, "vs_baseline": 0.0, "error": repr(exc)})
        ok = False
    out["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out))
    if not ok:
        sys.exit(1)


# 24 steps x 2048 images: epochs long enough (~250ms on the CI box) that
# the paired-ratio median is stable against scheduler noise; 7 pairs.
INPUT_STEPS = 24
INPUT_BATCH = 2048
INPUT_REPS = 7


def _isolate_cpu_input_compute() -> bool:
    """Make the CPU backend's step behave like a chip for the overlap
    measurement.

    On the CPU backend a single XLA execution grabs the whole host Eigen
    threadpool, so on this box the "device" step and the feeder thread
    fight for the same cores and the pipelined-vs-synchronous comparison
    measures core contention, not overlap (the exact failure mode
    ``_isolate_cpu_serve_devices`` fixes for the replica pool). A real
    accelerator computes off-host — the host CPU is idle during the
    step, which is what gives the feeder its window; Eigen isolation
    pins the step to one core so the other models that idle host CPU.
    Skipped entirely unless the run is CPU-bound.
    """
    if "xla_cpu_multi_thread_eigen" in os.environ.get("XLA_FLAGS", ""):
        # Flag already decided (e.g. a CI wrapper pre-set it): no need
        # to pay a child `import jax` just to learn the backend.
        return _ensure_cpu_eigen_isolation()
    if not _run_is_cpu_bound():
        # No env declaration doesn't mean an accelerator is present: an
        # accelerator-less box auto-selects the CPU backend and needs
        # the same isolation, or the comparison measures feeder/step
        # core contention.
        return False
    return _ensure_cpu_eigen_isolation()


def main_input() -> None:
    """``--mode input``: the input data plane's BENCH line (ISSUE 6).

    Measures the feed path in isolation and end to end, emitting ONE
    JSON line whose ``input_pipeline`` block carries:

    - ``feed_images_per_sec``: feed-only throughput — the staging
      pipeline (host gather + sharded ``device_put``) driven with no
      training step consuming it. This is the ceiling the input plane
      can sustain; a chip whose step rate exceeds it starves.
    - ``pipelined_feed_speedup``: real per-batch training epochs with
      the feeder at window 2 vs window 1 (today's synchronous strict
      alternation), as the MEDIAN of per-rep paired ratios from
      ABBA-interleaved drives — the serve bench's pairing methodology,
      because on a shares-throttled CI box adjacent drives see the same
      neighbor load and the ratio survives drift that best-of-each-side
      would turn into noise. Window 1 is trajectory-bitwise-identical
      to window 2 (tests/test_staging.py), so the delta is pure
      latency.
    - ``native_preprocess_speedup`` / ``native_pad_speedup``: the serve
      dispatch path's host-side array work (normalize + the
      pad-into-staging copy) in multithreaded C++ vs the bitwise-
      identical NumPy fallbacks, same interleaved-pairs protocol.
      ``native_available: false`` labels a fallback-only environment
      honestly (the ``--mode serve`` CPU-labeling convention), with
      null speedups rather than fabricated ones.
    - zero-steady-state-recompile checks for BOTH sides: the measured
      train epochs and a serve dispatch drive after warmup.

    Never raises; failures become an ``error`` line (the
    always-emit-JSON contract every bench mode follows).
    """
    out = {
        "metric": "mnist_input_pipeline_feed_images_per_sec",
        "unit": "images/sec",
        "baseline": "synchronous (window 1) per-batch staging, same "
                    "loader and jitted step: vs_baseline is the "
                    "pipelined-feed epoch speedup",
    }
    ok = False
    try:
        import statistics

        # Must run before the first jax device query: XLA_FLAGS are read
        # once, at backend init.
        cpu_isolated = _isolate_cpu_input_compute()

        import jax

        configure_jax(jax, force_cpu=bool(os.environ.get("BENCH_FORCE_CPU")))

        import jax.numpy as jnp
        import numpy as np

        from pytorch_distributed_mnist_tpu.data import native as native_mod
        from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
        from pytorch_distributed_mnist_tpu.data.mnist import synthetic_dataset
        from pytorch_distributed_mnist_tpu.data.staging import BatchFeeder
        from pytorch_distributed_mnist_tpu.models import get_model
        from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
        from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
        from pytorch_distributed_mnist_tpu.train.state import create_train_state
        from pytorch_distributed_mnist_tpu.train.trainer import Trainer
        from pytorch_distributed_mnist_tpu.utils.profiling import (
            StagingLog,
            compile_log,
        )

        device = jax.devices()[0]
        n_chips = jax.device_count()
        mesh = make_mesh(("data",)) if n_chips > 1 else None
        steps = int(os.environ.get("BENCH_INPUT_STEPS", INPUT_STEPS))
        batch = int(os.environ.get("BENCH_INPUT_BATCH", INPUT_BATCH))
        reps = int(os.environ.get("BENCH_INPUT_REPS", INPUT_REPS))

        # Linear model on purpose: its step cost is the same order as
        # the staging cost at this batch size, which is the regime where
        # overlap is visible. (A conv step hundreds of ms long hides ANY
        # feed path; a chip fast enough to starve is the linear case.)
        n = steps * batch
        rng = np.random.default_rng(0)
        data_images = rng.standard_normal((n, 28, 28, 1)).astype(np.float32)
        data_labels = (np.arange(n) % 10).astype(np.int32)
        model = get_model("linear", compute_dtype=jnp.float32)

        def make_trainer(window: int, staging: StagingLog = None):
            state = create_train_state(model, jax.random.key(0))
            loader = MNISTDataLoader(data_images, data_labels,
                                     batch_size=batch, train=True, seed=7)
            trainer = Trainer(state, loader, loader, mesh=mesh,
                              mode="stepwise", feed_window=window,
                              staging_log=staging)
            return trainer, loader

        # -- feed-only throughput: the staging pipeline with no consumer
        # compute, inline (window 1) so the log's feed rate is the pure
        # staging wall.
        feed_log = StagingLog()
        feed_loader = MNISTDataLoader(data_images, data_labels,
                                      batch_size=batch, train=True, seed=7)
        feed_only = BatchFeeder(feed_loader, mesh, window=1,
                                staging_log=feed_log)
        t_feed = time.perf_counter()
        for staged in feed_only.epoch():
            jax.block_until_ready(staged["image"])
        feed_wall_s = time.perf_counter() - t_feed
        feed = feed_log.summary()
        # Async-dispatch honesty: the log's stage walls time the
        # device_put DISPATCH (JAX returns before the transfer lands);
        # only the block_until_ready above observes completion. The
        # headline feed rate comes from the full blocked wall so a real
        # chip's DMA time can't be silently excluded — on the CPU
        # backend the two are within noise, on a TPU they are not.
        feed["feed_images_per_sec"] = round(
            feed["images"] / max(feed_wall_s, 1e-9), 1)

        # -- pipelined vs synchronous epochs, ABBA-interleaved pairs.
        pipe_log = StagingLog()
        pipe, pipe_loader = make_trainer(2, pipe_log)
        sync, sync_loader = make_trainer(1)
        epoch_counter = {"pipe": 0, "sync": 0}

        def drive_epoch(trainer, loader, key) -> float:
            loader.set_sample_epoch(epoch_counter[key])
            epoch_counter[key] += 1
            t0 = time.perf_counter()
            loss, _acc = trainer.train()
            float(loss.average)  # host read: execution definitely done
            return time.perf_counter() - t0

        drive_epoch(pipe, pipe_loader, "pipe")  # compile + warm both
        drive_epoch(sync, sync_loader, "sync")
        totals_before = dict(compile_log.stats()["totals"])
        pipe_log.reset()
        pairs = []
        pipe_walls, sync_walls = [], []
        for rep in range(reps):
            order = ("pipe", "sync") if rep % 2 == 0 else ("sync", "pipe")
            walls = {}
            for key in order:
                trainer, loader = (pipe, pipe_loader) if key == "pipe" \
                    else (sync, sync_loader)
                walls[key] = drive_epoch(trainer, loader, key)
            pipe_walls.append(walls["pipe"])
            sync_walls.append(walls["sync"])
            pairs.append(round(walls["sync"] / walls["pipe"], 3))
        feed_speedup = statistics.median(pairs)
        train_totals_after = dict(compile_log.stats()["totals"])
        zero_recompiles_train = (
            train_totals_after["backend_compiles"]
            == totals_before["backend_compiles"])
        overlap = pipe_log.summary()

        # -- serve dispatch path: native vs NumPy preprocess + pad, and
        # the post-warmup zero-recompile check on real predicts.
        raw_images, _ = synthetic_dataset(4096, seed=1)
        serve_state = create_train_state(model, jax.random.key(0))
        engine = InferenceEngine(model.apply, serve_state.params)
        engine.warmup()
        serve_before = dict(compile_log.stats()["totals"])
        stack = engine.preprocess(raw_images[:128])
        for _ in range(8):
            engine.predict(stack)
        zero_recompiles_serve = (
            compile_log.stats()["totals"]["backend_compiles"]
            == serve_before["backend_compiles"])

        bucket = max(engine.buckets)
        pad_src = np.ascontiguousarray(
            engine.preprocess(raw_images[:bucket - 16]), np.float32)
        pad_dst = np.empty((bucket,) + pad_src.shape[1:], np.float32)

        def time_preprocess() -> float:
            t0 = time.perf_counter()
            engine.preprocess(raw_images)
            return time.perf_counter() - t0

        def time_pad(use_native: bool, iters: int = 200) -> float:
            # One pad is ~tens of microseconds — integrate over many so
            # the ratio measures the copy, not perf_counter granularity.
            t0 = time.perf_counter()
            if use_native:
                for _ in range(iters):
                    if not native_mod.pad_into(pad_dst, pad_src,
                                               workers=engine.workers):
                        # Not an assert: python -O would strip the CALL
                        # and time 200 iterations of nothing.
                        raise RuntimeError("native pad_into refused a "
                                           "layout it must accept")
            else:
                for _ in range(iters):
                    pad_dst[:len(pad_src)] = pad_src
                    pad_dst[len(pad_src):] = 0.0
            return time.perf_counter() - t0

        native_available = native_mod.available()
        pre_speedup = pad_speedup = None
        pre_pairs, pad_pairs = [], []
        if native_available:
            def numpy_only(fn):
                """Run ``fn`` with the native library switched off (the
                mandatory fallback path) in this same process."""
                prior = os.environ.get("TPUMNIST_NATIVE")
                os.environ["TPUMNIST_NATIVE"] = "0"
                native_mod._lib = None
                try:
                    return fn()
                finally:
                    if prior is None:
                        del os.environ["TPUMNIST_NATIVE"]
                    else:
                        os.environ["TPUMNIST_NATIVE"] = prior
                    native_mod._lib = None
                    # Re-warm the load NOW, outside any timed window:
                    # the next native-side measurement must not pay the
                    # filesystem probe + dlopen + argtype wiring inside
                    # its timer (it would bias every pair's native leg).
                    native_mod.available()

            time_preprocess()               # warm both paths once
            numpy_only(time_preprocess)
            time_pad(True)
            time_pad(False)  # pure slice-assign; no native switch needed
            for rep in range(reps):
                if rep % 2 == 0:
                    nat = time_preprocess()
                    np_t = numpy_only(time_preprocess)
                else:
                    np_t = numpy_only(time_preprocess)
                    nat = time_preprocess()
                pre_pairs.append(round(np_t / nat, 3))
                if rep % 2 == 0:
                    nat_p = time_pad(True)
                    np_p = time_pad(False)
                else:
                    np_p = time_pad(False)
                    nat_p = time_pad(True)
                pad_pairs.append(round(np_p / nat_p, 3))
            pre_speedup = statistics.median(pre_pairs)
            pad_speedup = statistics.median(pad_pairs)

        out.update({
            "value": feed["feed_images_per_sec"],
            "vs_baseline": round(feed_speedup, 3),
            "input_pipeline": {
                "feed_images_per_sec": feed["feed_images_per_sec"],
                "feed_host_ms": feed["host_ms"],
                "feed_h2d_ms": feed["h2d_ms"],
                "feed_steps": feed["stages"],
                "global_batch": batch,
                "pipelined_epoch_ms": round(
                    statistics.median(pipe_walls) * 1e3, 1),
                "synchronous_epoch_ms": round(
                    statistics.median(sync_walls) * 1e3, 1),
                "pipelined_feed_speedup": round(feed_speedup, 3),
                "pipeline_pairs": pairs,
                "feed_window": 2,
                "overlap_fraction": overlap["overlap_fraction"],
                "native_available": native_available,
                "native_preprocess_speedup": pre_speedup,
                "native_preprocess_pairs": pre_pairs,
                "native_pad_speedup": pad_speedup,
                "native_pad_pairs": pad_pairs,
                "preprocess_images": len(raw_images),
                "cpu_compute_isolated": cpu_isolated,
                "zero_steady_state_recompiles_train":
                    zero_recompiles_train,
                "zero_steady_state_recompiles_serve":
                    zero_recompiles_serve,
            },
            "backend": device.platform,
            "device_kind": device.device_kind,
            "n_chips": n_chips,
            "compile_stats": compile_log.stats(),
        })
        ok = zero_recompiles_train and zero_recompiles_serve
        if not zero_recompiles_train:
            out["error"] = ("measured train epochs recompiled: "
                            f"{totals_before} -> {train_totals_after}")
        elif not zero_recompiles_serve:
            out["error"] = "steady-state serve dispatch recompiled"
    except Exception as exc:  # noqa: BLE001 - bench must always emit JSON
        out.update({"value": 0.0, "vs_baseline": 0.0, "error": repr(exc)})
        ok = False
    out["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out))
    if not ok:
        sys.exit(1)


# 16 steps x 1024 images (256 on the CPU fallback): per-step jitted
# drives long enough that the ABBA paired ratios are stable against
# scheduler noise on the CI box; 5 pairs.
ZERO_STEPS = 16
ZERO_REPS = 5


def _force_cpu_zero_world() -> dict:
    """CPU backends get a forced multi-device world for ``--mode zero``.

    ZeRO over one device has nothing to scatter: a single-chip CPU run
    would measure degenerate collectives and report a meaningless
    overlap. When the run is CPU-bound and no device count is forced
    yet, probe-append ``--xla_force_host_platform_device_count=4`` (the
    serve bench's CI stand-in for a 4-chip host) and the Eigen isolation
    that makes one "device" stop grabbing every host core
    (``_ensure_cpu_eigen_isolation``). Must run before the first jax
    device query — XLA_FLAGS are read once, at backend init. No-op on
    real accelerators.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return {"cpu_devices_forced": False,
                "cpu_compute_isolated": _ensure_cpu_eigen_isolation()}
    if not _run_is_cpu_bound():
        return {"cpu_devices_forced": False,
                "cpu_compute_isolated": False}
    candidate = (flags + " --xla_force_host_platform_device_count=4").strip()
    supported = _probe_xla_flags(candidate)
    if supported:
        os.environ["XLA_FLAGS"] = candidate
    return {"cpu_devices_forced": supported,
            "cpu_compute_isolated": _ensure_cpu_eigen_isolation()}


def main_zero() -> None:
    """``--mode zero``: the overlapped-ZeRO weight update's BENCH line
    (ISSUE 7).

    Drives the explicit overlapped data plane
    (``parallel/zero_overlap.py``) against the propagation-scheduled
    path (``parallel/zero.py`` + GSPMD) on the same model, state layout,
    and batches, and emits ONE JSON line whose ``zero_overlap`` block
    carries the measured — not asserted — overlap story:

    - ``step_ms_overlap`` / ``step_ms_propagation``: median per-step
      walls from ABBA-interleaved paired drives (the PR 4/6 pairing
      methodology: adjacent drives see the same neighbor load, so the
      ratio survives CPU-share drift); ``vs_baseline`` is the median
      paired speedup, overlapped over propagation.
    - ``comm_ms_per_step``: a compute-free twin running EXACTLY the
      step's bucket-fenced reduce-scatter + allgather sequence
      (``make_comm_only_program``).
    - ``compute_ms_per_step``: a communication-free twin — the same
      overlapped step on a 1-device mesh with this chip's share of the
      batch (collectives degenerate to copies).
    - ``overlap_fraction``: ``comm_overlap_fraction(step, compute,
      comm)`` (utils/profiling.py) — how much of the measured
      communication the measured step actually hid.
    - train MFU via ``_peak_flops`` (the headline bench's convention,
      same >100%-of-peak sync guard), FLOPs/step from the compiled
      overlapped program's own cost analysis.
    - zero-steady-state-recompile verdicts for BOTH paths through
      ``CompileLog``: the measured drives run under per-path measures,
      so any backend compile during the steady-state window attributes
      to the path that triggered it, and a nonzero count fails the
      bench loudly (exit 1).
    - ``two_tier``: the hierarchical (DCN x ICI) schedule measured per
      tier — real slice topology when the runtime reports one, else the
      emulated 2-slice map (labelled ``dcn_emulated``). Per-tier
      compute-free comm twins (the ici-only RS+AG chain, the dcn-only
      shard all-reduces), per-tier overlap fractions
      (``per_tier_overlap_fractions``), an ABBA-paired two-tier-vs-flat
      speedup, and per-drive recompile verdicts (the two-tier step AND
      each tier twin) that fail the bench exactly like the flat ones.
      ``BENCH_ZERO_INJECT_RECOMPILE=two_tier`` poisons the hier drive
      specifically.

    A CPU run is honestly labelled (``cpu_fallback`` + caveat: XLA:CPU
    has no async communication stream, so overlap cannot manifest and
    the speedup sign is not accelerator evidence — the BENCH_r05
    CPU-fallback precedent). ``BENCH_ZERO_INJECT_RECOMPILE`` is a
    test-only hook that compiles a fresh program inside each measured
    overlap drive so the fails-loudly path is itself testable. Never
    raises; failures become an ``error`` line.
    """
    out = {
        "metric": "mnist_zero_overlap_train_images_per_sec_per_chip",
        "unit": "images/sec/chip",
        "baseline": "same model/state layout/batches with "
                    "propagation-scheduled ZeRO (XLA sharding "
                    "propagation): vs_baseline is the median ABBA-paired "
                    "overlapped-vs-propagation step-drive speedup",
    }
    ok = False
    try:
        import statistics

        world = _force_cpu_zero_world()

        import jax

        configure_jax(jax, force_cpu=bool(os.environ.get("BENCH_FORCE_CPU")))

        import jax.numpy as jnp
        import numpy as np

        from pytorch_distributed_mnist_tpu.data.mnist import (
            normalize_images,
            synthetic_dataset,
        )
        from pytorch_distributed_mnist_tpu.models import get_model
        from pytorch_distributed_mnist_tpu.parallel.mesh import (
            device_slice_index,
            infer_dcn_slices,
            make_hier_mesh,
            make_mesh,
        )
        from pytorch_distributed_mnist_tpu.parallel.zero import (
            shard_state_zero,
        )
        from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
            make_comm_only_program,
            make_overlap_train_step,
            make_param_gather,
        )
        from pytorch_distributed_mnist_tpu.train.state import (
            create_train_state,
        )
        from pytorch_distributed_mnist_tpu.train.steps import make_train_step
        from pytorch_distributed_mnist_tpu.utils.profiling import (
            comm_overlap_fraction,
            compile_log,
            per_tier_overlap_fractions,
        )

        device = jax.devices()[0]
        n_chips = jax.device_count()
        on_tpu = device.platform == "tpu"
        refused = _refuse_fakes_on_tpu(out, device.platform)
        if refused:
            raise RuntimeError(refused["error"])
        level = int(os.environ.get("BENCH_ZERO_LEVEL", "3"))
        bucket_mb = float(os.environ.get("BENCH_ZERO_BUCKET_MB", "4.0"))
        steps = int(os.environ.get("BENCH_ZERO_STEPS", ZERO_STEPS))
        reps = int(os.environ.get("BENCH_ZERO_REPS", ZERO_REPS))
        batch = int(os.environ.get("BENCH_ZERO_BATCH",
                                   "1024" if on_tpu else "256"))
        batch = max(batch - batch % n_chips, n_chips)  # exact row split
        # Test-only recompile injections: "1" (any truthy value except
        # "two_tier") poisons the flat overlap drive, "two_tier" the
        # hierarchical drive — so both fails-loudly paths are testable
        # with per-path attribution.
        inject_env = os.environ.get("BENCH_ZERO_INJECT_RECOMPILE", "")
        inject = bool(inject_env) and inject_env != "two_tier"
        inject_two_tier = inject_env == "two_tier"

        mesh = make_mesh(("data",))
        # Same backend policy as the training bench: bf16 MXU path on
        # TPU, f32 on the CPU fallback.
        model = get_model(
            "cnn", **({} if on_tpu else {"compute_dtype": jnp.float32}))
        images, labels = synthetic_dataset(batch, seed=0)
        x = np.asarray(normalize_images(images))
        y = labels.astype(np.int32)
        one = {"image": jnp.asarray(x), "label": jnp.asarray(y)}

        # -- the two paths, identical state layout, AOT-compiled.
        prop_state, sharding = shard_state_zero(
            create_train_state(model, jax.random.key(0)), mesh, level=level)
        prop_jit = make_train_step(mesh, state_sharding=sharding)
        with compile_log.measure("zero_step_propagation"):
            prop_step = prop_jit.lower(prop_state, one).compile()

        ov_state, _ = shard_state_zero(
            create_train_state(model, jax.random.key(0)), mesh, level=level)
        ov_jit = make_overlap_train_step(
            ov_state, mesh, level=level, bucket_mb=bucket_mb)
        gather = make_param_gather(mesh)  # one program, both uses below
        gathered = gather(ov_state.params) if level == 3 else None
        with compile_log.measure("zero_step_overlap"):
            ov_step = (ov_jit.lower(ov_state, gathered, one).compile()
                       if level == 3
                       else ov_jit.lower(ov_state, one).compile())

        flops_per_step = None
        try:
            cost = ov_step.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            total = float(cost.get("flops", 0.0))
            if total > 0:
                flops_per_step = total
        except Exception:  # noqa: BLE001 - analytic fallback below
            pass
        if not flops_per_step:
            flops_per_step = float(_CNN_STEP_FLOPS_PER_IMAGE * batch)

        # -- comm-only twin: exactly the step's collective sequence on
        # param-shaped values, no model compute between.
        comm_jit = make_comm_only_program(ov_state, mesh,
                                          bucket_mb=bucket_mb)
        params_full = gather(ov_state.params)
        with compile_log.measure("zero_comm_only"):
            comm_prog = comm_jit.lower(params_full).compile()

        # -- compute-only twin: the same overlapped step on a 1-device
        # mesh with this chip's share of the batch (collectives
        # degenerate to local copies: the step minus communication).
        mesh1 = make_mesh(("data",), devices=[jax.devices()[0]])
        c_state, _ = shard_state_zero(
            create_train_state(model, jax.random.key(0)), mesh1,
            level=level)
        c_jit = make_overlap_train_step(
            c_state, mesh1, level=level, bucket_mb=bucket_mb)
        c_gathered = (make_param_gather(mesh1)(c_state.params)
                      if level == 3 else None)
        per_chip = max(n_chips, 1)
        one_c = {"image": jnp.asarray(x[: batch // per_chip]),
                 "label": jnp.asarray(y[: batch // per_chip])}
        with compile_log.measure("zero_compute_only"):
            c_step = (c_jit.lower(c_state, c_gathered, one_c).compile()
                      if level == 3
                      else c_jit.lower(c_state, one_c).compile())

        # -- drives: per-step executables chained with ONE host sync at
        # the end (the metric-count read, the _warmup_and_time protocol).
        state_of = {"overlap": (ov_state, gathered),
                    "propagation": (prop_state, None)}
        step_of = {"overlap": ov_step, "propagation": prop_step}
        injected = {"n": 0}

        def drive(key, n_steps) -> float:
            st, gp = state_of[key]
            fn = step_of[key]
            m = None
            t0 = time.perf_counter()
            for _ in range(n_steps):
                if gp is not None:
                    st, gp, m = fn(st, gp, one)
                else:
                    st, m = fn(st, one)
            if float(m.count) != batch:  # full host roundtrip sync — a
                # plain statement, not assert: python -O would strip the
                # only sync and time async DISPATCH of the whole drive.
                raise RuntimeError(
                    f"zero drive sync: count {float(m.count)} != {batch}")
            wall = time.perf_counter() - t0
            state_of[key] = (st, gp)
            return wall

        drive("overlap", 2)       # warm end to end (donation, dispatch)
        drive("propagation", 2)
        for _ in range(3):        # warm the twins
            float(comm_prog(params_full))
        if c_gathered is not None:
            c_st, c_gp, cm = c_step(c_state, c_gathered, one_c)
        else:
            c_st, cm = c_step(c_state, one_c)
            c_gp = None
        float(cm.count)

        # -- measured ABBA pairs, each drive under its path's CompileLog
        # measure so a steady-state compile attributes to its path.
        walls = {"overlap": [], "propagation": []}
        for rep in range(reps):
            order = (("overlap", "propagation") if rep % 2 == 0
                     else ("propagation", "overlap"))
            for key in order:
                with compile_log.measure(f"zero_drive_{key}"):
                    if inject and key == "overlap":
                        # Test-only: a fresh program per rep inside the
                        # measured window — drives the fails-loudly path.
                        injected["n"] += 1
                        jax.jit(lambda v, _k=injected["n"]: v * (_k + 1))(
                            jnp.ones((2,), jnp.float32)
                        ).block_until_ready()
                    walls[key].append(drive(key, steps))
        pairs = [round(p / o, 3)
                 for o, p in zip(walls["overlap"], walls["propagation"])]
        speedup = statistics.median(pairs)

        def _per_step_ms(wall_list) -> float:
            return statistics.median(wall_list) / steps * 1e3

        step_ms_overlap = _per_step_ms(walls["overlap"])
        step_ms_prop = _per_step_ms(walls["propagation"])

        comm_walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                r = comm_prog(params_full)
            float(r)
            comm_walls.append(time.perf_counter() - t0)
        comm_ms = min(comm_walls) / steps * 1e3

        compute_walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                if c_gp is not None:
                    c_st, c_gp, cm = c_step(c_st, c_gp, one_c)
                else:
                    c_st, cm = c_step(c_st, one_c)
            float(cm.count)
            compute_walls.append(time.perf_counter() - t0)
        compute_ms = min(compute_walls) / steps * 1e3

        overlap_frac = comm_overlap_fraction(
            step_ms_overlap, compute_ms, comm_ms)

        # -- two-tier (DCN x ICI) twin: the hierarchical-mesh schedule
        # with a PER-TIER comm breakdown — real slice topology when the
        # runtime reports one, else the emulated slice map (2 slices by
        # default on an even chip count), honestly labelled. Each
        # tier's comm cost comes from its own compute-free twin (the
        # ici-only RS+AG chain / the dcn-only shard all-reduces), and
        # each measured drive runs under its own CompileLog measure so
        # a steady-state recompile attributes to — and fails — exactly
        # the program that triggered it.
        two_tier = None
        two_tier_verdicts = {}
        dcn_slices = infer_dcn_slices()
        if dcn_slices < 2 and n_chips >= 2 and n_chips % 2 == 0:
            dcn_slices = 2  # emulated default: the smallest hierarchy
        dcn_emulated = any(
            device_slice_index(d) is None for d in jax.devices())
        if dcn_slices < 2 or n_chips % dcn_slices:
            two_tier = {"skipped": (
                f"{n_chips} chip(s) do not split into {dcn_slices} "
                f"equal DCN slices — nothing hierarchical to measure")}
        else:
            bucket_mb_dcn = float(os.environ.get(
                "BENCH_ZERO_BUCKET_MB_DCN", str(bucket_mb)))
            hier_mesh = make_hier_mesh(dcn_slices)
            h_state, _ = shard_state_zero(
                create_train_state(model, jax.random.key(0)), hier_mesh,
                level=level)
            h_jit = make_overlap_train_step(
                h_state, hier_mesh, level=level, bucket_mb=bucket_mb,
                bucket_mb_dcn=bucket_mb_dcn)
            h_gather = make_param_gather(hier_mesh)
            h_gathered = h_gather(h_state.params) if level == 3 else None
            with compile_log.measure("zero_step_two_tier"):
                h_step = (h_jit.lower(h_state, h_gathered, one).compile()
                          if level == 3
                          else h_jit.lower(h_state, one).compile())
            state_of["two_tier"] = (h_state, h_gathered)
            step_of["two_tier"] = h_step
            # Per-tier compute-free twins on the SAME hier mesh/state.
            # h_full is a SEPARATE gather on purpose (not h_gathered):
            # the two-tier step donates its gathered carry, so the tier
            # twins need a buffer the drives can never invalidate.
            h_full = h_gather(h_state.params)
            tier_progs = {}
            for tier in ("ici", "dcn"):
                t_jit = make_comm_only_program(
                    h_state, hier_mesh, bucket_mb=bucket_mb,
                    bucket_mb_dcn=bucket_mb_dcn, tier=tier)
                with compile_log.measure(f"zero_comm_tier_{tier}"):
                    tier_progs[tier] = t_jit.lower(h_full).compile()
            drive("two_tier", 2)  # warm end to end
            for tier in ("ici", "dcn"):
                for _ in range(3):
                    float(tier_progs[tier](h_full))
            # Measured ABBA pairs: two-tier vs the flat overlapped path
            # (same chips, same batches — the "what does the hierarchy
            # cost/buy on this box" ratio).
            walls_tt, walls_fo = [], []
            for rep in range(reps):
                order = (("two_tier", "overlap") if rep % 2 == 0
                         else ("overlap", "two_tier"))
                for key in order:
                    with compile_log.measure(f"zero_drive_{key}"):
                        if inject_two_tier and key == "two_tier":
                            injected["n"] += 1
                            jax.jit(lambda v, _k=injected["n"]:
                                    v * (_k + 2))(
                                jnp.ones((3,), jnp.float32)
                            ).block_until_ready()
                        w = drive(key, steps)
                    (walls_tt if key == "two_tier"
                     else walls_fo).append(w)
            pairs_tt = [round(f / t, 3)
                        for t, f in zip(walls_tt, walls_fo)]
            step_ms_tt = statistics.median(walls_tt) / steps * 1e3
            tier_ms = {}
            for tier in ("ici", "dcn"):
                tws = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    with compile_log.measure(f"zero_drive_tier_{tier}"):
                        for _ in range(steps):
                            r = tier_progs[tier](h_full)
                        float(r)
                    tws.append(time.perf_counter() - t0)
                tier_ms[tier] = min(tws) / steps * 1e3
            # The 1-device compute twin above is tier-free (collectives
            # degenerate either way), so it serves both decompositions.
            tier_fracs = per_tier_overlap_fractions(
                step_ms_tt, compute_ms, tier_ms)

        steps_per_sec = steps / min(walls["overlap"])
        peak = _peak_flops(device.device_kind)
        mfu = (flops_per_step * steps_per_sec / n_chips / peak) if peak \
            else None
        if mfu is not None and mfu > 1.0:
            raise RuntimeError(
                f"impossible zero-overlap train MFU {mfu:.3g} (>100% of "
                f"peak): device sync did not wait for execution")

        programs = compile_log.stats()["programs"]

        def _drive_compiles(key) -> int:
            return programs.get(f"zero_drive_{key}",
                                {}).get("backend_compiles", 0)

        verdicts = {key: _drive_compiles(key) == 0
                    for key in ("overlap", "propagation")}
        if two_tier is None or "skipped" not in two_tier:
            two_tier_verdicts = {
                key: _drive_compiles(key) == 0
                for key in ("two_tier", "tier_ici", "tier_dcn")}
            two_tier = {
                "dcn_slices": dcn_slices,
                "chips_per_slice": n_chips // dcn_slices,
                "dcn_emulated": dcn_emulated,
                "bucket_mb": bucket_mb,
                "bucket_mb_dcn": bucket_mb_dcn,
                "step_ms_two_tier": round(step_ms_tt, 3),
                "vs_flat_overlap_speedup": round(
                    statistics.median(pairs_tt), 3),
                "pairs": pairs_tt,
                "tiers": {
                    tier: {
                        "comm_ms_per_step": round(tier_ms[tier], 3),
                        "overlap_fraction": tier_fracs[tier],
                        "zero_steady_state_recompiles":
                            two_tier_verdicts[f"tier_{tier}"],
                    }
                    for tier in ("ici", "dcn")
                },
                "zero_steady_state_recompiles_two_tier":
                    two_tier_verdicts["two_tier"],
            }
            if dcn_emulated:
                two_tier["caveat"] = (
                    "emulated DCN slices: host-thread collectives say "
                    "nothing about real cross-slice DCN latency, so "
                    "the per-tier split shows the schedule's traffic "
                    "shape, not DCN cost, and the vs-flat sign is not "
                    "accelerator evidence (BENCH_r05 CPU-fallback "
                    "precedent)")

        value = batch * steps / min(walls["overlap"]) / n_chips
        block = {
            "level": level,
            "bucket_mb": bucket_mb,
            "steps": steps,
            "global_batch": batch,
            "step_ms_overlap": round(step_ms_overlap, 3),
            "step_ms_propagation": round(step_ms_prop, 3),
            "comm_ms_per_step": round(comm_ms, 3),
            "compute_ms_per_step": round(compute_ms, 3),
            "overlap_fraction": overlap_frac,
            "overlap_vs_propagation_speedup": round(speedup, 3),
            "pairs": pairs,
            "overlap_beats_propagation": speedup > 1.0,
            "mfu": round(mfu, 4) if mfu is not None else None,
            "flops_per_step": flops_per_step,
            "peak_flops_per_chip": peak,
            "zero_steady_state_recompiles_overlap": verdicts["overlap"],
            "zero_steady_state_recompiles_propagation":
                verdicts["propagation"],
            "cpu_devices_forced": world["cpu_devices_forced"],
            "cpu_compute_isolated": world["cpu_compute_isolated"],
            "two_tier": two_tier,
        }
        if not on_tpu:
            block["cpu_fallback"] = True
            block["caveat"] = (
                "CPU backend: XLA:CPU runs collectives and compute on "
                "the same host cores with no asynchronous communication "
                "stream, so comm/compute overlap cannot manifest here "
                "and the overlapped-vs-propagation sign is not "
                "accelerator evidence (BENCH_r05 CPU-fallback precedent)")
        elif not block["overlap_beats_propagation"]:
            out["note"] = (
                "overlapped path did not beat propagation on this TPU "
                "drive; XLA's propagation schedule may already overlap "
                "— see the zero_overlap block's per-step decomposition")
        out.update({
            "value": round(value, 1),
            "vs_baseline": round(speedup, 3),
            "zero_overlap": block,
            "backend": device.platform,
            "device_kind": device.device_kind,
            "n_chips": n_chips,
            "compile_stats": compile_log.stats(),
        })
        ok = (verdicts["overlap"] and verdicts["propagation"]
              and all(two_tier_verdicts.values()))
        if not ok:
            tier_counts = "".join(
                f", {key}={_drive_compiles(key)}"
                for key in sorted(two_tier_verdicts))
            out["error"] = (
                "steady-state recompiles during the measured zero "
                "drives: overlap="
                f"{_drive_compiles('overlap')}, propagation="
                f"{_drive_compiles('propagation')}{tier_counts} "
                "backend compile(s) "
                "(the AOT executables must be shape-stable)")
    except Exception as exc:  # noqa: BLE001 - bench must always emit JSON
        out.update({"value": 0.0, "vs_baseline": 0.0, "error": repr(exc)})
        ok = False
    out["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out))
    if not ok:
        sys.exit(1)


def main_publish() -> None:
    """``--mode publish``: the delta-distribution BENCH line (ISSUE 18).

    Measures what a checkpoint publish COSTS and how fast a fleet
    becomes consistent, delta vs whole-file:

    - **publisher side**: whole-file npz bytes + write time vs the
      cold (first) delta publish vs an ADJACENT publish (one leaf
      changed — the training-loop steady state); the adjacent publish's
      new chunk bytes over the whole-file bytes is the headline ratio.
    - **fleet side**: three in-process ``DeltaFetcher`` "backends" over
      one published manifest — backend 0 fetches from the source
      directory and seeds a real loopback ``/chunks/<hash>`` HTTP
      server (the gossip plane); backends 1-2 list it as a peer, so
      their bytes must arrive peer-first (``bytes_source == 0``).
      Cold-start fetch (a new backend joins: every params chunk moves,
      but never the optimizer moments) and adjacent fetch (only the
      dirty leaf's chunks move) each get bytes + time-to-fleet-
      consistency, and the adjacent fleet bytes must land under 30% of
      shipping the whole file to every backend — the ISSUE 18
      acceptance bar, asserted here so it fails loudly.

    Filesystem + loopback-HTTP only (no device program in the measured
    path), so absolute times are the host's; the byte counts and
    ratios are platform-independent. ``BENCH_PUBLISH_INJECT_FAIL``
    pins the fails-loudly path for tests."""
    import shutil as _shutil
    import tempfile
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.distrib.cas import ChunkStore
    from pytorch_distributed_mnist_tpu.distrib.fetch import DeltaFetcher
    from pytorch_distributed_mnist_tpu.distrib.publish import publish_state
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from pytorch_distributed_mnist_tpu.train.state import create_train_state

    chunk_mb = float(os.environ.get("BENCH_PUBLISH_CHUNK_MB", "0.25"))
    n_backends = int(os.environ.get("BENCH_PUBLISH_BACKENDS", "3"))
    device = jax.devices()[0]
    out = {
        "metric": "mnist_delta_publish_adjacent_fleet_bytes_fraction",
        "unit": "fraction of whole-file x backends bytes",
        "baseline": "whole-file npz publish copied to every backend",
        "backend": device.platform,
        "device_kind": device.device_kind,
    }
    failures = []
    dirs = [tempfile.mkdtemp(prefix="bench-publish-") for _ in range(3)]
    whole_dir, source_dir, fleet_root = dirs
    backend_dirs = [os.path.join(fleet_root, f"b{i}")
                    for i in range(n_backends)]
    httpd = None
    try:
        model = get_model("linear", compute_dtype=jnp.float32)
        state = create_train_state(model, jax.random.key(7))
        leaves, treedef = jax.tree_util.tree_flatten(state.params)
        small = min(range(len(leaves)), key=lambda j: leaves[j].size)

        def _adjacent(epoch):
            shifted = list(leaves)
            shifted[small] = leaves[small] + epoch * 1e-3
            return state.replace(
                params=jax.tree_util.tree_unflatten(treedef, shifted))

        def _dir_bytes(d):
            chunks = os.path.join(d, "chunks")
            if not os.path.isdir(chunks):
                return 0
            return sum(os.path.getsize(os.path.join(chunks, f))
                       for f in os.listdir(chunks))

        # -- publisher side ---------------------------------------------
        t0 = time.perf_counter()
        save_checkpoint(state, epoch=1, best_acc=0.5, is_best=False,
                        directory=whole_dir, process_index=0)
        whole_s = time.perf_counter() - t0
        whole_path = os.path.join(whole_dir, "checkpoint_1.npz")
        whole_bytes = os.path.getsize(whole_path)

        t0 = time.perf_counter()
        manifest1 = publish_state(state, epoch=1, best_acc=0.5,
                                  directory=source_dir, chunk_mb=chunk_mb,
                                  process_index=0)
        cold_s = time.perf_counter() - t0
        cold_bytes = _dir_bytes(source_dir)

        t0 = time.perf_counter()
        manifest2 = publish_state(_adjacent(2), epoch=2, best_acc=0.5,
                                  directory=source_dir, chunk_mb=chunk_mb,
                                  process_index=0)
        adj_s = time.perf_counter() - t0
        adj_bytes = _dir_bytes(source_dir) - cold_bytes
        publish_ratio = adj_bytes / whole_bytes

        # -- fleet side: loopback gossip over real HTTP -----------------
        seed_store = ChunkStore(backend_dirs[0])

        class _ChunkHandler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
                digest = self.path.rsplit("/", 1)[-1]
                if not seed_store.has(digest):
                    self.send_response(404)
                    self.end_headers()
                    return
                data = seed_store.get(digest)
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # noqa: D102 - quiet bench server
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _ChunkHandler)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        peer_url = f"http://127.0.0.1:{httpd.server_address[1]}"

        # Backend 0 pulls from the source dir and thereby SEEDS the
        # gossip endpoint; the rest list it as their (only) peer with
        # the source dir as fallback — peer-first is then observable as
        # bytes_source == 0 on every non-seed backend.
        fetchers = [DeltaFetcher(backend_dirs[0], source_dir=source_dir)]
        fetchers += [DeltaFetcher(d, peers=(peer_url,),
                                  source_dir=source_dir)
                     for d in backend_dirs[1:]]

        def _fleet_load(path, want_epoch):
            t0 = time.perf_counter()
            for fetcher in fetchers:
                _, epoch = fetcher.load(path, state)
                if epoch != want_epoch:
                    failures.append(
                        f"fetcher returned epoch {epoch}, want "
                        f"{want_epoch} from {path}")
            return time.perf_counter() - t0

        cold_fleet_s = _fleet_load(manifest1, 1)
        cold_fetch_bytes = sum(f.last["bytes_fetched"] for f in fetchers)
        adj_fleet_s = _fleet_load(manifest2, 2)
        adj_fetch_bytes = sum(f.last["bytes_fetched"] for f in fetchers)
        peer_bytes = sum(f.total["bytes_peer"] for f in fetchers[1:])
        source_bytes_nonseed = sum(f.total["bytes_source"]
                                   for f in fetchers[1:])
        dirty = [f.last["dirty_leaves"] for f in fetchers]
        clean = [f.last["clean_leaves"] for f in fetchers]

        fleet_ratio = adj_fetch_bytes / (whole_bytes * n_backends)
        if fleet_ratio >= 0.30:
            failures.append(
                f"adjacent delta fetch moved {adj_fetch_bytes}B to "
                f"{n_backends} backends = {fleet_ratio:.3f} of "
                f"whole-file x backends; the ISSUE 18 bar is < 0.30")
        if peer_bytes <= 0:
            failures.append(
                "gossip never moved a byte: non-seed backends should "
                "fetch from the peer endpoint")
        if source_bytes_nonseed:
            failures.append(
                f"non-seed backends pulled {source_bytes_nonseed}B from "
                f"the source dir despite a complete peer (peers must be "
                f"tried first)")
        if any(d != dirty[0] for d in dirty) or \
                any(c != clean[0] for c in clean):
            failures.append(
                f"backends disagree on the diff: dirty={dirty}, "
                f"clean={clean}")
        if os.environ.get("BENCH_PUBLISH_INJECT_FAIL"):
            # Test hook: pin the fails-loudly path (mirrors
            # BENCH_FLEET_INJECT_FAIL).
            failures.append("BENCH_PUBLISH_INJECT_FAIL set: injected "
                            "publish verdict failure")

        out.update({
            "value": round(fleet_ratio, 5),
            "vs_baseline": round(
                (whole_bytes * n_backends) / max(adj_fetch_bytes, 1), 1),
            "publish": {
                "chunk_mb": chunk_mb,
                "whole_file_bytes": whole_bytes,
                "whole_file_publish_s": round(whole_s, 4),
                "cold_chunk_bytes": cold_bytes,
                "cold_publish_s": round(cold_s, 4),
                "adjacent_new_chunk_bytes": adj_bytes,
                "adjacent_publish_s": round(adj_s, 4),
                "adjacent_publish_bytes_fraction": round(
                    publish_ratio, 5),
            },
            "fleet": {
                "backends": n_backends,
                "cold_fetch_bytes": cold_fetch_bytes,
                "cold_time_to_consistency_s": round(cold_fleet_s, 4),
                "adjacent_fetch_bytes": adj_fetch_bytes,
                "adjacent_time_to_consistency_s": round(adj_fleet_s, 4),
                "adjacent_fleet_bytes_fraction": round(fleet_ratio, 5),
                "gossip_peer_bytes": peer_bytes,
                "non_seed_source_bytes": source_bytes_nonseed,
                "dirty_leaves": dirty[0],
                "clean_leaves": clean[0],
                "delta_under_30pct_of_whole_file": fleet_ratio < 0.30,
            },
            "caveat": (
                "filesystem + loopback HTTP on this host: absolute "
                "publish/fetch times are not a fabric's (the BENCH_r05 "
                "convention) — the byte counts, the ratios, and the "
                "peer-vs-source split are the meaningful part"),
        })
        if failures:
            out["error"] = "; ".join(failures)
    except Exception as exc:  # noqa: BLE001 - bench must always emit JSON
        out.update({"value": 0.0, "vs_baseline": 0.0, "error": repr(exc)})
        failures.append(repr(exc))
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        for d in dirs:
            _shutil.rmtree(d, ignore_errors=True)
    out["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out))
    if failures:
        sys.exit(1)


def bench_torch_reference() -> float:
    """Reference-style per-batch torch loop (same CNN, Adam), CPU."""
    import torch
    import torch.nn as tnn
    import torch.nn.functional as F

    torch.set_num_threads(max(1, torch.get_num_threads()))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(1, 32, 3, padding=1)
            self.conv2 = tnn.Conv2d(32, 64, 3, padding=1)
            self.fc1 = tnn.Linear(64 * 14 * 14, 128)
            self.fc2 = tnn.Linear(128, 10)

        def forward(self, x):
            x = F.relu(self.conv1(x))
            x = F.relu(self.conv2(x))
            x = F.max_pool2d(x, 2)
            x = x.flatten(1)
            return self.fc2(F.relu(self.fc1(x)))

    torch.manual_seed(0)  # same weights/data every run: the baseline-side
    model = Net()         # contribution to vs_baseline stays stable
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    bs = 256
    data = torch.randn(bs, 1, 28, 28)
    target = torch.randint(0, 10, (bs,))
    # warmup
    for _ in range(2):
        opt.zero_grad()
        F.cross_entropy(model(data), target).backward()
        opt.step()
    t0 = time.perf_counter()
    for _ in range(TORCH_STEPS):
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()
        loss.item()  # per-batch host sync, as the reference does (:94)
    dt = time.perf_counter() - t0
    return bs * TORCH_STEPS / dt


def main() -> None:
    result = bench_accelerator()
    if result.get("captured"):
        # Watcher capture: already a fully formatted output line (baseline
        # ratio computed at capture time); pass it through with the live
        # failure attached so the provenance is auditable.
        out = result["captured"]
        out["tpu_error_live"] = result.get("live_errors")
        print(json.dumps(out))
        return
    try:
        baseline = bench_torch_reference()
    except Exception as exc:  # noqa: BLE001 - bench must always emit JSON
        baseline = 0.0
        result.setdefault("notes", []).append(f"torch baseline failed: {exc}")

    out = {
        "metric": "mnist_cnn_train_images_per_sec_per_chip",
        "unit": "images/sec/chip",
        "baseline": "torch-CPU per-batch reference loop, same CNN (BASELINE.md)",
    }
    if result.get("ok"):
        value = result["images_per_sec_per_chip"]
        out["value"] = round(value, 1)
        out["vs_baseline"] = round(value / baseline, 2) if baseline > 0 else 0.0
        mfu = result.get("mfu")
        out["mfu"] = round(mfu, 4) if mfu is not None else None
        for key in ("backend", "device_kind", "n_chips", "global_batch",
                    "steps_per_sec", "flops_per_step", "peak_flops_per_chip",
                    "mode", "images_per_sec_per_chip_fused_kernels",
                    "fused_kernels_error",
                    "images_per_sec_per_chip_device_gather",
                    "images_per_sec_per_chip_device_gather_sorted",
                    "device_gather_error", "compile_stats", "tpu_error",
                    "notes"):
            if result.get(key) is not None:
                val = result[key]
                out[key] = round(val, 2) if isinstance(val, float) else val
    else:
        out["value"] = 0.0
        out["vs_baseline"] = 0.0
        out["error"] = result.get("error", "unknown failure")
    if baseline > 0:
        out["baseline_images_per_sec"] = round(baseline, 1)
    if out.get("backend") != "tpu":
        # Chip-dead round: the honest CPU/error line still records where
        # the newest real TPU evidence lives (non-headline pointer).
        pointer = _last_valid_tpu_capture()
        if pointer is not None:
            out["last_valid_tpu_capture"] = pointer
    # Measurement provenance travels inside the line itself so a later
    # re-emission (watcher-capture fallback) can never restamp it.
    out["measured_at"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(json.dumps(out))
    if not result.get("ok"):
        # Even the CPU fallback died: same failed-runs-never-exit-0
        # convention as --vit / the kernel tools, after the JSON line.
        sys.exit(1)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        steps = int(sys.argv[2]) if len(sys.argv) > 2 else 50
        reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
        try:
            if os.environ.get("BENCH_VIT"):
                print(json.dumps(child_bench_vit(steps, reps)))
            else:
                print(json.dumps(child_bench(
                    steps, reps, probe=bool(os.environ.get("BENCH_PROBE")))))
        except Exception as exc:  # noqa: BLE001 - parent parses this
            print(json.dumps({"ok": False, "error": repr(exc)}))
            sys.exit(1)
        sys.exit(0)
    argv = sys.argv[1:]
    mode = None
    if "--mode" in argv:
        idx = argv.index("--mode")
        # A bare trailing --mode must error, not silently run the
        # multi-minute training bench (empty $MODE in a CI invocation).
        mode = argv[idx + 1] if idx + 1 < len(argv) else "(missing)"
    else:
        mode = next((a.split("=", 1)[1] for a in argv
                     if a.startswith("--mode=")), None)
    if mode == "serve":
        main_serve()
    elif mode == "input":
        main_input()
    elif mode == "zero":
        main_zero()
    elif mode == "publish":
        main_publish()
    elif mode not in (None, "train"):
        print(json.dumps({"error": f"unknown --mode {mode!r}; expected "
                                   f"train, serve, input, zero or "
                                   f"publish"}))
        sys.exit(2)
    elif "--vit" in argv:
        main_vit()
    else:
        main()
