"""Benchmark: MNIST CNN training throughput, images/sec/chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

``value`` is this framework's jitted scan-epoch training throughput on the
available accelerator(s). ``vs_baseline`` compares against the reference
implementation's approach — a PyTorch per-batch train loop with the same CNN
architecture and optimizer, run on the hardware the reference can use here
(CPU; the reference repo is CUDA-only and publishes no numbers of its own,
see BASELINE.md) — measured in-process at bench time.
"""

import json
import time

import numpy as np


BATCH = 1024
BENCH_STEPS = 50
TORCH_STEPS = 8


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images, synthetic_dataset
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_epoch

    n_chips = jax.device_count()
    mesh = make_mesh(("data",)) if n_chips > 1 else None
    model = get_model("cnn")
    state = create_train_state(model, jax.random.key(0))

    images, labels = synthetic_dataset(BATCH, seed=0)
    x = normalize_images(images)
    y = labels.astype(np.int32)

    def stacked(steps):
        return {
            "image": jnp.broadcast_to(x, (steps,) + x.shape),
            "label": jnp.broadcast_to(y, (steps,) + y.shape),
        }

    epoch = make_train_epoch(mesh)
    batches = stacked(BENCH_STEPS)
    # Warmup with the SAME shape so the timed region is compile-free.
    state, m = epoch(state, batches)
    float(m.count)  # full host roundtrip: remote execution definitely done
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        state, m = epoch(state, batches)
        assert float(m.count) == BATCH * BENCH_STEPS  # sync point
        best = min(best, time.perf_counter() - t0)
    return BATCH * BENCH_STEPS / best / n_chips


def bench_torch_reference() -> float:
    """Reference-style per-batch torch loop (same CNN, Adam), CPU."""
    import torch
    import torch.nn as tnn
    import torch.nn.functional as F

    torch.set_num_threads(max(1, torch.get_num_threads()))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(1, 32, 3, padding=1)
            self.conv2 = tnn.Conv2d(32, 64, 3, padding=1)
            self.fc1 = tnn.Linear(64 * 14 * 14, 128)
            self.fc2 = tnn.Linear(128, 10)

        def forward(self, x):
            x = F.relu(self.conv1(x))
            x = F.relu(self.conv2(x))
            x = F.max_pool2d(x, 2)
            x = x.flatten(1)
            return self.fc2(F.relu(self.fc1(x)))

    model = Net()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    bs = 256
    data = torch.randn(bs, 1, 28, 28)
    target = torch.randint(0, 10, (bs,))
    # warmup
    for _ in range(2):
        opt.zero_grad()
        F.cross_entropy(model(data), target).backward()
        opt.step()
    t0 = time.perf_counter()
    for _ in range(TORCH_STEPS):
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()
        loss.item()  # per-batch host sync, as the reference does (:94)
    dt = time.perf_counter() - t0
    return bs * TORCH_STEPS / dt


def main() -> None:
    value = bench_tpu()
    try:
        baseline = bench_torch_reference()
    except Exception:
        baseline = 0.0
    vs = value / baseline if baseline > 0 else 0.0
    print(json.dumps({
        "metric": "mnist_cnn_train_images_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 2),
    }))


if __name__ == "__main__":
    main()
