"""Benchmark: MNIST CNN training throughput, images/sec/chip (+ MFU).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "mfu": ..., "backend": ..., "device_kind": ..., ...}

``value`` is this framework's jitted scan-epoch training throughput.
``mfu`` is model-FLOPs utilization: (FLOPs/step x steps/sec) / chip peak
FLOPs, with FLOPs/step taken from the compiled program's own cost analysis
(falling back to an analytic count for the 2-conv CNN) and the peak from the
device kind's bf16 spec (the CNN computes in bfloat16, models/cnn.py).

``vs_baseline`` compares against the only baseline measurable here: the
reference implementation's approach — a PyTorch per-batch train loop with
the same CNN and optimizer — on the hardware the reference can use in this
environment (CPU; the reference repo is CUDA-only and publishes no numbers
of its own, see BASELINE.md). The ``baseline`` field names this so the ratio
is not mistaken for a like-for-like chip comparison.

Robustness (round-1 postmortem: BENCH_r01.json was rc=1/parsed=null because
one TPU-init failure escaped as a traceback): the accelerator bench runs in
a CHILD process with a timeout, retried with backoff; on persistent TPU
failure it falls back to a CPU-backend run (honestly labelled
``"backend": "cpu"`` with the TPU error attached); if even that fails the
parent still exits 0 with an ``{"error": ...}`` JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BATCH = 2048  # throughput peak on v5e: ~430k img/s at 2048-4096, +22% over 1024
TORCH_STEPS = 8

# Per-chip peak dense bf16 FLOPs by TPU generation (public spec sheets).
_PEAK_FLOPS = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# Analytic fallback: forward FLOPs/image for models/cnn.py (2 MACs per
# multiply-add), x3 for a training step (fwd + ~2x in bwd).
_CNN_FWD_FLOPS = (
    2 * 28 * 28 * 32 * 9 * 1  # conv1
    + 2 * 28 * 28 * 64 * 9 * 32  # conv2
    + 2 * (64 * 14 * 14) * 128  # fc1
    + 2 * 128 * 10  # fc2
)
_CNN_STEP_FLOPS_PER_IMAGE = 3 * _CNN_FWD_FLOPS


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for key, peak in _PEAK_FLOPS:
        if key in kind:
            return peak
    return None


def child_bench(steps: int, reps: int) -> dict:
    """Run the accelerator bench on whatever backend the env selects."""
    if os.environ.get("BENCH_FORCE_CPU"):
        # Some accelerator plugins force-write jax_platforms at import time,
        # so both the env var (before import) and the config API (after) are
        # needed — same workaround as tests/conftest.py.
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_mnist_tpu.data.mnist import (
        normalize_images,
        synthetic_dataset,
    )
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import (
        make_train_epoch,
        make_train_step,
    )

    n_chips = jax.device_count()
    device = jax.devices()[0]
    mesh = make_mesh(("data",)) if n_chips > 1 else None
    if device.platform == "cpu":
        # Fallback mode: bf16 conv is emulated (and awful) on CPU; use f32
        # and a smaller batch so the fallback finishes in seconds, not
        # minutes. The TPU path keeps the bf16 MXU configuration.
        batch = 256
        model = get_model("cnn", compute_dtype=jnp.float32)
    else:
        batch = BATCH
        model = get_model("cnn")
    state = create_train_state(model, jax.random.key(0))

    images, labels = synthetic_dataset(batch, seed=0)
    x = normalize_images(images)
    y = labels.astype(np.int32)
    batches = {
        "image": jnp.broadcast_to(x, (steps,) + x.shape),
        "label": jnp.broadcast_to(y, (steps,) + y.shape),
    }

    if device.platform == "cpu":
        # XLA:CPU compiles convolutions inside the scanned while-loop body
        # to a far slower code path than top-level convs (~30x observed), so
        # the fallback times the per-batch jitted step instead. On TPU the
        # scan epoch is the whole point: one device program per epoch, no
        # host round-trips through the tunnel.
        one = {"image": jnp.asarray(x), "label": jnp.asarray(y)}
        step_fn = make_train_step(mesh)

        def run_pass(state):
            m = None
            for _ in range(steps):
                state, m = step_fn(state, one)
            return state, m

        flops_probe = step_fn.lower(state, one)
        per_step_scale = 1.0
    else:
        epoch_fn = make_train_epoch(mesh)

        def run_pass(state):
            return epoch_fn(state, batches)

        flops_probe = epoch_fn.lower(state, batches)
        per_step_scale = float(steps)

    flops_per_step = None
    try:
        cost = flops_probe.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        total = float(cost.get("flops", 0.0))
        if total > 0:
            flops_per_step = total / per_step_scale
    except Exception:
        pass
    if not flops_per_step:
        flops_per_step = float(_CNN_STEP_FLOPS_PER_IMAGE * batch)

    def warmup_and_time(run_fn, st, expected_count):
        """Shared timing protocol: one compile/warmup pass synced by a full
        host read, then best-of-``reps`` — identical for the primary and
        the fused-kernel secondary so the two numbers stay comparable."""
        st, m = run_fn(st)
        float(m.count)  # full host roundtrip: remote execution definitely done
        t_best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            st, m = run_fn(st)
            assert float(m.count) == expected_count
            t_best = min(t_best, time.perf_counter() - t0)
        return st, t_best

    expected = batch * (1 if device.platform == "cpu" else steps)
    state, best = warmup_and_time(run_pass, state, expected)

    steps_per_sec = steps / best
    peak = _peak_flops(device.device_kind)
    mfu = (flops_per_step * steps_per_sec / n_chips / peak) if peak else None
    result = {
        "ok": True,
        "images_per_sec_per_chip": batch * steps / best / n_chips,
        "steps_per_sec": steps_per_sec,
        "global_batch": batch,
        "n_chips": n_chips,
        "backend": device.platform,
        "device_kind": device.device_kind,
        "flops_per_step": flops_per_step,
        "peak_flops_per_chip": peak,
        "mfu": mfu,
    }

    if device.platform != "cpu" and not os.environ.get("BENCH_SKIP_FUSED"):
        # Secondary measurement: the all-first-party-kernel path (Pallas
        # fused cross-entropy + fused Adam). Extra fields only — any
        # failure here is recorded and cannot harm the primary number.
        # Passing the mesh embeds the loss kernel in the GSPMD program
        # via its nested shard_map (per-device batch shards, no gather) —
        # the same path `--loss fused` takes on a multi-chip run.
        try:
            from pytorch_distributed_mnist_tpu.ops.loss import set_loss_impl

            set_loss_impl("fused", mesh=mesh)
            try:
                state_f = create_train_state(
                    model, jax.random.key(0), optimizer="adam_pallas")
                epoch_f = make_train_epoch(mesh)
                state_f, best_f = warmup_and_time(
                    epoch_f, state_f, batch * steps)
                result["images_per_sec_per_chip_fused_kernels"] = (
                    batch * steps / best_f / n_chips)
            finally:
                set_loss_impl("xla")
        except Exception as exc:  # noqa: BLE001 - secondary must not fail the bench
            result["fused_kernels_error"] = repr(exc)
    return result


def _run_child(env_extra: dict, steps: int, reps: int, timeout: float):
    env = dict(os.environ, **env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             str(steps), str(reps)],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"child timed out after {timeout:.0f}s"
    child_error = None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            if result.get("ok"):
                return result, None
            if child_error is None and result.get("error"):
                child_error = result["error"]  # the child's own diagnosis
    if child_error is not None:
        return None, f"rc={proc.returncode}: {child_error}"
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-6:]
    return None, f"rc={proc.returncode}: " + " | ".join(tail)


def bench_accelerator() -> dict:
    """TPU child with retry/backoff; CPU-backend fallback; never raises."""
    errors = []
    timeouts = (480.0, 720.0)
    for attempt, timeout in enumerate(timeouts):
        result, err = _run_child({}, steps=50, reps=3, timeout=timeout)
        if result:
            return result
        errors.append(f"tpu attempt {attempt + 1}: {err}")
        if attempt + 1 < len(timeouts):  # backoff only between retries
            time.sleep(15 * (attempt + 1))
    # This environment has a single host core; keep the CPU fallback tiny so
    # it finishes inside the timeout (it exists to produce an honest number,
    # not a fast one).
    result, err = _run_child(
        {"BENCH_FORCE_CPU": "1"}, steps=4, reps=2, timeout=900.0
    )
    if result:
        result["tpu_error"] = "; ".join(errors)
        return result
    errors.append(f"cpu fallback: {err}")
    return {"ok": False, "error": "; ".join(errors)}


def bench_torch_reference() -> float:
    """Reference-style per-batch torch loop (same CNN, Adam), CPU."""
    import torch
    import torch.nn as tnn
    import torch.nn.functional as F

    torch.set_num_threads(max(1, torch.get_num_threads()))

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(1, 32, 3, padding=1)
            self.conv2 = tnn.Conv2d(32, 64, 3, padding=1)
            self.fc1 = tnn.Linear(64 * 14 * 14, 128)
            self.fc2 = tnn.Linear(128, 10)

        def forward(self, x):
            x = F.relu(self.conv1(x))
            x = F.relu(self.conv2(x))
            x = F.max_pool2d(x, 2)
            x = x.flatten(1)
            return self.fc2(F.relu(self.fc1(x)))

    model = Net()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    bs = 256
    data = torch.randn(bs, 1, 28, 28)
    target = torch.randint(0, 10, (bs,))
    # warmup
    for _ in range(2):
        opt.zero_grad()
        F.cross_entropy(model(data), target).backward()
        opt.step()
    t0 = time.perf_counter()
    for _ in range(TORCH_STEPS):
        opt.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        opt.step()
        loss.item()  # per-batch host sync, as the reference does (:94)
    dt = time.perf_counter() - t0
    return bs * TORCH_STEPS / dt


def main() -> None:
    result = bench_accelerator()
    try:
        baseline = bench_torch_reference()
    except Exception as exc:  # noqa: BLE001 - bench must always emit JSON
        baseline = 0.0
        result.setdefault("notes", []).append(f"torch baseline failed: {exc}")

    out = {
        "metric": "mnist_cnn_train_images_per_sec_per_chip",
        "unit": "images/sec/chip",
        "baseline": "torch-CPU per-batch reference loop, same CNN (BASELINE.md)",
    }
    if result.get("ok"):
        value = result["images_per_sec_per_chip"]
        out["value"] = round(value, 1)
        out["vs_baseline"] = round(value / baseline, 2) if baseline > 0 else 0.0
        mfu = result.get("mfu")
        out["mfu"] = round(mfu, 4) if mfu is not None else None
        for key in ("backend", "device_kind", "n_chips", "global_batch",
                    "steps_per_sec", "flops_per_step", "peak_flops_per_chip",
                    "tpu_error", "notes"):
            if result.get(key) is not None:
                val = result[key]
                out[key] = round(val, 2) if isinstance(val, float) else val
    else:
        out["value"] = 0.0
        out["vs_baseline"] = 0.0
        out["error"] = result.get("error", "unknown failure")
    if baseline > 0:
        out["baseline_images_per_sec"] = round(baseline, 1)
    print(json.dumps(out))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        steps = int(sys.argv[2]) if len(sys.argv) > 2 else 50
        reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
        try:
            print(json.dumps(child_bench(steps, reps)))
        except Exception as exc:  # noqa: BLE001 - parent parses this
            print(json.dumps({"ok": False, "error": repr(exc)}))
            sys.exit(1)
        sys.exit(0)
    main()
