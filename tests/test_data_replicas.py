"""Host batch sharding groups processes by DATA coordinate, not identity.

``parallel/mesh.py data_replica_coords`` decides which rows each host
feeds: processes whose devices differ only along model/stage/seq/expert
axes are the SAME data replica and must load identical rows (the batch is
replicated w.r.t. them), while processes at different data coordinates
load disjoint DistributedSampler shards. Getting this wrong is silent —
``jax.make_array_from_process_local_data`` never value-checks nominal
replicas across hosts — which is exactly how the pre-fix loader fed
half-sized, host-divergent batches to multi-host PP runs (mesh
``data=1 x stage=2`` over 2 processes). The unit half drives the core
``_data_groups`` with fake devices; the end-to-end half lives in
tests/test_multiprocess.py::test_two_process_tensor_parallel_matches_single.
"""

from types import SimpleNamespace

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.parallel.mesh import (
    _data_groups,
    data_replica_coords,
    make_mesh,
)


def _grid(shape, proc_of_flat):
    """Device ndarray of the given mesh shape; flat device i belongs to
    process proc_of_flat(i) — mirroring make_mesh's reshape of the
    process-major jax.devices() order."""
    n = int(np.prod(shape))
    devs = np.array(
        [SimpleNamespace(process_index=proc_of_flat(i)) for i in range(n)],
        dtype=object,
    ).reshape(shape)
    return devs


def test_classic_dp_one_device_per_process():
    # mesh ('data',) over 4 single-device hosts: identity mapping.
    devs = _grid((4,), lambda i: i)
    assert [_data_groups(devs, p) for p in range(4)] == [
        (4, 0), (4, 1), (4, 2), (4, 3)]


def test_model_axis_spanning_processes_shares_the_batch():
    # mesh (data=1, stage=2) over 2 single-device hosts — the multi-host
    # PP/TP shape: both processes are the one data replica and must feed
    # the full, identical batch.
    devs = _grid((1, 2), lambda i: i)
    assert _data_groups(devs, 0) == (1, 0)
    assert _data_groups(devs, 1) == (1, 0)


def test_mixed_dp_times_model_grid():
    # mesh (data=2, model=2) over 4 single-device hosts, row-major device
    # order: hosts {0,1} share data row 0, hosts {2,3} share row 1.
    devs = _grid((2, 2), lambda i: i)
    assert _data_groups(devs, 0) == (2, 0)
    assert _data_groups(devs, 1) == (2, 0)
    assert _data_groups(devs, 2) == (2, 1)
    assert _data_groups(devs, 3) == (2, 1)


def test_multi_device_hosts_span_data_blocks():
    # 2 hosts x 4 devices, mesh (data=4, model=2): host 0's devices fill
    # data rows {0,1}, host 1's {2,3} — two replicas of two rows each.
    devs = _grid((4, 2), lambda i: i // 4)
    assert _data_groups(devs, 0) == (2, 0)
    assert _data_groups(devs, 1) == (2, 1)


def test_process_without_devices_raises():
    devs = _grid((2,), lambda i: 0)
    with pytest.raises(ValueError, match="owns no devices"):
        _data_groups(devs, 1)


def test_non_contiguous_ownership_raises():
    # Interleaved hosts along data (not a layout make_mesh produces).
    devs = _grid((4,), lambda i: i % 2)
    with pytest.raises(ValueError, match="contiguous"):
        _data_groups(devs, 0)


def test_misaligned_block_raises():
    # Coordinates [1,2] of 4: contiguous, dividing span, but straddling
    # the shard boundary — rank 1//2 == 0 would feed shard-0 rows to
    # shard-1 devices. Must refuse, not mis-rank.
    devs = _grid((4,), lambda i: {0: 0, 1: 1, 2: 1, 3: 2}[i])
    with pytest.raises(ValueError, match="aligned"):
        _data_groups(devs, 1)


def test_non_data_major_mesh_raises():
    mesh = make_mesh(("data", "model"), shape=(4, 2))
    from jax.sharding import Mesh

    swapped = Mesh(mesh.devices.T, ("model", "data"))
    with pytest.raises(ValueError, match="data-major"):
        data_replica_coords(swapped, process_index=0)


def test_single_process_any_mesh_is_one_replica():
    # The in-process (virtual 8-device) case: every mesh shape collapses
    # to one replica, rank 0 — current single-host behavior unchanged.
    for axes, shape in [
        (("data",), None),
        (("data", "model"), (4, 2)),
        (("data", "stage", "model"), (2, 2, 2)),
    ]:
        mesh = make_mesh(axes, shape=shape)
        assert data_replica_coords(mesh, process_index=0) == (1, 0)
