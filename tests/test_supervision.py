"""Run-supervision unit tests: watchdog deadlines, retry/backoff, fault
plans, the agreement record protocol, and hook/docs drift gates.

Everything here is hermetic (no subprocesses): multi-host behavior is
exercised by monkeypatching the supervisor's topology probes and its raw
allgather, so the protocol logic — deadline trips, phase-report dumps,
poison idempotence, record parsing — is pinned at unit speed. The real
2-process proofs live in tests/test_chaos.py and tests/test_multiprocess.py.
"""

import io
import os
import threading
import time

import pytest

from pytorch_distributed_mnist_tpu.runtime import supervision as sup
from pytorch_distributed_mnist_tpu.utils.profiling import EventLog, failure_events
from pytorch_distributed_mnist_tpu.utils.watchdog import (
    WatchdogTimeout,
    retry_with_backoff,
    run_with_deadline,
)


@pytest.fixture(autouse=True)
def _reset_supervisor(monkeypatch):
    """Supervisor state is process-global (configured per run by cli.run);
    every test starts and ends disarmed so nothing leaks across tests."""
    monkeypatch.delenv(sup.FAULT_ENV, raising=False)
    monkeypatch.delenv(sup.TIMEOUT_ENV, raising=False)
    sup.configure(timeout=0, hard_exit_after=None)
    failure_events.reset()
    yield
    sup.configure(timeout=0, hard_exit_after=None)
    failure_events.reset()


# -- utils/watchdog.py -------------------------------------------------------


def test_deadline_zero_runs_inline():
    """timeout<=0 disables supervision entirely: fn runs on the CALLING
    thread (the production multi-host TPU default must not move
    collectives onto a worker thread for nothing)."""
    tid = {}
    out = run_with_deadline(
        lambda: tid.setdefault("t", threading.get_ident()) and 42 or 42,
        timeout=0, label="off")
    assert out == 42
    assert tid["t"] == threading.get_ident()


def test_deadline_returns_result_and_propagates_error():
    assert run_with_deadline(lambda: "ok", timeout=5, label="x") == "ok"
    with pytest.raises(ValueError, match="boom"):
        run_with_deadline(lambda: (_ for _ in ()).throw(ValueError("boom")),
                          timeout=5, label="x")


def test_deadline_trips_on_stall_and_dumps():
    """A stalled call trips the deadline, runs the diagnostic dump first,
    and raises WatchdogTimeout (marked already_agreed: no poison after)."""
    stall = threading.Event()
    dumped = []
    t0 = time.monotonic()
    with pytest.raises(WatchdogTimeout) as exc:
        run_with_deadline(lambda: stall.wait(60), timeout=0.3,
                          label="fake collective",
                          on_timeout=lambda: dumped.append(True))
    elapsed = time.monotonic() - t0
    stall.set()
    assert dumped == [True]
    assert elapsed < 30  # tripped at the deadline, not the stall length
    assert "fake collective" in str(exc.value)
    assert exc.value.already_agreed  # the agreed-exit contract


def test_retry_backoff_flaky_then_succeeds():
    calls = []
    delays = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "done"

    out = retry_with_backoff(
        flaky, attempts=5, base_delay=0.5, max_delay=8.0, jitter=0.25,
        sleep=delays.append)
    assert out == "done" and len(calls) == 3
    # exponential base + bounded jitter
    assert 0.5 <= delays[0] < 0.75 and 1.0 <= delays[1] < 1.25


def test_retry_backoff_exhaustion_and_nonretryable():
    with pytest.raises(OSError):
        retry_with_backoff(lambda: (_ for _ in ()).throw(OSError("x")),
                           attempts=2, sleep=lambda _: None)
    calls = []

    def wrong_type():
        calls.append(1)
        raise KeyError("not retryable")

    with pytest.raises(KeyError):
        retry_with_backoff(wrong_type, attempts=5, retry_on=(OSError,),
                           sleep=lambda _: None)
    assert len(calls) == 1  # no retry on a non-listed exception type

    observed = []
    with pytest.raises(OSError):
        retry_with_backoff(
            lambda: (_ for _ in ()).throw(OSError("x")), attempts=3,
            sleep=lambda _: None, jitter=0.0,
            on_retry=lambda n, exc, d: observed.append((n, d)))
    assert [n for n, _ in observed] == [1, 2]  # final failure: no on_retry


# -- fault plans -------------------------------------------------------------


def test_fault_plan_parse_and_defaults():
    p = sup.FaultPlan.parse("ckpt_publish:0:kill")
    assert (p.point, p.host, p.kind, p.arg) == ("ckpt_publish", "0",
                                                "kill", 0.0)
    assert sup.FaultPlan.parse("train_epoch:*:kill:2").arg == 2.0
    assert sup.FaultPlan.parse("eval:1:stall").arg == 3600.0
    for bad in ("nope", "unknown_point:0:kill", "eval:0:explode",
                "eval:x1:kill"):
        with pytest.raises(ValueError):
            sup.FaultPlan.parse(bad)


def test_maybe_fault_raise_kind(monkeypatch):
    monkeypatch.setenv(sup.FAULT_ENV, "eval:0:raise")
    sup.configure(timeout=0, hard_exit_after=None)  # re-parse the plan
    monkeypatch.setattr(sup, "process_index", lambda: 0)
    with pytest.raises(sup.InjectedFault, match="eval:0:raise"):
        sup.maybe_fault("eval")
    # host mismatch: silent no-op
    monkeypatch.setattr(sup, "process_index", lambda: 1)
    sup.maybe_fault("eval")


def test_maybe_fault_skip_count(monkeypatch):
    """arg = hits to SKIP for kill/raise: 'the Nth epoch' selectors."""
    monkeypatch.setenv(sup.FAULT_ENV, "train_epoch:*:raise:2")
    sup.configure(timeout=0, hard_exit_after=None)
    sup.maybe_fault("train_epoch")  # hit 0: skipped
    sup.maybe_fault("train_epoch")  # hit 1: skipped
    with pytest.raises(sup.InjectedFault):
        sup.maybe_fault("train_epoch")  # hit 2: fires


def test_maybe_fault_stall(monkeypatch):
    monkeypatch.setenv(sup.FAULT_ENV, "eval:*:stall:0.2")
    sup.configure(timeout=0, hard_exit_after=None)
    t0 = time.monotonic()
    sup.maybe_fault("eval")  # sleeps, then returns
    assert time.monotonic() - t0 >= 0.2


def test_unregistered_fault_point_asserts():
    with pytest.raises(AssertionError):
        sup.maybe_fault("not_a_point")


def test_parse_fault_specs_multi():
    """Comma-joined multi-fault plans: the mid-rebuild chaos shape (a
    host loss plus an elastic_rebuild sabotage of a survivor)."""
    plans = sup.parse_fault_specs(
        "train_epoch:2:kill:1,elastic_rebuild:1:stall")
    assert [(p.point, p.host, p.kind) for p in plans] == [
        ("train_epoch", "2", "kill"), ("elastic_rebuild", "1", "stall")]
    assert plans[1].arg == 3600.0
    # single-spec back-compat and per-spec validation
    assert len(sup.parse_fault_specs("eval:0:raise")) == 1
    with pytest.raises(ValueError, match="unknown fault point"):
        sup.parse_fault_specs("eval:0:raise,bogus:0:kill")
    with pytest.raises(ValueError, match="one fault per spec"):
        sup.FaultPlan.parse("eval:0:raise,eval:1:raise")


def test_maybe_fault_multi_plan_fires_matching_point(monkeypatch):
    """With two plans configured, each point fires only its own."""
    monkeypatch.setenv(sup.FAULT_ENV,
                       "eval:*:raise,elastic_rebuild:*:raise")
    sup.configure(timeout=0, hard_exit_after=None)
    sup.maybe_fault("train_epoch")  # matches neither plan
    with pytest.raises(sup.InjectedFault, match="eval"):
        sup.maybe_fault("eval")
    with pytest.raises(sup.InjectedFault, match="elastic_rebuild"):
        sup.maybe_fault("elastic_rebuild")


def _analyzer():
    """Thin-wrapper plumbing: since ISSUE 5 the registry<->hook drift
    logic lives in tpumnist-lint (tools/analyzer, ``registry-drift``
    checker); these tests drive it through its API so the runtime
    registry, the static gate, and chaos --list can never disagree.
    conftest.py already put the repo root on sys.path."""
    import tools.analyzer as analyzer

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return analyzer, repo


def test_fault_points_registry_matches_call_sites():
    """Drift gate (now a wrapper over the analyzer): a hook without a
    registry entry, a registry entry whose hook was deleted, or a
    computed point name all fail here — so tools/chaos.py --list and the
    docs can never advertise fault points that don't exist."""
    analyzer, repo = _analyzer()
    result = analyzer.run_analysis(
        [os.path.join(repo, "pytorch_distributed_mnist_tpu"),
         os.path.join(repo, "tools"), os.path.join(repo, "bench.py")],
        checkers=["registry-drift"], baseline=None)
    assert result.ok, "\n".join(f.render() for f in result.findings)
    report = result.reports["registry-drift"]
    # The checker saw the real registry and real hooks, not a vacuous
    # empty view — and they agree with the runtime module's own dict.
    assert report["fault_points"] == sorted(sup.FAULT_POINTS)
    assert report["hook_sites"] >= len(sup.FAULT_POINTS)


def test_chaos_list_matches_registry():
    """chaos --list renders what the analyzer statically parsed as the
    registry; the spawned-tool view, the AST view, and the runtime dict
    must be one set."""
    import importlib.util

    analyzer, repo = _analyzer()
    from tools.analyzer.checkers.registry_drift import registry_entries
    from tools.analyzer.core import parse_modules

    sup_path = os.path.join(repo, "pytorch_distributed_mnist_tpu",
                            "runtime", "supervision.py")
    modules, problems = parse_modules([sup_path])
    assert not problems
    _module, keys = registry_entries(modules)
    assert set(keys) == set(sup.FAULT_POINTS)  # AST view == runtime view

    spec = importlib.util.spec_from_file_location(
        "chaos_tool", os.path.join(repo, "tools", "chaos.py"))
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)
    buf = io.StringIO()
    chaos.list_fault_points(buf)
    listed = {line.split("\t")[0]
              for line in buf.getvalue().splitlines() if line}
    assert listed == set(keys)  # --list view == AST view


# -- agreement records -------------------------------------------------------


def _fake_world(monkeypatch, nproc=2, rank=0):
    monkeypatch.setattr(sup, "process_count", lambda: nproc)
    monkeypatch.setattr(sup, "process_index", lambda: rank)


def test_record_roundtrip():
    sup.set_phase("train@3")
    rec = sup._decode_record(sup._encode_record(sup._OK, "detail text"))
    assert rec.ok and not rec.poisoned
    assert rec.phase == "train@3" and rec.detail == "detail text"
    pill = sup._decode_record(sup._encode_record(sup._POISON, "r"))
    assert pill.poisoned and not pill.ok


def test_single_process_agree_is_local():
    """No collective machinery for one process: agree returns this host's
    failure (if any) and callers re-raise their own error."""
    failed = sup.agree("write", None)
    assert failed == []
    err = OSError("local")
    failed = sup.agree("write", err)
    assert failed and failed[0][0] == 0
    assert getattr(err, "_poison_delivered", False)  # marked as delivered


def test_agreement_exchanges_records(monkeypatch):
    """Peers' E records come back attributed (host, phase, reason)."""
    import numpy as np

    _fake_world(monkeypatch, rank=0)

    def fake_allgather(payload):
        sup_phase = sup.current_phase()
        try:
            sup.set_phase("checkpoint@1")
            peer = np.frombuffer(
                sup._encode_record(sup._ERR, "peer exploded"), np.uint8)
        finally:
            sup.set_phase(sup_phase)
        return np.stack([payload, peer])

    monkeypatch.setattr(sup, "_raw_allgather", fake_allgather)
    failed = sup.agree("ckpt_write", None)
    assert failed == [(1, "checkpoint@1", "peer exploded")]


def test_agreement_watchdog_trips_with_phase_report(monkeypatch, capsys):
    """A silent peer trips the agreement deadline: the per-host phase
    report is dumped and PeerFailure implicates every other host."""
    _fake_world(monkeypatch, nproc=3, rank=1)
    sup.configure(timeout=0.3, hard_exit_after=None)
    sup.set_phase("checkpoint@2")
    stall = threading.Event()
    monkeypatch.setattr(sup, "_raw_allgather", lambda p: stall.wait(60))
    with pytest.raises(sup.PeerFailure) as exc:
        sup.allgather_records("ckpt_publish", True)
    stall.set()
    assert exc.value.hosts == [0, 2]
    assert exc.value.phase == "ckpt_publish"
    assert exc.value.already_agreed
    err = capsys.readouterr().err
    assert "supervision watchdog report" in err
    assert "blocked in: agreement 'ckpt_publish'" in err
    assert "lifecycle phase: checkpoint@2" in err
    kinds = [e["kind"] for e in failure_events.snapshot()]
    assert "agreement_timeout" in kinds


def test_agreement_timeout_zero_disables_watchdog(monkeypatch):
    """--agreement-timeout 0: the collective runs inline on the calling
    thread, unbounded — the real multi-host TPU default."""
    import numpy as np

    _fake_world(monkeypatch)
    sup.configure(timeout=0, hard_exit_after=None)
    seen = {}

    def fake_allgather(payload):
        seen["thread"] = threading.get_ident()
        return np.stack([payload, payload])

    monkeypatch.setattr(sup, "_raw_allgather", fake_allgather)
    records = sup.allgather_records("ckpt_write", True)
    assert len(records) == 2 and all(r.ok for r in records)
    assert seen["thread"] == threading.get_ident()


def test_heartbeats_recorded_and_dumped(monkeypatch, capsys):
    """Completed agreements record each host's reported phase; the next
    watchdog trip renders them as the last-heartbeat table."""
    import numpy as np

    _fake_world(monkeypatch, rank=0)
    monkeypatch.setattr(
        sup, "_raw_allgather", lambda p: np.stack([p, p]))
    sup.set_phase("train@7")
    sup.allgather_records("ckpt_write", True)
    sup.configure(timeout=0.2, hard_exit_after=None)
    # configure() resets heartbeats; record one under the armed deadline
    sup.set_phase("train@7")
    sup.allgather_records("ckpt_write", True)
    stall = threading.Event()
    monkeypatch.setattr(sup, "_raw_allgather", lambda p: stall.wait(60))
    with pytest.raises(sup.PeerFailure):
        sup.allgather_records("ckpt_publish", True)
    stall.set()
    err = capsys.readouterr().err
    assert "host 1: phase 'train@7' at agreement #1" in err


def test_deliver_poison_idempotent_and_skips_agreed(monkeypatch):
    import numpy as np

    _fake_world(monkeypatch)
    calls = []

    def fake_allgather(payload):
        calls.append(payload)
        return np.stack([payload, payload])

    monkeypatch.setattr(sup, "_raw_allgather", fake_allgather)
    err = RuntimeError("host-local")
    sup.deliver_poison(err)
    sup.deliver_poison(err)  # second delivery for the same exception
    assert len(calls) == 1  # exactly one pill
    rec = sup._decode_record(calls[0].tobytes())
    assert rec.poisoned and "host-local" in rec.detail

    # already-agreed failures (PeerFailure, WatchdogTimeout) never poison
    sup.deliver_poison(sup.PeerFailure("x", hosts=[1], phase="p"))
    sup.deliver_poison(WatchdogTimeout("label", 1.0))
    sup.deliver_poison(KeyboardInterrupt())
    assert len(calls) == 1


def test_raise_if_poisoned(monkeypatch):
    _fake_world(monkeypatch, rank=0)
    records = [sup.Record("K", "resume", ""),
               sup.Record("P", "train@4", "OOM on host 1")]
    with pytest.raises(sup.PeerFailure) as exc:
        sup.raise_if_poisoned(records, "the resume agreement")
    assert exc.value.hosts == [1]
    assert exc.value.phase == "train@4"
    assert "OOM on host 1" in str(exc.value)
    # an E vote in the same phase is NOT a poison pill
    sup.raise_if_poisoned([sup.Record("K", "resume", ""),
                           sup.Record("E", "resume", "no file")],
                          "the resume agreement")


def test_configure_env_resolution(monkeypatch):
    monkeypatch.setenv(sup.TIMEOUT_ENV, "12.5")
    assert sup.configure() == 12.5
    assert sup.configure(timeout=3.0) == 3.0  # flag wins over env
    assert sup.configure(timeout=0) == 0.0
    monkeypatch.setenv(sup.TIMEOUT_ENV, "not-a-number")
    with pytest.raises(SystemExit):
        sup.configure()


def test_event_log_thread_safe_snapshot():
    log = EventLog()
    log.record("kind_a", "one", phase="p")
    log.record("kind_b", "two")
    snap = log.snapshot()
    assert [e["kind"] for e in snap] == ["kind_a", "kind_b"]
    assert snap[0]["phase"] == "p"
    snap[0]["kind"] = "mutated"  # snapshots are copies
    assert log.snapshot()[0]["kind"] == "kind_a"
    log.reset()
    assert log.snapshot() == []


# ---------------------------------------------------------------------------
# InjectedFault transparency through the broadened download handlers
# ---------------------------------------------------------------------------
# The tpumnist-lint audit broadened the download warn-and-continue paths to
# `except Exception` (the zlib-strand class), but `chaos --list` advertises
# `download_fetch:*:raise` — the injection must still escape both callers,
# or the harness can never drive the download-failure -> poison-pill path
# once the IDX files are on disk.


def test_mnist_download_handler_reraises_injected_fault(tmp_path, monkeypatch):
    from pytorch_distributed_mnist_tpu.data import download as dl
    from pytorch_distributed_mnist_tpu.data.mnist import load_dataset

    def boom(root, name):
        raise sup.InjectedFault("injected fault at download_fetch")

    monkeypatch.setattr(dl, "download_dataset", boom)
    with pytest.raises(sup.InjectedFault):
        load_dataset(str(tmp_path), "mnist", train=True,
                     synthesize_if_missing=True, download=True)


def test_mnist_download_handler_still_funnels_real_failures(
        tmp_path, monkeypatch, capsys):
    import zlib
    from pytorch_distributed_mnist_tpu.data import download as dl
    from pytorch_distributed_mnist_tpu.data.mnist import load_dataset

    def boom(root, name):
        raise zlib.error("Error -3 while decompressing data")

    monkeypatch.setattr(dl, "download_dataset", boom)
    images, labels = load_dataset(str(tmp_path), "mnist", train=True,
                                  synthesize_if_missing=True, download=True)
    assert images.shape[0] == labels.shape[0] > 0  # synthetic fallback
    assert "WARNING: download" in capsys.readouterr().out


def test_cli_download_stage_reraises_injected_fault(tmp_path, monkeypatch):
    import argparse

    from pytorch_distributed_mnist_tpu import cli
    from pytorch_distributed_mnist_tpu.data import download as dl

    def boom(root, name):
        raise sup.InjectedFault("injected fault at download_fetch")

    monkeypatch.setattr(dl, "download_dataset", boom)
    args = argparse.Namespace(dataset="mnist", download=True,
                              root=str(tmp_path))
    with pytest.raises(sup.InjectedFault):
        cli._build_loaders(args, seed=0, mesh=None)
