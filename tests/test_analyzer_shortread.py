"""Fixture suite: the short-read checker + the real fetch paths.

Pins the PR 19 distrib/fetch.py torn-chunk incident: ``http.client``
only raises ``IncompleteRead`` for chunk-framed bodies — a
Content-Length body torn mid-stream comes back as plain short bytes,
and only comparing the received count against the header catches it.
The reversion tests re-remove the shipped fixes from the REAL files
(data/download.py ``_fetch``, serve/router.py ``http_exchange``) and
assert the checker reproduces a file:line finding.
"""

import os
import pathlib

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src, filename="snippet.py"):
    return analyze_snippet(src, checkers=["short-read"],
                           filename=filename)


# -- firing ------------------------------------------------------------------


def test_fires_on_chunked_read_loop_without_length_check():
    """The download.py shape before the fix: a torn connection ends the
    chunk loop exactly like a complete body."""
    src = """
import urllib.request

def fetch(url, dest):
    with urllib.request.urlopen(url) as r, open(dest, "wb") as f:
        while True:
            chunk = r.read(1 << 20)
            if not chunk:
                break
            f.write(chunk)
"""
    (f,) = _findings(src)
    assert "Content-Length" in f.message and "torn" in f.message


def test_fires_on_getresponse_read_without_length_check():
    """The router.py http_exchange shape before the fix."""
    src = """
import http.client

def exchange(host, path):
    conn = http.client.HTTPConnection(host)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    return resp.status, data
"""
    (f,) = _findings(src)
    assert "Content-Length" in f.message


# -- non-firing --------------------------------------------------------------


def test_clean_when_received_count_is_compared():
    src = """
import urllib.request

def fetch(url, dest):
    with urllib.request.urlopen(url) as r, open(dest, "wb") as f:
        expected = r.headers.get("Content-Length")
        received = 0
        while True:
            chunk = r.read(1 << 20)
            if not chunk:
                break
            received += len(chunk)
            f.write(chunk)
        if expected is not None and received != int(expected):
            raise OSError("short read")
"""
    assert _findings(src) == []


def test_clean_when_body_feeds_json_loads():
    """json.loads is its own truncation detector: torn JSON raises."""
    src = """
import json, urllib.request

def get_json(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())
"""
    assert _findings(src) == []


def test_clean_when_read_result_is_discarded():
    """The loadgen drain shape: the bytes are thrown away, truncation
    cannot corrupt anything."""
    src = """
import urllib.request

def drain(url):
    with urllib.request.urlopen(url) as r:
        r.read()
"""
    assert _findings(src) == []


def test_clean_on_nonhttp_reads():
    src = """
def load(path):
    with open(path, "rb") as f:
        return f.read()
"""
    assert _findings(src) == []


# -- reversion: re-remove the shipped fixes from the REAL files --------------


_DOWNLOAD = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / \
    "data" / "download.py"
_ROUTER = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / \
    "serve" / "router.py"


def test_removing_the_download_length_check_fails_the_gate():
    source = _DOWNLOAD.read_text()
    guard = "expected is not None and received != int(expected)"
    assert guard in source, (
        "download.py _fetch no longer verifies Content-Length — evolve "
        "this fixture with the code")
    broken = source.replace(guard, "False", 1)
    findings = _findings(broken, filename="download.py")
    assert findings, "unverified chunk loop was not flagged"
    f = findings[0]
    assert f.path == "download.py" and f.line > 0
    assert f.symbol == "_fetch"


def test_pristine_download_is_clean():
    assert _findings(_DOWNLOAD.read_text(), filename="download.py") == []


def test_removing_the_router_length_check_fails_the_gate():
    source = _ROUTER.read_text()
    assert "len(data) != int(expected)" in source, (
        "router.py http_exchange no longer verifies Content-Length — "
        "evolve this fixture with the code")
    broken = source.replace("len(data) != int(expected)", "False", 1)
    findings = _findings(broken, filename="router.py")
    assert findings, "unverified body read was not flagged"
    f = findings[0]
    assert f.path == "router.py" and f.line > 0
    assert f.symbol == "http_exchange"


def test_pristine_router_is_clean():
    assert _findings(_ROUTER.read_text(), filename="router.py") == []
