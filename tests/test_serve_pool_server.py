"""Pooled serving over real loopback HTTP: `--serve-devices 4` boots an
EnginePool behind the pipelined batcher; loadgen's smoke gate passes
with zero steady-state recompiles on EVERY replica; hot reload under
live traffic swaps the whole fleet; and the default single-replica
configuration keeps the exact pre-pool /stats schema."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.server import build_parser, create_server
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _publish(ckpt_dir, epoch, seed):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


def _serve_args(ckpt_dir, **overrides):
    argv = [
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8,32",
        "--max-wait-ms", "2", "--max-queue", "128",
        "--poll-interval", "0.1",
        # Split-plane boots: this suite pins no fused behavior, and the
        # fused AOT warm would re-pay its compile wall per boot (x replicas)
        # across the whole file -- tier-1 compile budget. The fused default
        # is pinned in test_serve_server.py / test_serve_fused.py.
        "--no-fuse",
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


class _Server:
    def __init__(self, args):
        self.httpd = create_server(args)
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())


@pytest.fixture()
def pooled_server(tmp_path):
    ckpt = tmp_path / "ckpt"
    state = _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_devices=4))
    try:
        yield srv, state, ckpt
    finally:
        srv.close()


def _replica_program_compiles():
    return {name: rec["backend_compiles"]
            for name, rec in compile_log.stats()["programs"].items()
            if name.startswith("serve_forward_") and "@" in name}


def test_pooled_loadgen_smoke_zero_recompiles_every_replica(pooled_server):
    """The pooled acceptance run: loadgen --smoke --expect-replicas 4
    against a 4-replica server passes, with ZERO steady-state recompiles
    on every replica (per-replica CompileLog program names)."""
    srv, state, _ = pooled_server
    images, _ = synthetic_dataset(3, seed=0)
    reply = srv.post("/predict", {"images": images.tolist()})
    # Predictions pinned to the direct forward pass through the pool.
    model = get_model("linear", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state.params, jnp.asarray(normalize_images(images)), train=False)),
        axis=-1)
    assert reply["predictions"] == [int(v) for v in want]
    assert reply["model_epoch"] == 0

    before = _replica_program_compiles()
    # 4 replicas x 3 buckets all AOT-compiled (superset: compile_log is a
    # process singleton, other pool tests may have added replica names).
    assert {f"serve_forward_b{b}@r{i}" for b in (1, 8, 32)
            for i in range(4)} <= set(before)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", srv.url, "--requests", "600",
         "--concurrency", "8", "--expect-replicas", "4"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["smoke_ok"] and report["ok"] == 600
    assert len(report["replicas"]) == 4
    # Zero steady-state recompiles, checked replica by replica.
    assert _replica_program_compiles() == before

    stats = srv.get("/stats")
    assert stats["serve_devices"] == 4 and stats["max_inflight"] == 5
    assert sorted(stats["replicas"]) == ["r0", "r1", "r2", "r3"]
    assert sum(r["batches"] for r in stats["replicas"].values()) \
        == stats["batches"]
    assert all(r["params_epoch"] == 0 for r in stats["replicas"].values())


def test_pooled_hot_reload_under_live_traffic(pooled_server):
    """Publish a new checkpoint while clients hammer the pooled server:
    no failures, every reply carries a real epoch (old or new), the
    WHOLE fleet converges to the new epoch, and steady state serves the
    new params."""
    srv, _, ckpt = pooled_server
    images, _ = synthetic_dataset(4, seed=3)
    payload = {"images": images.tolist()}
    failures = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                reply = srv.post("/predict", payload)
                if (len(reply["predictions"]) != 4
                        or reply["model_epoch"] not in (0, 9)):
                    failures.append(("malformed", reply))
            except Exception as exc:  # noqa: BLE001
                failures.append(("error", repr(exc)))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    state_b = _publish(ckpt, epoch=9, seed=77)
    deadline = time.time() + 15.0
    while time.time() < deadline:
        if srv.get("/healthz")["model_epoch"] == 9:
            break
        time.sleep(0.05)
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(10.0)

    assert not failures, failures[:5]
    stats = srv.get("/stats")
    assert stats["reloads"] == 1
    # One host-side load fanned out: EVERY replica serves epoch 9.
    assert all(r["params_epoch"] == 9 for r in stats["replicas"].values())
    model = get_model("linear", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state_b.params, jnp.asarray(normalize_images(images)),
        train=False)), axis=-1)
    assert srv.post("/predict", payload)["predictions"] \
        == [int(v) for v in want]


def test_default_single_replica_stats_schema_unchanged(tmp_path):
    """Criterion: the default configuration (no --serve-devices /
    --max-inflight) is the pre-pool data plane — /stats carries no
    replica fields and the engine programs keep their unsuffixed
    names."""
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    args = _serve_args(ckpt)
    assert args.serve_devices == 1 and args.max_inflight == 0
    srv = _Server(args)
    try:
        assert srv.httpd.ctx.pool is None
        images, _ = synthetic_dataset(2, seed=1)
        srv.post("/predict", {"images": images.tolist()})
        stats = srv.get("/stats")
        assert "replicas" not in stats
        assert "serve_devices" not in stats and "max_inflight" not in stats
        assert {"serve_forward_b1", "serve_forward_b8",
                "serve_forward_b32"} <= set(stats["compile"]["programs"])
    finally:
        srv.close()


def test_serve_devices_zero_means_all_and_bounds_checked(tmp_path):
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_devices=0, buckets="1,8"))
    try:
        stats = srv.get("/stats")
        assert stats["serve_devices"] == len(jax.local_devices())
    finally:
        srv.close()
    with pytest.raises(SystemExit, match="local device"):
        create_server(_serve_args(ckpt, serve_devices=99))


def test_pipelining_on_single_device(tmp_path):
    """--max-inflight alone (one replica) still runs the pooled pipelined
    plane: requests serve correctly with the window open."""
    ckpt = tmp_path / "ckpt"
    state = _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_devices=1, max_inflight=3,
                              buckets="1,8"))
    try:
        assert srv.httpd.ctx.pool is not None
        assert srv.get("/stats")["max_inflight"] == 3
        images, _ = synthetic_dataset(6, seed=4)
        reply = srv.post("/predict", {"images": images.tolist()})
        model = get_model("linear", compute_dtype=jnp.float32)
        want = np.argmax(np.asarray(model.apply(
            state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
    finally:
        srv.close()


# -- the sharded data plane over real HTTP (serve/programs.py) ---------------


def _publish_model(ckpt_dir, model_name, epoch, seed, parallel_layout=None):
    model = get_model(model_name, compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0,
                    parallel_layout=parallel_layout)
    return model, state


def test_sharded_server_loadgen_smoke_expect_mode(tmp_path):
    """The ISSUE acceptance run: ``serve --serve-mode tensor`` on a
    2-chip mesh answers /predict with logits pinned to the single-device
    forward, /stats carries the mode + mesh shape, and loadgen's
    ``--smoke --expect-mode tensor`` gate passes with zero steady-state
    recompiles per bucket x mode."""
    ckpt = tmp_path / "ckpt"
    model, state = _publish_model(ckpt, "vit", epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, model="vit", buckets="1,8",
                              serve_devices=2, serve_mode="tensor",
                              serve_mesh=2))
    try:
        images, _ = synthetic_dataset(5, seed=0)
        reply = srv.post("/predict", {"images": images.tolist()})
        want = np.argmax(np.asarray(model.apply(
            state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
        assert reply["model_epoch"] == 0

        stats = srv.get("/stats")
        assert stats["serve_mode"] == "tensor"
        assert stats["serve_devices"] == 2
        assert stats["mesh_devices"] == 2 and stats["mesh_groups"] == 1
        assert sorted(stats["replicas"]) == ["tensor"]
        row = stats["replicas"]["tensor"]
        assert row["mode"] == "tensor" and len(row["devices"]) == 2

        programs = compile_log.stats()["programs"]
        names = {f"serve_forward_b{b}@tensor" for b in (1, 8)}
        assert names <= set(programs)
        before = {n: programs[n]["backend_compiles"] for n in names}
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--smoke", "--url", srv.url, "--requests", "200",
             "--concurrency", "8", "--expect-mode", "tensor",
             "--expect-replicas", "1"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["smoke_ok"] and report["ok"] == 200
        # The loadgen report names WHAT it measured (sourced from /stats).
        assert report["serve_mode"] == "tensor"
        assert report["mesh_devices"] == 2 and report["mesh_groups"] == 1
        after = compile_log.stats()["programs"]
        assert {n: after[n]["backend_compiles"] for n in names} == before
    finally:
        srv.close()


def test_sharded_server_stats_forward_slice_straddling(tmp_path,
                                                       monkeypatch):
    """The /stats handler FORWARDS the pool's slice-alignment warning
    (a field present only when a DCN slice topology exists): 8 emulated
    1-chip slices make every 2-chip tensor group straddle, and the
    served stats — the surface loadgen reports copy — name both."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import DCN_SLICES_ENV

    monkeypatch.setenv(DCN_SLICES_ENV, "8")
    ckpt = tmp_path / "ckpt"
    _publish_model(ckpt, "vit", epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, model="vit", buckets="8",
                              serve_devices=4, serve_mode="tensor",
                              serve_mesh=2))
    try:
        stats = srv.get("/stats")
        assert sorted(stats["slice_straddling_groups"]) \
            == ["tensor.g0", "tensor.g1"]
    finally:
        srv.close()


def test_sharded_server_hot_reload_under_traffic(tmp_path):
    """Fleet-wide hot reload on the mesh plane: a newer checkpoint
    published under live traffic swaps every mesh group; replies after
    the swap carry the new epoch and its exact predictions."""
    ckpt = tmp_path / "ckpt"
    model, _ = _publish_model(ckpt, "moe_mlp", epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, model="moe_mlp", buckets="1,8",
                              serve_devices=4, serve_mode="expert",
                              serve_mesh=2))
    try:
        images, _ = synthetic_dataset(6, seed=2)
        srv.post("/predict", {"images": images.tolist()})
        _, new_state = _publish_model(ckpt, "moe_mlp", epoch=3, seed=77)
        deadline = time.time() + 30
        while time.time() < deadline:
            if srv.get("/healthz")["model_epoch"] == 3:
                break
            srv.post("/predict", {"images": images.tolist()})
            time.sleep(0.05)
        reply = srv.post("/predict", {"images": images.tolist()})
        assert reply["model_epoch"] == 3
        want = np.argmax(np.asarray(model.apply(
            new_state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
        assert srv.get("/stats")["reloads"] == 1
    finally:
        srv.close()


def test_sharded_server_flag_rejections(tmp_path):
    """Unservable combinations die at boot with flag language: model
    without a rule table, mesh not dividing the chips, a mesh on the
    replicated plane, and a layout-mismatched boot checkpoint naming the
    valid --serve-mode choices."""
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)  # linear checkpoint
    with pytest.raises(SystemExit, match="no sharding rule table"):
        create_server(_serve_args(ckpt, serve_devices=2,
                                  serve_mode="tensor"))
    with pytest.raises(SystemExit, match="must divide --serve-devices"):
        create_server(_serve_args(ckpt, model="vit", serve_devices=4,
                                  serve_mode="tensor", serve_mesh=3))
    with pytest.raises(SystemExit, match="needs a sharded mode"):
        create_server(_serve_args(ckpt, serve_devices=2, serve_mesh=2))
    moe_ckpt = tmp_path / "moe_ckpt"
    _publish_model(moe_ckpt, "moe_mlp", epoch=0, seed=1,
                   parallel_layout={"expert": 4})
    with pytest.raises(SystemExit, match="--serve-mode expert"):
        create_server(_serve_args(moe_ckpt, model="moe_mlp"))
    # The same checkpoint boots fine under the matching mode.
    srv = _Server(_serve_args(moe_ckpt, model="moe_mlp", buckets="1,8",
                              serve_devices=2, serve_mode="expert"))
    try:
        assert srv.get("/stats")["serve_mode"] == "expert"
    finally:
        srv.close()


def test_layout_mismatched_newest_falls_back_to_older_epoch(tmp_path):
    """Restart availability beats strictness when an older compatible
    checkpoint exists: a newest publish stamped with a mismatched
    training layout is skipped IN the boot walk (meta-only read, no
    template load) and the server boots on the next-older compatible
    epoch — the same stance the corrupt-latest walk takes. Only when
    layout mismatches are the SOLE servable content does boot fail
    loudly (test_sharded_server_flag_rejections pins that arm)."""
    ckpt = tmp_path / "ckpt"
    model, old_state = _publish_model(ckpt, "moe_mlp", epoch=0, seed=5,
                                      parallel_layout={"expert": 1})
    _publish_model(ckpt, "moe_mlp", epoch=1, seed=6,
                   parallel_layout={"expert": 4})
    srv = _Server(_serve_args(ckpt, model="moe_mlp", buckets="1,8"))
    try:
        health = srv.get("/healthz")
        assert health["model_epoch"] == 0
        assert health["checkpoint"].endswith("checkpoint_0.npz")
        images, _ = synthetic_dataset(4, seed=9)
        reply = srv.post("/predict", {"images": images.tolist()})
        want = np.argmax(np.asarray(model.apply(
            old_state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
    finally:
        srv.close()


# -- MPMD pipeline serving (ISSUE 12) ----------------------------------------


def _publish_pipeline(ckpt_dir, epoch, seed, stages=2):
    """A pipeline-trained checkpoint: the stage-stacked {embed, blocks,
    head} param layout plus the pipeline parallel_layout stamp — what a
    --pipeline-stages training run publishes."""
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        make_pipeline_template,
    )

    model = get_model("vit", compute_dtype=jnp.float32)
    state = make_pipeline_template(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0,
                    parallel_layout={"pipeline": stages})
    return model, state


def _pipeline_direct_labels(model, state, images):
    from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
        merge_vit_params,
    )

    return np.argmax(np.asarray(model.apply(
        merge_vit_params(state.params),
        jnp.asarray(normalize_images(images)), train=False)), axis=-1)


def test_pipeline_server_loadgen_smoke_expect_stages(tmp_path):
    """The ISSUE 12 acceptance run: a pipeline-trained ViT checkpoint
    boots under ``serve --serve-mode pipeline`` (2 per-chip stage
    programs), answers /predict with predictions pinned to the
    single-device forward, /stats carries pipeline_stages, and loadgen's
    ``--smoke --expect-mode pipeline --expect-stages 2`` gate passes
    with zero steady-state recompiles per bucket x stage."""
    ckpt = tmp_path / "ckpt"
    model, state = _publish_pipeline(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, model="vit", buckets="1,8",
                              serve_devices=2, serve_mode="pipeline",
                              serve_mesh=2))
    try:
        images, _ = synthetic_dataset(5, seed=0)
        reply = srv.post("/predict", {"images": images.tolist()})
        want = _pipeline_direct_labels(model, state, images)
        assert reply["predictions"] == [int(v) for v in want]
        assert reply["model_epoch"] == 0

        stats = srv.get("/stats")
        assert stats["serve_mode"] == "pipeline"
        assert stats["serve_devices"] == 2
        assert stats["mesh_devices"] == 2 and stats["mesh_groups"] == 1
        assert stats["pipeline_stages"] == 2
        row = stats["replicas"]["pipeline"]
        assert row["mode"] == "pipeline" and row["stages"] == 2

        programs = compile_log.stats()["programs"]
        names = {f"serve_forward_b{b}@pipeline.s{k}"
                 for b in (1, 8) for k in (0, 1)}
        assert names <= set(programs)
        before = {n: programs[n]["backend_compiles"] for n in names}
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--smoke", "--url", srv.url, "--requests", "200",
             "--concurrency", "8", "--expect-mode", "pipeline",
             "--expect-stages", "2"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["smoke_ok"] and report["ok"] == 200
        # The loadgen report names WHAT it measured (sourced from /stats).
        assert report["serve_mode"] == "pipeline"
        assert report["pipeline_stages"] == 2
        after = compile_log.stats()["programs"]
        assert {n: after[n]["backend_compiles"] for n in names} == before
    finally:
        srv.close()


def test_pipeline_server_hot_reload_under_traffic(tmp_path):
    """Hot reload on the MPMD plane: a newer pipeline checkpoint
    published under live traffic swaps EVERY stage of the chain
    together; replies after the swap carry the new epoch and its exact
    predictions."""
    ckpt = tmp_path / "ckpt"
    model, _ = _publish_pipeline(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, model="vit", buckets="1,8",
                              serve_devices=2, serve_mode="pipeline",
                              serve_mesh=2))
    try:
        images, _ = synthetic_dataset(6, seed=2)
        srv.post("/predict", {"images": images.tolist()})
        _, new_state = _publish_pipeline(ckpt, epoch=3, seed=77)
        deadline = time.time() + 30
        while time.time() < deadline:
            if srv.get("/healthz")["model_epoch"] == 3:
                break
            srv.post("/predict", {"images": images.tolist()})
            time.sleep(0.05)
        reply = srv.post("/predict", {"images": images.tolist()})
        assert reply["model_epoch"] == 3
        want = _pipeline_direct_labels(model, new_state, images)
        assert reply["predictions"] == [int(v) for v in want]
        assert srv.get("/stats")["reloads"] == 1
    finally:
        srv.close()


def test_pipeline_layout_gate_both_directions(tmp_path):
    """The flipped boot gate: a pipeline-stamped checkpoint under
    replicated serving dies naming --serve-mode pipeline as the valid
    choice, and the SAME checkpoint boots under it. A model WITHOUT a
    pipeline rule table dies with flag language BEFORE the template
    build (the mode's template hook assumes its model family)."""
    ckpt = tmp_path / "ckpt"
    _publish_pipeline(ckpt, epoch=0, seed=3)
    with pytest.raises(SystemExit, match="--serve-mode pipeline"):
        create_server(_serve_args(ckpt, model="vit", buckets="1,8"))
    with pytest.raises(SystemExit, match="no sharding rule table"):
        create_server(_serve_args(ckpt, model="linear", buckets="1,8",
                                  serve_devices=2, serve_mode="pipeline"))
    srv = _Server(_serve_args(ckpt, model="vit", buckets="1,8",
                              serve_devices=2, serve_mode="pipeline"))
    try:
        stats = srv.get("/stats")
        assert stats["serve_mode"] == "pipeline"
        assert stats["pipeline_stages"] == 2
    finally:
        srv.close()
