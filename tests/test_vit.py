"""ViT model family: forward shapes, training step, sequence-parallel swap."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model, list_models
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.ring import ring_attention
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


def test_vit_registered():
    assert "vit" in list_models()


@pytest.mark.parametrize("shape", [(4, 784), (4, 28, 28), (4, 28, 28, 1)])
def test_vit_forward_shapes(shape):
    model = get_model("vit")
    x = jnp.zeros(shape, jnp.float32)
    params = model.init(jax.random.key(0), x)
    out = model.apply(params, x)
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32


def test_vit_trains_single_device():
    """A few steps on a fixed batch must reduce the loss (finite + learning)."""
    model = get_model("vit")
    state = create_train_state(model, jax.random.key(0), lr=1e-3)
    step = make_train_step()
    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }
    losses = []
    for _ in range(10):
        state, m = step(state, batch)
        losses.append(float(m.loss_sum) / float(m.count))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_vit_trains_with_ring_attention():
    """Gradients flow through shard_map+ppermute: a ring-attention ViT train
    step runs and matches the dense-attention step's loss on same params."""
    mesh = make_mesh(("seq",))
    kwargs = dict(patch_size=7, compute_dtype=jnp.float32)
    dense = get_model("vit", **kwargs)
    ring = get_model(
        "vit", attention_fn=partial(ring_attention, mesh=mesh), **kwargs
    )
    rng = np.random.default_rng(1)
    batch = {
        "image": jnp.asarray(rng.normal(size=(8, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(8,)), jnp.int32),
    }
    state_d = create_train_state(dense, jax.random.key(0), lr=1e-3)
    state_r = create_train_state(ring, jax.random.key(0), lr=1e-3)
    step = make_train_step()
    for _ in range(3):
        state_d, md = step(state_d, batch)
        state_r, mr = step(state_r, batch)
    np.testing.assert_allclose(
        float(mr.loss_sum), float(md.loss_sum), rtol=1e-4
    )
    assert np.isfinite(float(mr.loss_sum))


@pytest.mark.slow
def test_vit_ring_attention_forward_matches_dense():
    """Same params, dense vs ring attention_fn: identical logits."""
    mesh = make_mesh(("seq",))
    # patch 4 -> 49 tokens, not divisible by 8; use patch 7 -> 16 tokens.
    dense = get_model("vit", patch_size=7, compute_dtype=jnp.float32)
    ring = get_model(
        "vit", patch_size=7, compute_dtype=jnp.float32,
        attention_fn=partial(ring_attention, mesh=mesh),
    )
    x = jax.random.normal(jax.random.key(3), (4, 28, 28, 1), jnp.float32)
    params = dense.init(jax.random.key(1), x)
    np.testing.assert_allclose(
        ring.apply(params, x), dense.apply(params, x), rtol=2e-4, atol=2e-4
    )


@pytest.mark.slow
def test_remat_same_params_loss_and_grads():
    """nn.remat(TransformerBlock) must be a pure memory/FLOPs trade:
    identical param structure, identical forward, identical gradients."""
    import numpy as np

    from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy

    k = jax.random.key(0)
    x = jax.random.normal(k, (4, 28, 28, 1), jnp.float32)
    y = jnp.array([1, 2, 3, 4], jnp.int32)

    base = get_model("vit", compute_dtype=jnp.float32)
    rem = get_model("vit", compute_dtype=jnp.float32, remat=True)
    params = base.init(k, x)["params"]
    assert jax.tree_util.tree_structure(
        params) == jax.tree_util.tree_structure(rem.init(k, x)["params"])

    def loss(m, p):
        return cross_entropy(m.apply({"params": p}, x), y)

    l0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(rem, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_remat_cli(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    s = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--remat",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path), "--trainer-mode", "stepwise",
    ]))
    assert s["epochs_run"] == 1


def test_remat_wrong_model_errors(tmp_path):
    import pytest

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    with pytest.raises(SystemExit, match="remat"):
        run(build_parser().parse_args([
            "--dataset", "synthetic", "--model", "cnn", "--remat",
            "--checkpoint-dir", str(tmp_path),
        ]))


@pytest.mark.slow
def test_ulysses_flash_cli(tmp_path):
    """--sequence-parallel-impl ulysses --attention flash end-to-end."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    s = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--patch-size", "7",
        "--sequence-parallel", "2", "--sequence-parallel-impl", "ulysses",
        "--attention", "flash",
        "--batch-size", "32", "--synthetic-train-size", "64",
        "--synthetic-test-size", "32", "--seed", "0", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path), "--trainer-mode", "stepwise",
    ]))
    assert s["epochs_run"] == 1


def test_ring_flash_cli_still_rejected(tmp_path):
    import pytest

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    with pytest.raises(SystemExit, match="ulysses"):
        run(build_parser().parse_args([
            "--dataset", "synthetic", "--model", "vit", "--patch-size", "7",
            "--sequence-parallel", "2", "--attention", "flash",
            "--checkpoint-dir", str(tmp_path),
        ]))


@pytest.mark.slow
def test_tp_flash_cli(tmp_path):
    """--tensor-parallel 2 --attention flash end-to-end (sharded kernel)."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    s = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit",
        "--tensor-parallel", "2", "--attention", "flash",
        "--batch-size", "32", "--synthetic-train-size", "64",
        "--synthetic-test-size", "32", "--seed", "0", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path), "--trainer-mode", "stepwise",
    ]))
    assert s["epochs_run"] == 1
