"""--epoch-gather device (train/steps.py make_*_epoch_indexed): the
dataset stays device-resident and each scan tick gathers its batch with
jnp.take — trajectories must equal the host-gather path exactly; the only
thing that changes is what crosses the host boundary per epoch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer


def _run_cli(tmp_path, tag, extra):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    return run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--dtype", "f32",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "96",  # ragged: 96/8 devices pads eval
        "--seed", "0", "--epochs", "2",
        "--checkpoint-dir", str(tmp_path / tag),
    ] + extra))


def test_device_gather_cli_matches_host_gather(tmp_path):
    host = _run_cli(tmp_path, "h", [])
    dev = _run_cli(tmp_path, "d", ["--epoch-gather", "device"])
    assert dev["history"] == host["history"]  # exact float equality
    assert dev["best_acc"] == host["best_acc"]


def test_device_gather_with_grad_accum_matches(tmp_path):
    host = _run_cli(tmp_path, "ha", ["--grad-accum", "2"])
    dev = _run_cli(tmp_path, "da", ["--grad-accum", "2",
                                    "--epoch-gather", "device"])
    assert dev["history"] == host["history"]


def test_device_gather_eval_counts_each_sample_once():
    """Eval under device mode uses the one-time staged path (the eval set
    never reshuffles; device-gathering it would only replicate the test
    set across HBM) — padding must still count 110 of 110, not 120."""
    rng = np.random.default_rng(0)
    images = rng.normal(size=(110, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(110) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    train = MNISTDataLoader(images, labels, batch_size=20, train=True)
    test = MNISTDataLoader(images, labels, batch_size=20, train=False)
    trainer = Trainer(state, train, test, mode="scan",
                      epoch_gather="device")
    loss, acc = trainer.evaluate()
    assert acc.count == 110
    assert loss.count == 110


def test_device_gather_requires_scan_mode(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    with pytest.raises(SystemExit, match="scan"):
        run(build_parser().parse_args([
            "--dataset", "synthetic", "--model", "linear",
            "--trainer-mode", "stepwise", "--epoch-gather", "device",
            "--checkpoint-dir", str(tmp_path),
        ]))


def test_dataset_uploaded_once():
    """The resident dataset is placed on device exactly once per run."""
    rng = np.random.default_rng(1)
    images = rng.normal(size=(128, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(128) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    train = MNISTDataLoader(images, labels, batch_size=32, train=True)
    test = MNISTDataLoader(images, labels, batch_size=32, train=False)
    trainer = Trainer(state, train, test, mode="scan",
                      epoch_gather="device")
    trainer.train()
    data_id = id(trainer._train_data)
    train.set_sample_epoch(1)
    trainer.train()
    assert id(trainer._train_data) == data_id