"""--epoch-gather device (train/steps.py make_*_epoch_indexed): the
dataset stays device-resident and each scan tick gathers its batch with
jnp.take — trajectories must equal the host-gather path exactly; the only
thing that changes is what crosses the host boundary per epoch.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer


def _run_cli(tmp_path, tag, extra):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    return run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--dtype", "f32",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "96",  # ragged: 96/8 devices pads eval
        "--seed", "0", "--epochs", "2",
        "--checkpoint-dir", str(tmp_path / tag),
    ] + extra))


def test_device_gather_cli_matches_host_gather(tmp_path):
    host = _run_cli(tmp_path, "h", [])
    dev = _run_cli(tmp_path, "d", ["--epoch-gather", "device"])
    assert dev["history"] == host["history"]  # exact float equality
    assert dev["best_acc"] == host["best_acc"]


@pytest.mark.slow
@pytest.mark.parametrize("extra", [
    ("--model", "vit", "--pipeline-stages", "2"),
    ("--model", "vit", "--pipeline-stages", "2", "--tensor-parallel", "2"),
])
def test_device_gather_on_pipeline_meshes_matches(tmp_path, extra):
    """The indexed epoch program composes with the pipeline layouts: the
    resident dataset is replicated over stage/model axes, the tick matrix
    shards on data, and the GPipe (x Megatron) apply runs per tick —
    trajectory equal to the host-gather run.

    Runs in a CHILD process with the persistent compile cache disabled:
    reloading this pair of collective programs (ppermute + all-reduce)
    from the cache trips an XLA:CPU AOT-deserialization deadlock
    ('only 5 of 8 threads arrived' in the collective-permute rendezvous,
    SIGABRT; fresh compiles of the same HLO always pass — observed
    2026-07-30, jaxlib 0.9.0, 8 virtual devices). Fresh-compiling in a
    child keeps the equivalence coverage without importing the bug into
    the suite process.
    """
    import subprocess
    import sys

    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_distributed_mnist_tpu.cli import build_parser, run\n"
        "common = ['--dataset', 'synthetic', '--batch-size', '64',\n"
        "          '--synthetic-train-size', '256',\n"
        "          '--synthetic-test-size', '64', '--seed', '0',\n"
        "          '--epochs', '1'] + %r\n"
        "host = run(build_parser().parse_args(\n"
        "    common + ['--checkpoint-dir', %r]))\n"
        "dev = run(build_parser().parse_args(\n"
        "    common + ['--checkpoint-dir', %r,\n"
        "              '--epoch-gather', 'device']))\n"
        "assert dev['history'] == host['history'], (dev, host)\n"
        "print('EQUAL')\n"
    ) % (list(extra), str(tmp_path / "h"), str(tmp_path / "d"))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        JAX_COMPILATION_CACHE_DIR="",
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=700)
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "EQUAL" in proc.stdout


def test_device_gather_with_grad_accum_matches(tmp_path):
    host = _run_cli(tmp_path, "ha", ["--grad-accum", "2"])
    dev = _run_cli(tmp_path, "da", ["--grad-accum", "2",
                                    "--epoch-gather", "device"])
    assert dev["history"] == host["history"]


def test_device_gather_eval_counts_each_sample_once():
    """Eval under device mode uses the one-time staged path (the eval set
    never reshuffles; device-gathering it would only replicate the test
    set across HBM) — padding must still count 110 of 110, not 120."""
    rng = np.random.default_rng(0)
    images = rng.normal(size=(110, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(110) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    train = MNISTDataLoader(images, labels, batch_size=20, train=True)
    test = MNISTDataLoader(images, labels, batch_size=20, train=False)
    trainer = Trainer(state, train, test, mode="scan",
                      epoch_gather="device")
    loss, acc = trainer.evaluate()
    assert acc.count == 110
    assert loss.count == 110


def test_device_gather_requires_scan_mode(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    with pytest.raises(SystemExit, match="scan"):
        run(build_parser().parse_args([
            "--dataset", "synthetic", "--model", "linear",
            "--trainer-mode", "stepwise", "--epoch-gather", "device",
            "--checkpoint-dir", str(tmp_path),
        ]))


def test_dataset_uploaded_once():
    """The resident dataset is placed on device exactly once per run."""
    rng = np.random.default_rng(1)
    images = rng.normal(size=(128, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(128) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    train = MNISTDataLoader(images, labels, batch_size=32, train=True)
    test = MNISTDataLoader(images, labels, batch_size=32, train=False)
    trainer = Trainer(state, train, test, mode="scan",
                      epoch_gather="device")
    trainer.train()
    data_id = id(trainer._train_data)
    train.set_sample_epoch(1)
    trainer.train()
    assert id(trainer._train_data) == data_id