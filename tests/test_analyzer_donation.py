"""Fixture suite: the donated-reuse checker.

Pins the PR 7 carry hazard: ``donate_argnums`` lets XLA update buffers
in place, which makes the caller's reference a dangling handle — any
read of the donated argument after the call (or a loop that re-donates
without rebinding) touches freed memory.
"""

import os
import pathlib

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src, filename="snippet.py"):
    return analyze_snippet(src, checkers=["donated-reuse"],
                           filename=filename)


# -- firing ------------------------------------------------------------------


def test_fires_on_read_after_donating_call():
    src = """
import jax

def train(state, batch):
    step = jax.jit(update, donate_argnums=(0,))
    new_state = step(state, batch)
    loss = metrics(state)
    return new_state, loss
"""
    (f,) = _findings(src)
    assert "'state'" in f.message and "PR 7" in f.message
    assert f.line == 7  # the read, not the call


def test_fires_on_loop_that_never_rebinds_the_carry():
    src = """
import jax

def train(state, batches):
    step = jax.jit(update, donate_argnums=(0,))
    for batch in batches:
        out = step(state, batch)
"""
    (f,) = _findings(src)
    assert "every loop iteration" in f.message


def test_fires_through_a_factory_binding():
    """The make_step idiom: the donating jit lives in a factory the
    index resolves; the caller's binding inherits its positions."""
    src = """
import jax

def make_step(fn):
    return jax.jit(fn, donate_argnums=(0,))

def train(state, batch):
    step = make_step(update)
    new_state = step(state, batch)
    print(state.mean())
"""
    (f,) = _findings(src)
    assert "'state'" in f.message


# -- non-firing --------------------------------------------------------------


def test_clean_on_rebound_carry():
    src = """
import jax

def train(state, batches):
    step = jax.jit(update, donate_argnums=(0,))
    for batch in batches:
        state = step(state, batch)
    return state
"""
    assert _findings(src) == []


def test_clean_without_donation():
    src = """
import jax

def train(state, batch):
    step = jax.jit(update)
    new_state = step(state, batch)
    loss = metrics(state)
    return new_state, loss
"""
    assert _findings(src) == []


def test_clean_when_read_happens_after_rebinding():
    src = """
import jax

def train(state, batch):
    step = jax.jit(update, donate_argnums=(1,))
    state = step(batch, state)
    return metrics(state)
"""
    assert _findings(src) == []


def test_clean_on_nondonated_position():
    src = """
import jax

def train(state, batch):
    step = jax.jit(update, donate_argnums=(0,))
    new_state = step(state, batch)
    stats = summarize(batch)
    return new_state, stats
"""
    assert _findings(src) == []


# -- the real donation sites stay clean --------------------------------------


_SERVE = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / "serve"


def test_real_serve_programs_are_clean():
    for name in ("programs.py", "engine.py"):
        path = _SERVE / name
        assert _findings(path.read_text(), filename=name) == [], name
