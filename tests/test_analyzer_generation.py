"""Fixture suite: the generation-ordering checker.

Pins the PR 4 hot-reload swap (install without re-comparing the epoch
under the lock) and the PR 19 stale-cache-insert (a response computed
against generation G inserted after the bump to G+1) — the same
sentence at two layers: snapshot the counter under the lock, compute
outside, re-compare under the lock immediately before the install.
"""

import os
import pathlib

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src, filename="snippet.py"):
    return analyze_snippet(src, checkers=["generation-ordering"],
                           filename=filename)


# -- firing ------------------------------------------------------------------


def test_fires_on_swap_without_recompare():
    """The PR 4 swap_params shape: caller-snapshotted epoch, install
    under the lock, no compare under the lock."""
    src = """
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._epoch = 0

    def swap_params(self, params, epoch):
        placed = self.place(params)
        with self._lock:
            self._params = placed
            self._epoch = epoch
"""
    (f,) = _findings(src)
    assert f.symbol == "Engine.swap_params"
    assert "self._params" in f.message and "PR 4" in f.message


def test_fires_on_stale_cache_insert():
    """The PR 19 shape: a subscript install into a self container under
    the lock, generation passed in, never re-compared."""
    src = """
import threading

class ResponseCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._generation = 0
        self._entries = {}

    def put(self, key, value, generation):
        with self._lock:
            self._entries[key] = (value, generation)
"""
    (f,) = _findings(src)
    assert f.symbol == "ResponseCache.put"
    assert "self._entries" in f.message


# -- non-firing --------------------------------------------------------------


def test_clean_with_recompare_under_the_lock():
    src = """
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._epoch = 0

    def swap_params(self, params, epoch):
        placed = self.place(params)
        with self._lock:
            if epoch <= self._epoch:
                return
            self._params = placed
            self._epoch = epoch
"""
    assert _findings(src) == []


def test_clean_when_the_compare_lives_in_a_resolvable_callee():
    """Cross-module rule: the engine->pool->watcher fan-outs delegate
    the ordering compare; the index follows the call."""
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._epoch = 0

    def _stale(self, epoch):
        return epoch <= self._epoch

    def install(self, params, epoch):
        with self._lock:
            if self._stale(epoch):
                return
            self._params = params
            self._epoch = epoch
"""
    assert _findings(src) == []


def test_clean_on_generation_producer_without_counter_param():
    """resize/regroup bump the counter themselves — the producer, not a
    stale consumer racing it; no caller-supplied counter, no finding."""
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._generation = 0

    def resize(self, n):
        replicas = self.build(n)
        with self._lock:
            self.replicas = replicas
            self._generation += 1
"""
    assert _findings(src) == []


def test_clean_on_counterless_stats_update():
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._generation = 0

    def note(self, n):
        with self._lock:
            self._count = n
"""
    assert _findings(src) == []


# -- reversion: the real swap path stays pinned ------------------------------


_ENGINE = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / \
    "serve" / "engine.py"


def test_real_engine_swap_is_clean():
    assert _findings(_ENGINE.read_text(), filename="engine.py") == []


def test_removing_the_swap_epoch_compare_fails_the_gate():
    """Delete swap_params' under-lock staleness compare — the exact
    PR 4 bug — and the checker must flag the install with file:line."""
    source = _ENGINE.read_text()
    guard = ("            if (epoch is not None and self._params_epoch "
             "is not None\n"
             "                    and epoch < self._params_epoch):\n"
             "                return False  # a newer checkpoint "
             "already installed\n")
    assert guard in source, (
        "engine.py swap_params no longer carries the epoch guard this "
        "test re-narrows — evolve the fixture with the code")
    broken = source.replace(guard, "", 1)
    findings = _findings(broken, filename="engine.py")
    assert findings, "guardless swap install was not flagged"
    f = findings[0]
    assert f.path == "engine.py" and f.line > 0
    assert "swap_params" in f.symbol
