"""Data pipeline: IDX round-trip, synthetic determinism, normalize transform,
loader sharding/batching (reference ``:129-161``)."""

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
from pytorch_distributed_mnist_tpu.data.mnist import (
    MNIST_MEAN,
    MNIST_STD,
    load_dataset,
    normalize_images,
    parse_idx,
    synthetic_dataset,
    write_idx,
)


def test_idx_round_trip(tmp_path):
    arr = np.arange(2 * 28 * 28, dtype=np.uint8).reshape(2, 28, 28)
    p = str(tmp_path / "imgs-idx3-ubyte")
    write_idx(p, arr)
    np.testing.assert_array_equal(parse_idx(p), arr)


def test_idx_gzip(tmp_path):
    import gzip

    arr = np.arange(100, dtype=np.uint8)
    raw = str(tmp_path / "x-idx1-ubyte")
    write_idx(raw, arr)
    gz = raw + ".gz"
    with open(raw, "rb") as f, gzip.open(gz, "wb") as g:
        g.write(f.read())
    np.testing.assert_array_equal(parse_idx(gz), arr)


def test_idx_rejects_garbage(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\xff\xff\xff\xff garbage")
    with pytest.raises(ValueError, match="not an IDX file"):
        parse_idx(p)


def test_synthetic_deterministic_and_shaped():
    a_imgs, a_lbls = synthetic_dataset(64, seed=7)
    b_imgs, b_lbls = synthetic_dataset(64, seed=7)
    np.testing.assert_array_equal(a_imgs, b_imgs)
    np.testing.assert_array_equal(a_lbls, b_lbls)
    assert a_imgs.shape == (64, 28, 28) and a_imgs.dtype == np.uint8
    assert set(np.unique(a_lbls)) <= set(range(10))
    c_imgs, _ = synthetic_dataset(64, seed=8)
    assert not np.array_equal(a_imgs, c_imgs)


def test_load_dataset_prefers_real_idx(tmp_path):
    imgs = np.full((10, 28, 28), 7, np.uint8)
    lbls = np.arange(10, dtype=np.uint8) % 10
    d = tmp_path / "mnist"
    d.mkdir()
    write_idx(str(d / "train-images-idx3-ubyte"), imgs)
    write_idx(str(d / "train-labels-idx1-ubyte"), lbls)
    got_imgs, got_lbls = load_dataset(str(tmp_path), "mnist", train=True)
    np.testing.assert_array_equal(got_imgs, imgs)
    np.testing.assert_array_equal(got_lbls, lbls)


def test_load_dataset_missing_raises_when_no_fallback(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_dataset(str(tmp_path), "mnist", train=True, synthesize_if_missing=False)


def test_load_dataset_train_test_disjoint_seeds(tmp_path):
    tr, _ = load_dataset(str(tmp_path), train=True, synthetic_train_size=32)
    te, _ = load_dataset(str(tmp_path), train=False, synthetic_test_size=32)
    assert not np.array_equal(tr[:32], te[:32])


def test_normalize_parity_with_reference_transform():
    imgs = np.zeros((2, 28, 28), np.uint8)
    imgs[0, 0, 0] = 255
    x = normalize_images(imgs)
    assert x.shape == (2, 28, 28, 1) and x.dtype == np.float32
    np.testing.assert_allclose(x[1, 0, 0, 0], (0.0 - MNIST_MEAN) / MNIST_STD, rtol=1e-6)
    np.testing.assert_allclose(x[0, 0, 0, 0], (1.0 - MNIST_MEAN) / MNIST_STD, rtol=1e-6)


def _loader(n=100, bs=20, **kw):
    images = np.arange(n, dtype=np.float32)[:, None, None, None] * np.ones((1, 28, 28, 1), np.float32)
    labels = np.arange(n) % 10
    return MNISTDataLoader(images, labels, batch_size=bs, **kw)


def test_loader_batches_cover_shard():
    loader = _loader(n=100, bs=20, train=True)
    batches = list(loader)
    assert len(batches) == 5 == len(loader)
    seen = np.concatenate([b["image"][:, 0, 0, 0].astype(int) for b in batches])
    assert sorted(seen.tolist()) == list(range(100))


def test_loader_train_drops_ragged_batch():
    loader = _loader(n=110, bs=20, train=True)
    assert loader.steps_per_epoch == 5  # 110 // 20, ragged 10 dropped


def test_loader_eval_pads_ragged_batch():
    loader = _loader(n=110, bs=20, train=False)
    assert loader.steps_per_epoch == 6  # ceil: every sample evaluated


def test_loader_global_batch_split_across_processes():
    l0 = _loader(n=64, bs=16, train=True, num_replicas=4, rank=0)
    assert l0.local_batch_size == 4
    shards = []
    for r in range(4):
        lr_ = _loader(n=64, bs=16, train=True, num_replicas=4, rank=r)
        lr_.set_sample_epoch(3)
        shards.append(np.concatenate([b["image"][:, 0, 0, 0].astype(int) for b in lr_]))
    allidx = np.concatenate(shards)
    assert sorted(allidx.tolist()) == list(range(64))  # joint exact cover


def test_loader_epoch_reshuffle():
    loader = _loader(n=100, bs=20, train=True)
    loader.set_sample_epoch(0)
    e0 = np.concatenate([b["label"] for b in loader])
    loader.set_sample_epoch(1)
    e1 = np.concatenate([b["label"] for b in loader])
    assert not np.array_equal(e0, e1)


def test_loader_eval_not_sharded_by_default():
    # Reference parity: test loader never gets a DistributedSampler (:143-144).
    loader = _loader(n=100, bs=20, train=False, num_replicas=4, rank=2)
    seen = np.concatenate([b["image"][:, 0, 0, 0].astype(int) for b in loader])
    assert sorted(seen.tolist()) == list(range(100))  # full set on every rank


def test_loader_eval_sharded_when_asked():
    shards = []
    for r in range(4):
        loader = _loader(n=100, bs=20, train=False, num_replicas=4, rank=r, shard=True)
        shards.append(np.concatenate([b["image"][:, 0, 0, 0].astype(int) for b in loader]))
    assert len(set(np.concatenate(shards).tolist())) == 100


def test_loader_rejects_indivisible_batch():
    with pytest.raises(ValueError, match="not divisible"):
        _loader(n=64, bs=10, train=True, num_replicas=4, rank=0)


def test_stacked_epoch_shapes():
    loader = _loader(n=100, bs=20, train=True)
    ep = loader.stacked_epoch()
    assert ep["image"].shape == (5, 20, 28, 28, 1)
    assert ep["label"].shape == (5, 20)
