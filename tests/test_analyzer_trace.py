"""Fixture suite: the trace-purity checker.

Traced-function discovery (decorators, the jit/shard_map factory idiom,
the module-local call-graph walk) and each impurity class: host side
effects (print/logging/time/random), tracer concretization
(.item()/float()/np.asarray on traced params), and enclosing-state
mutation (global/nonlocal).
"""


import pytest


from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src):
    return analyze_snippet(src, checkers=["trace-purity"])


# -- firing ------------------------------------------------------------------


def test_fires_on_print_in_jit_factory_product():
    src = """
import jax

def make_step():
    def step(state, batch):
        print("debug", batch)
        return state
    return jax.jit(step, donate_argnums=(0,))
"""
    (f,) = _findings(src)
    assert f.symbol == "step" and "trace time" in f.message


def test_fires_on_item_under_partial_jit_decorator():
    src = """
import functools, jax

@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    loss = batch.item()
    return state, loss
"""
    (f,) = _findings(src)
    assert ".item()" in f.message and "batch" in f.message


def test_fires_through_the_call_graph_walk():
    """An impure helper is caught even though only its caller is jitted
    — the walk follows module-local calls."""
    src = """
import jax, time

def _stamp(x):
    return x + time.time()

def step(state):
    return _stamp(state)

step = jax.jit(step)
"""
    (f,) = _findings(src)
    assert f.symbol == "_stamp" and "time.time" in f.message


def test_fires_on_global_mutation_in_shard_map_body():
    src = """
import functools, jax

@functools.partial(jax.shard_map, mesh=None, in_specs=(), out_specs=())
def body(batch):
    global _seen
    _seen += 1
    return batch
"""
    (f,) = _findings(src)
    assert "global" in f.message


def test_fires_on_python_random_and_np_asarray():
    src = """
import random
import numpy as np
import jax

def step(x):
    noise = random.random()
    host = np.asarray(x)
    return host + noise

step = jax.jit(step)
"""
    messages = " | ".join(f.message for f in _findings(src))
    assert "random" in messages and "np.asarray" in messages


# -- non-firing --------------------------------------------------------------


def test_silent_on_static_param_concretization():
    """float()/branching on a declared-static parameter is trace-time
    resolution — the point of declaring it static."""
    src = """
import functools, jax

@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def kernel(x, scale, interpret=False):
    if interpret:
        return x
    return x * float(scale)
"""
    assert _findings(src) == []


def test_silent_on_jnp_asarray_and_shape_math():
    """The codebase idiom: jnp.asarray stays abstract, and float() on a
    non-parameter expression (static shapes) is fine."""
    src = """
import jax
import jax.numpy as jnp

def step(state, batch):
    n = jnp.asarray(float(batch.shape[0]), jnp.float32)
    return state, n

step = jax.jit(step, donate_argnums=(0,))
"""
    assert _findings(src) == []


def test_silent_on_host_side_code():
    """print/time/.item() in UNtraced functions is ordinary host code."""
    src = """
import time

def train_loop(trainer):
    t0 = time.time()
    loss = trainer.step().item()
    print(f"epoch done in {time.time() - t0:.1f}s, loss {loss}")
"""
    assert _findings(src) == []


def test_silent_on_raise_for_static_shape_validation():
    """Raising on static shape mismatch at trace time is sanctioned
    (the make_accum_train_step_fn idiom)."""
    src = """
import jax

def step(state, batch):
    if batch.shape[0] % 4:
        raise ValueError(f"batch {batch.shape[0]} not divisible by 4")
    return state

step = jax.jit(step)
"""
    assert _findings(src) == []


# -- the shard_map-reduce-scatter shape (ISSUE 7, parallel/zero_overlap.py) --


def test_fires_on_print_in_shard_map_reduce_scatter_body():
    """A debug print inside the overlapped-ZeRO body (discovered through
    the shard_map factory-call idiom zero_overlap.py uses) runs once at
    trace time — and would break the zero-steady-state-recompiles
    contract if ever replaced with a callback."""
    src = """
import jax
from jax import lax

def make_zero_body(mesh, plan):
    def body(state, batch):
        grads = compute_grads(state, batch)
        print("reduce-scattering", len(plan), "buckets")
        return [lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)
                for g in grads]

    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
"""
    (f,) = _findings(src)
    assert f.symbol == "body" and "trace time" in f.message


def test_fires_on_host_timing_in_bucket_chain_helper():
    """An impure helper called from the shard_map'd body is caught by
    the module-local call-graph walk even though only the body is the
    traced root — timing a bucket's reduce-scatter belongs on the host
    around the compiled call, never under trace."""
    src = """
import jax, time
from jax import lax

def _timed_scatter(g):
    t0 = time.perf_counter()
    out = lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)
    record_ms(time.perf_counter() - t0)
    return out

def body(state, grads):
    return [_timed_scatter(g) for g in grads]

step = jax.shard_map(body, mesh=None, in_specs=None, out_specs=None)
"""
    messages = " | ".join(f.message for f in _findings(src))
    assert "perf_counter" in messages


def test_silent_on_clean_barrier_chained_reduce_scatter_body():
    """The sanctioned zero_overlap body: optimization_barrier fences,
    psum_scatter/all_gather collectives, jnp reductions for the chain
    anchors — pure throughout."""
    src = """
import jax
import jax.numpy as jnp
from jax import lax

def make_zero_body(mesh, plan, dims):
    def body(state, grads):
        token = jnp.zeros((), jnp.float32)
        shards = list(grads)
        for bucket in plan:
            fenced = lax.optimization_barrier(
                tuple(shards[i] for i in bucket) + (token,))
            token = fenced[-1]
            for leaf, i in zip(fenced[:-1], bucket):
                shards[i] = lax.psum_scatter(
                    leaf, "data", scatter_dimension=dims[i], tiled=True)
            token = lax.optimization_barrier((token, jnp.sum(shards[bucket[0]])))[0]
        return shards

    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
"""
    assert _findings(src) == []


def test_silent_on_static_bucket_plan_iteration():
    """Iterating a Python-level bucket plan (trace-time unrolling) and
    raising on a static shape mismatch are both sanctioned — the
    zero_overlap build-time validation idiom."""
    src = """
import jax
from jax import lax

def body(state, grads, axis_size=8):
    for g in grads:
        if g.shape[0] % axis_size:
            raise ValueError(f"leaf {g.shape} not divisible by {axis_size}")
    return [lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)
            for g in grads]

step = jax.shard_map(body, mesh=None, in_specs=None, out_specs=None)
"""
    assert _findings(src) == []


# -- the serving-mesh lowering shape (ISSUE 8, serve/programs.py) ------------


def test_fires_on_print_in_mesh_lowered_serve_forward():
    """A debug print inside the registry-built forward (discovered
    through the pjit-with-shardings factory idiom programs.py uses) runs
    at trace time — once per bucket lowering, never per request — so
    it is a lie the moment it ships."""
    src = """
import jax

def build_serve_program(apply_fn, param_shardings, io_sharding):
    def forward(params, images):
        print("serving", images.shape[0], "rows")
        return apply_fn(params, images, train=False)

    return jax.jit(forward, in_shardings=(param_shardings, io_sharding),
                   out_shardings=io_sharding)
"""
    (f,) = _findings(src)
    assert f.symbol == "forward" and "trace time" in f.message


def test_fires_on_host_timing_in_mesh_forward_helper():
    """Timing a mesh group's forward belongs on the host around the
    compiled bucket executable; a helper under the traced root is caught
    by the call-graph walk."""
    src = """
import jax, time

def _traced_span(apply_fn, params, images):
    t0 = time.perf_counter()
    out = apply_fn(params, images, train=False)
    record_ms(time.perf_counter() - t0)
    return out

def build_serve_program(apply_fn, shardings):
    def forward(params, images):
        return _traced_span(apply_fn, params, images)

    return jax.jit(forward, in_shardings=shardings, out_shardings=None)
"""
    messages = " | ".join(f.message for f in _findings(src))
    assert "perf_counter" in messages


def test_silent_on_clean_mesh_placement_forward():
    """The sanctioned programs.py shape: the traced forward is pure; the
    mesh build, sharding derivation, and divisibility validation all run
    at build time on the host, outside any traced root."""
    src = """
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

def build_placement(apply_fn, devices, axis, rules, params):
    mesh = Mesh(devices, (axis,))
    shardings = jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, rules.get(path, P())), params)

    def forward(params_, images):
        return apply_fn(params_, images, train=False)

    return jax.jit(forward, in_shardings=(shardings, NamedSharding(mesh, P())),
                   out_shardings=NamedSharding(mesh, P()))
"""
    assert _findings(src) == []


def test_silent_on_build_time_mesh_validation_raise():
    """Build-time rejection of non-dividing weight dims (host Python
    over static shapes, raising with flag language) is sanctioned — it
    never runs under trace."""
    src = """
import jax

def validate_mode(params, mesh_devices):
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if leaf.shape[0] % mesh_devices:
            raise ValueError(f"{path} dim 0 does not divide {mesh_devices}")

def build(apply_fn, params, mesh_devices, shardings):
    validate_mode(params, mesh_devices)

    def forward(params_, images):
        return apply_fn(params_, images, train=False)

    return jax.jit(forward, in_shardings=shardings, out_shardings=None)
"""
    assert _findings(src) == []


# -- the quantize plane (ISSUE 14) -------------------------------------------


def test_fires_on_host_concretization_on_the_quant_path():
    """Dequantization inside a jitted forward must be jnp ops on the
    tracer: pulling the scale out with .item()/float() concretizes a
    traced param (and would silently bake one publish's scale into the
    program)."""
    src = """
import jax

def make_quant_forward(forward):
    def quant_forward(qparams, x):
        scale = qparams.item()
        return forward(qparams * scale, x)
    return jax.jit(quant_forward)
"""
    (f,) = _findings(src)
    assert f.symbol == "quant_forward" and ".item()" in f.message


def test_silent_on_jnp_dequant_inside_jitted_forward():
    """The shipped shape: dequant is pure jnp arithmetic on the traced
    quantized leaves (astype + multiply), trace-clean."""
    src = """
import jax
import jax.numpy as jnp

def make_quant_forward(forward):
    def quant_forward(q, s, x):
        params = q.astype(jnp.float32) * s
        return forward(params, x)
    return jax.jit(quant_forward)
"""
    assert _findings(src) == []


# -- the whole-program plane (ISSUE 16) --------------------------------------


def test_fires_on_host_preprocess_inside_fused_program():
    """The fused raw->logits program's whole point is moving normalize
    INTO XLA; an np.asarray on the traced raw batch concretizes the
    tracer (and silently hands the 'fused' preprocessing back to the
    host, unfusing the program while keeping the name)."""
    src = """
import jax
import numpy as np

def wrap_fused_forward(forward):
    def fused(params, raw):
        x = np.asarray(raw, dtype=np.float32) / 255.0
        return forward(params, x)
    return jax.jit(fused, donate_argnums=(1,))
"""
    (f,) = _findings(src)
    assert f.symbol == "fused" and "np.asarray" in f.message


def test_silent_on_in_xla_normalize_inside_fused_program():
    """The shipped fused shape: jnp arithmetic on the traced raw batch
    with the normalize constants hidden behind an optimization barrier
    (so constant folding can't perturb the bitwise split-path contract)
    — trace-clean, donation and all."""
    src = """
import jax
import jax.numpy as jnp
from jax import lax

def wrap_fused_forward(forward):
    def fused(params, raw):
        mean, std = lax.optimization_barrier(
            (jnp.float32(0.1307), jnp.float32(0.3081)))
        x = (raw.astype(jnp.float32) / jnp.float32(255.0) - mean) / std
        return forward(params, x[..., None])
    return jax.jit(fused, donate_argnums=(1,))
"""
    assert _findings(src) == []
