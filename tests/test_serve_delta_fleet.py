"""Delta distribution over real loopback HTTP (ISSUE 18): the gossip
endpoint, dynamic fleet membership, and a manifest rolling deploy —
in-process ThreadingHTTPServers, same harness as the fleet acceptance
twin (tests/test_serve_router_fleet.py).

Pins:
- a backend boots straight from a delta-published directory (manifest
  + chunk store, no npz anywhere);
- ``GET /chunks/<hash>`` serves immutable chunk bytes (content-typed,
  404 on absence/malformed digests) and ``fetch_chunk_http`` reads it —
  the two halves of the gossip plane meeting over a real socket;
- ``--register-dir`` records follow the backend lifecycle (boot
  registers, drain un-registers, undrain re-registers, shutdown
  removes) and a ``--backends-dir`` router's membership tracks them
  with no restart;
- ``POST /rollout`` with a manifest source: the router ships a few-KB
  manifest per backend, every fetcher pulls the chunks from
  ``--chunk-source`` staging, and the whole fleet converges on the new
  epoch.

The in-process unit halves (ChunkStore semantics, DeltaFetcher diff /
requantize / taxonomy, HealthPoller.sync_backends_dir as pure state)
live in tests/test_distrib_delta.py; the subprocess twins in
tools/chaos.py --torn-manifest and --fleet --delta-publish E.
"""

import os
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_mnist_tpu.distrib.cas import (
    ChunkStore,
    read_manifest,
)
from pytorch_distributed_mnist_tpu.distrib.fetch import fetch_chunk_http
from pytorch_distributed_mnist_tpu.distrib.publish import publish_state
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.router import create_router
from pytorch_distributed_mnist_tpu.serve.router import (
    build_parser as router_parser,
)
from pytorch_distributed_mnist_tpu.serve.server import (
    build_parser,
    create_server,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from tests.test_serve_router_fleet import _Server, _wait

pytestmark = [pytest.mark.serve, pytest.mark.fleet, pytest.mark.distrib]


def _delta_publish(ckpt_dir, epoch, seed, shift=0.0):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    if shift:
        state = state.replace(params=jax.tree_util.tree_map(
            lambda leaf: leaf + shift, state.params))
    publish_state(state, epoch=epoch, best_acc=0.5,
                  directory=str(ckpt_dir), process_index=0)
    return state


def _boot_backend(ckpt_dir, *extra):
    args = build_parser().parse_args([
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8",
        "--max-wait-ms", "2", "--max-queue", "256",
        "--poll-interval", "0.1",
        *extra,
    ])
    return _Server(create_server(args))


def _boot_router(argv):
    base = ["--host", "127.0.0.1", "--port", "0",
            "--health-interval", "0.1",
            "--quarantine-after", "2",
            "--probation-successes", "1",
            "--connect-timeout", "2.0"]
    return _Server(create_router(router_parser().parse_args(base + argv)))


def _healthz(router):
    """Router /healthz, tolerating the empty-fleet 503 (a discovery
    router starts with zero members — that reply is still JSON)."""
    import json as _json

    try:
        return router.get("/healthz")
    except urllib.error.HTTPError as exc:
        return _json.load(exc)


def _record_urls(register_dir):
    import json as _json

    urls = []
    for name in sorted(os.listdir(register_dir)):
        if name.startswith("backend_") and name.endswith(".json"):
            with open(os.path.join(register_dir, name)) as f:
                urls.append(_json.load(f)["url"])
    return urls


def test_boot_from_manifest_and_chunk_gossip_endpoint(tmp_path):
    """A backend whose checkpoint dir holds only a manifest + chunks
    boots serving that epoch, and its /chunks route feeds
    fetch_chunk_http the exact stored bytes."""
    ckpt = tmp_path / "ckpt"
    _delta_publish(ckpt, epoch=1, seed=10)
    assert not any(p.endswith(".npz") for p in os.listdir(str(ckpt)))
    backend = _boot_backend(ckpt)
    try:
        health = backend.get("/healthz")
        assert health["model_epoch"] == 1
        store = ChunkStore(str(ckpt))
        manifest = read_manifest(str(ckpt / "checkpoint_1.manifest"))
        for rec in manifest["leaves"][:2]:
            digest = rec["chunks"][0]
            data = fetch_chunk_http(backend.url, digest)
            assert data == store.get(digest)
        # Absent and malformed digests 404 — never a hang or a 500.
        for bogus in ("0" * 64, "nothex"):
            with pytest.raises(urllib.error.HTTPError) as err:
                fetch_chunk_http(backend.url, bogus)
            assert err.value.code == 404
    finally:
        backend.close()


def test_register_record_follows_lifecycle(tmp_path):
    ckpt, reg = tmp_path / "ckpt", tmp_path / "fleet"
    _delta_publish(ckpt, epoch=0, seed=10)
    backend = _boot_backend(ckpt, "--register-dir", str(reg))
    try:
        assert _record_urls(str(reg)) == [backend.url]
        backend.post("/drain", {"drain": True})
        assert _record_urls(str(reg)) == []
        backend.post("/drain", {"drain": False})
        assert _record_urls(str(reg)) == [backend.url]
    finally:
        backend.close()
    # Shutdown removes the record even without a preceding drain.
    assert _record_urls(str(reg)) == []


def test_router_membership_tracks_backends_dir(tmp_path):
    """A --backends-dir router with NO static --backends: membership
    grows when a backend registers, shrinks when it drains (the record
    removal IS the leave signal), and recovers on undrain."""
    reg = tmp_path / "fleet"
    backends = []
    for i in range(2):
        ckpt = tmp_path / f"b{i}"
        _delta_publish(ckpt, epoch=0, seed=10)
        backends.append(
            _boot_backend(ckpt, "--register-dir", str(reg)))
    router = _boot_router(["--backends-dir", str(reg)])
    try:
        _wait(lambda: _healthz(router)["routable"] == 2,
              what="both registered backends routable")
        backends[1].post("/drain", {"drain": True})
        _wait(lambda: _healthz(router)["routable"] == 1,
              what="drained backend reaped from the fleet")
        assert _healthz(router)["total"] == 1
        backends[1].post("/drain", {"drain": False})
        _wait(lambda: _healthz(router)["routable"] == 2,
              what="undrained backend re-admitted")
        # Late join: a third backend registers after the router booted.
        ckpt = tmp_path / "b2"
        _delta_publish(ckpt, epoch=0, seed=10)
        backends.append(
            _boot_backend(ckpt, "--register-dir", str(reg)))
        _wait(lambda: _healthz(router)["routable"] == 3,
              what="late-joining backend discovered")
    finally:
        router.close()
        for b in backends:
            b.close()


def test_manifest_rollout_converges_fleet(tmp_path):
    """POST /rollout with a manifest source: each backend receives the
    few-KB manifest (epoch-rewritten by the router), pulls only the
    chunks it lacks from --chunk-source staging, and the whole fleet
    lands on the new epoch with every backend answering throughout."""
    staging = tmp_path / "staging"
    _delta_publish(staging, epoch=0, seed=10)
    backends, dirs = [], []
    for i in range(3):
        ckpt = tmp_path / f"b{i}"
        _delta_publish(ckpt, epoch=0, seed=10)
        dirs.append(ckpt)
        backends.append(_boot_backend(
            ckpt, "--chunk-source", str(staging)))
    router = _boot_router(
        ["--backends", ",".join(b.name for b in backends)])
    try:
        _wait(lambda: router.get("/healthz")["routable"] == 3,
              what="all 3 backends healthy")
        _delta_publish(staging, epoch=1, seed=10, shift=1e-3)
        source = str(staging / "checkpoint_1.manifest")
        result = router.post("/rollout", {"source": source})
        assert result["ok"], result
        assert sorted(result["updated"]) == sorted(
            b.name for b in backends)
        assert result["target_epoch"] == 1
        for b, d in zip(backends, dirs):
            health = b.get("/healthz")
            assert health["model_epoch"] == 1
            assert health["draining"] is False
            # The router shipped a manifest, not a whole file — and the
            # fetcher installed the chunks into the backend's own store
            # (it is now a seeder for this epoch's bytes).
            assert os.path.isfile(str(d / "checkpoint_1.manifest"))
            assert not os.path.exists(str(d / "checkpoint_1.npz"))
    finally:
        router.close()
        for b in backends:
            b.close()
