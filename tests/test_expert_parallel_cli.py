"""--expert-parallel from the CLI: the EP analog of the TP/SP CLI tests.

Expert parallelism existed as a library capability (parallel/expert.py +
moe_dispatch, dryrun phase 3, tests/test_moe_pipeline.py); these tests pin
the CLI surface added in round 3: a ``data x expert`` mesh from one flag,
EP rule-table state sharding through the standard driver, capacity
dispatch with the mesh threaded into the model, ZeRO-1 composition, and
flag-level rejection of the ViT-family parallelism combinations.

Equivalence logic mirrors tests/test_tensor_parallel.py: EP is a layout
change, not a math change, so the EP run must match the plain-DP run's
trajectory (dense dispatch is algebraically layout-exact; router math is
pinned to f32 for exactly this reason, models/moe.py).
"""

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.cli import build_parser, run


def _base(tmp_path, *extra):
    return [
        "--dataset", "synthetic", "--model", "moe_mlp", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--root", str(tmp_path / "data"), *extra,
    ]


def test_cli_expert_parallel_matches_dp(tmp_path):
    # Unmarked deliberately (unlike the ViT TP/SP analogs, which are
    # slow): the MoE runs are 256 samples on a small MLP, ~5s for both
    # including compiles, and the fast tier keeps one end-to-end EP
    # equivalence this way.
    ep = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "4",
        "--checkpoint-dir", str(tmp_path / "ckpt_ep"))))
    dp = run(build_parser().parse_args(_base(
        tmp_path, "--checkpoint-dir", str(tmp_path / "ckpt_dp"))))
    assert ep["history"][0]["train_loss"] == pytest.approx(
        dp["history"][0]["train_loss"], rel=1e-4)
    assert ep["history"][0]["test_acc"] == pytest.approx(
        dp["history"][0]["test_acc"], abs=1e-6)


@pytest.mark.slow
def test_cli_expert_parallel_capacity_dispatch(tmp_path):
    """EP x capacity dispatch end to end: the model's all_to_all dispatch
    shard_map runs inside the jitted driver step on the data x expert
    mesh. With a generous capacity factor nothing drops, so the
    trajectory matches dense dispatch (the library-level guarantee,
    tests/test_moe_dispatch.py, here through the CLI)."""
    cap = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2", "--moe-dispatch", "capacity",
        "--checkpoint-dir", str(tmp_path / "ckpt_cap"))))
    dense = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt_dense"))))
    assert np.isfinite(cap["history"][0]["train_loss"])
    assert cap["history"][0]["train_loss"] == pytest.approx(
        dense["history"][0]["train_loss"], rel=0.05)


@pytest.mark.slow
def test_cli_expert_parallel_composes_with_zero1(tmp_path):
    summary = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2",
        "--optimizer-sharding", "zero1",
        "--checkpoint-dir", str(tmp_path / "ckpt"))))
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


@pytest.mark.slow
def test_cli_expert_parallel_composes_with_grad_accum_and_fused_loss(tmp_path):
    """EP x --grad-accum x --loss fused in one run: the micro-batch scan
    accumulates over the data x expert mesh and the Pallas loss kernel's
    nested shard_map (P('data') in_specs, expert-replicated logits) embeds
    in the same GSPMD program. Matches the plain EP run's trajectory
    (grad-accum applies the exact full-batch gradient; the fused loss is
    oracle-equal)."""
    combo = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2", "--grad-accum", "2",
        "--loss", "fused",
        "--checkpoint-dir", str(tmp_path / "ckpt_combo"))))
    plain = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt_plain"))))
    assert combo["history"][0]["train_loss"] == pytest.approx(
        plain["history"][0]["train_loss"], rel=1e-4)
    assert combo["history"][0]["test_acc"] == pytest.approx(
        plain["history"][0]["test_acc"], abs=1e-6)


def test_aux_weight_gradient_flows_metrics_stay_ce():
    """--moe-aux-weight changes the OBJECTIVE (router load-balance term
    added, so router gradients differ) but not the REPORTED loss (metrics
    are pure cross-entropy for reference parity, train/steps.py)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    rng = np.random.default_rng(5)
    batch = {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }
    model = get_model("moe_mlp")
    # Two identical states: the jitted steps donate their input state.
    s_a = create_train_state(model, jax.random.key(0))
    s_b = create_train_state(model, jax.random.key(0))
    s0, m0 = make_train_step()(s_a, batch)
    sw, mw = make_train_step(aux_weight=0.1)(s_b, batch)
    # identical reported CE
    assert float(m0.loss_sum) == pytest.approx(float(mw.loss_sum), rel=1e-6)
    # but the aux gradient flowed into the router
    r0 = np.asarray(s0.params["params"]["moe"]["router"]["kernel"])
    rw = np.asarray(sw.params["params"]["moe"]["router"]["kernel"])
    assert not np.allclose(r0, rw, atol=1e-9)
    # The HEAD has no aux path (aux = f(router probs), upstream of it):
    # from identical initial Adam moments, the first step must move the
    # head identically. (The embed is NOT aux-free — it feeds the router.)
    h0 = np.asarray(s0.params["params"]["head"]["kernel"])
    hw = np.asarray(sw.params["params"]["head"]["kernel"])
    np.testing.assert_allclose(h0, hw, atol=1e-6)


def test_aux_weight_rejects_non_aux_intermediates():
    """Only 'aux_loss'-named sows may join the objective: a diagnostic
    sow must raise at trace time, not silently enter the loss."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    class Sneaky(nn.Module):
        @nn.compact
        def __call__(self, x, *, train=False):
            x = x.reshape((x.shape[0], -1))
            y = nn.Dense(10)(x)
            self.sow("intermediates", "expert_load", jnp.mean(y))
            return y

    state = create_train_state(Sneaky(), jax.random.key(0))
    batch = {
        "image": jnp.zeros((8, 28, 28, 1), jnp.float32),
        "label": jnp.zeros((8,), jnp.int32),
    }
    with pytest.raises(ValueError, match="non-aux_loss intermediate"):
        make_train_step(aux_weight=0.1)(state, batch)


@pytest.mark.slow
def test_cli_moe_aux_weight_end_to_end(tmp_path):
    summary = run(build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2", "--moe-aux-weight", "0.01",
        "--grad-accum", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"))))
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


def test_cli_moe_aux_weight_rejects_non_moe(tmp_path):
    args = build_parser().parse_args(_base(
        tmp_path, "--moe-aux-weight", "0.01", "--model", "cnn",
        "--checkpoint-dir", str(tmp_path / "ckpt")))
    with pytest.raises(SystemExit, match="applies to --model moe_mlp"):
        run(args)


def test_cli_expert_parallel_rejects_non_moe(tmp_path):
    # argparse last-wins: --model cnn overrides _base's moe_mlp.
    args = build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2", "--model", "cnn",
        "--checkpoint-dir", str(tmp_path / "ckpt")))
    with pytest.raises(SystemExit, match="requires --model moe_mlp"):
        run(args)


def test_cli_expert_parallel_rejects_vit_family_combos(tmp_path):
    args = build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "2", "--tensor-parallel", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt")))
    with pytest.raises(SystemExit, match="does not combine"):
        run(args)


def test_cli_rule_table_parallelism_rejects_zero3(tmp_path):
    """EP/TP/SP x zero3 is marked unsupported in the README matrix;
    the CLI must reject it at flag level, not run an untested layout.
    argparse last-wins lets the extras override _base's model."""
    for extra in (["--expert-parallel", "2"],
                  ["--model", "vit", "--tensor-parallel", "2"]):
        args = build_parser().parse_args(_base(
            tmp_path, "--optimizer-sharding", "zero3",
            "--checkpoint-dir", str(tmp_path / "ckpt"), *extra))
        with pytest.raises(SystemExit, match="zero3 composes with data"):
            run(args)


def test_cli_expert_parallel_rejects_indivisible_experts(tmp_path):
    # default moe_mlp has 8 experts; 3 does not divide them.
    args = build_parser().parse_args(_base(
        tmp_path, "--expert-parallel", "3",
        "--checkpoint-dir", str(tmp_path / "ckpt")))
    with pytest.raises(SystemExit, match="must divide"):
        run(args)
