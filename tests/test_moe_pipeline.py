"""Expert parallelism (SwitchMoE) and pipeline parallelism on the 8-dev mesh.

Property under test, same as DP/TP: changing the layout must not change the
math — EP-sharded and pipelined programs reproduce their single-device
references.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.expert import moe_ep_rules
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.pipeline import (
    pipeline_apply,
    sequential_apply,
    stack_stage_params,
)
from pytorch_distributed_mnist_tpu.parallel.tensor import (
    shard_state,
    state_shardings,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(11)
    return {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }


# ------------------------------------------------------------ expert parallel

def test_moe_registered_and_trains(batch):
    model = get_model("moe_mlp")
    state = create_train_state(model, jax.random.key(0))
    step = make_train_step()
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m.loss_sum) / float(m.count))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_ep_rules_shard_expert_dims():
    mesh = make_mesh(("data", "expert"), shape=(2, 4))
    state = create_train_state(get_model("moe_mlp"), jax.random.key(0))
    sh = state_shardings(state, mesh, moe_ep_rules())
    assert sh.params["params"]["moe"]["w1"].spec == P("expert", None, None)
    assert sh.params["params"]["moe"]["router"]["kernel"].spec == P()
    mu_w2 = sh.opt_state.inner_state[0].mu["params"]["moe"]["w2"]
    assert mu_w2.spec == P("expert", None, None)


def test_ep_step_equals_single_device_step(batch):
    """DP(2) x EP(4) step == single-device step (routing included)."""
    model = get_model("moe_mlp")
    s1 = create_train_state(model, jax.random.key(0), optimizer="sgd")
    s2 = create_train_state(model, jax.random.key(0), optimizer="sgd")
    mesh = make_mesh(("data", "expert"), shape=(2, 4))
    rules = moe_ep_rules()
    s2, s2_sharding = shard_state(s2, mesh, rules)
    step1 = make_train_step()
    step2 = make_train_step(mesh, state_sharding=s2_sharding)
    for _ in range(3):
        s1, m1 = step1(s1, batch)
        s2, m2 = step2(s2, batch)
    np.testing.assert_allclose(float(m2.loss_sum), float(m1.loss_sum), rtol=1e-5)
    assert int(m2.correct) == int(m1.correct)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(jax.device_get(s2.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------- pipeline

def _mlp_stage(p, h):
    return jax.nn.relu(h @ p["w"] + p["b"])


def _make_stages(s, f, key):
    ks = jax.random.split(key, s)
    return stack_stage_params([
        {"w": jax.random.normal(k, (f, f)) * (1.0 / np.sqrt(f)),
         "b": jnp.zeros((f,))}
        for k in ks
    ])


@pytest.mark.parametrize("microbatches", [4, 8])
def test_pipeline_matches_sequential(microbatches):
    mesh = make_mesh(("stage",), devices=jax.devices()[:4])
    params = _make_stages(4, 32, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (16, 32))
    got = jax.jit(
        lambda p, x: pipeline_apply(
            _mlp_stage, p, x, mesh=mesh, num_microbatches=microbatches
        )
    )(params, x)
    want = sequential_apply(_mlp_stage, params, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_grads_match_sequential():
    """Backprop through scan+ppermute == backprop through the plain chain."""
    mesh = make_mesh(("stage",), devices=jax.devices()[:4])
    params = _make_stages(4, 16, jax.random.key(2))
    x = jax.random.normal(jax.random.key(3), (8, 16))

    def loss_pipe(p):
        return jnp.sum(pipeline_apply(_mlp_stage, p, x, mesh=mesh) ** 2)

    def loss_seq(p):
        return jnp.sum(sequential_apply(_mlp_stage, p, x) ** 2)

    gp = jax.grad(loss_pipe)(params)
    gs = jax.grad(loss_seq)(params)
    for a, b in zip(jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_rejects_bad_microbatch():
    mesh = make_mesh(("stage",), devices=jax.devices()[:4])
    params = _make_stages(4, 8, jax.random.key(4))
    x = jnp.zeros((10, 8))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(_mlp_stage, params, x, mesh=mesh, num_microbatches=4)
