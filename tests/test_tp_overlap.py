"""TP collective-matmul overlap (``--tp-overlap``).

The overlapped schedule (``parallel/tensor.py::allgather_matmul`` + the
sequence-sharded Megatron-SP block) is a SCHEDULING rewrite of the GSPMD
tensor-parallel path, not a math change: the gather decomposes into ring
ppermute hops and the matmul into independent row-block steps. These
tests pin that contract — the per-shard decomposition bitwise-equal to
gather-then-matmul, the overlapped apply equal to the dense model, and
the train trajectory equal to the single-device step at the same
tolerances the plain-TP suite uses (tests/test_tensor_parallel.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.pipeline_tp import (
    merge_vit_params_tp,
)
from pytorch_distributed_mnist_tpu.parallel.tensor import (
    allgather_matmul,
    create_overlap_tp_vit_state,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(11)
    return {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }


def _f32_vit():
    # patch 7 -> 16 tokens, divisible by tp=2 (the sequence shard).
    return get_model("vit", compute_dtype=jnp.float32, patch_size=7)


def test_allgather_matmul_bitwise_equals_gather_then_matmul():
    """Row blocks of a matmul are independent: the per-shard overlapped
    decomposition must be BITWISE equal to allgather-then-matmul."""
    mesh = make_mesh(("data", "model"), shape=(2, 4))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 16, 12)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(12, 5)), jnp.float32)

    def ref(xs, ww):
        full = lax.all_gather(xs, "model", axis=1, tiled=True)
        return jnp.tensordot(full, ww, axes=([2], [0]))

    def ovl(xs, ww):
        return allgather_matmul(xs, ww, "model")

    specs = dict(in_specs=(P(None, "model", None), P()), out_specs=P(),
                 check_vma=False)
    r = jax.jit(jax.shard_map(ref, mesh=mesh, **specs))(x, w)
    o = jax.jit(jax.shard_map(ovl, mesh=mesh, **specs))(x, w)
    np.testing.assert_array_equal(np.asarray(r), np.asarray(o))


def test_allgather_matmul_gradients_match(batch):
    """Grad wrt the weight sums per-chunk contributions (the gather's
    transpose), so it matches the reference up to reduction order."""
    mesh = make_mesh(("data", "model"), shape=(2, 4))
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 16, 12)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(12, 5)), jnp.float32)

    def make_loss(fn):
        specs = dict(in_specs=(P(None, "model", None), P()), out_specs=P(),
                     check_vma=False)
        sharded = jax.jit(jax.shard_map(fn, mesh=mesh, **specs))
        return lambda ww: jnp.sum(sharded(x, ww) ** 2)

    def ref(xs, ww):
        full = lax.all_gather(xs, "model", axis=1, tiled=True)
        return jnp.tensordot(full, ww, axes=([2], [0]))

    def ovl(xs, ww):
        return allgather_matmul(xs, ww, "model")

    gr = jax.grad(make_loss(ref))(w)
    go = jax.grad(make_loss(ovl))(w)
    np.testing.assert_allclose(np.asarray(go), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


def test_overlap_apply_matches_dense_model(batch):
    """Same init key -> the head-major overlapped apply reproduces the
    dense model's logits (f32; psum_scatter reassociation only)."""
    model = _f32_vit()
    mesh = make_mesh(("data", "model"), shape=(4, 2))
    ostate, _ = create_overlap_tp_vit_state(
        model, jax.random.key(0), mesh, optimizer="sgd")
    dstate = create_train_state(model, jax.random.key(0), optimizer="sgd")

    ld = dstate.apply_fn(dstate.params, batch["image"], train=False)
    lo = ostate.apply_fn(ostate.params, batch["image"], train=False)
    np.testing.assert_allclose(np.asarray(lo), np.asarray(ld),
                               rtol=1e-5, atol=1e-5)


def test_overlap_tp_step_equals_single_device_step(batch):
    """DP(4) x TP(2) overlapped train step == single-device step over a
    3-step trajectory (SGD; same conventions as the plain-TP test)."""
    model = _f32_vit()
    s1 = create_train_state(model, jax.random.key(0), optimizer="sgd")
    mesh = make_mesh(("data", "model"), shape=(4, 2))
    so, osh = create_overlap_tp_vit_state(
        model, jax.random.key(0), mesh, optimizer="sgd")

    step_1d = make_train_step()
    step_ov = make_train_step(mesh, "data", state_sharding=osh)
    for _ in range(3):
        s1, m1 = step_1d(s1, batch)
        so, mo = step_ov(so, batch)

    np.testing.assert_allclose(float(mo.loss_sum), float(m1.loss_sum),
                               rtol=1e-4)
    assert int(mo.correct) == int(m1.correct)
    merged = merge_vit_params_tp(jax.device_get(so.params))
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(merged)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_cli_tp_overlap_matches_unoverlapped_tp(tmp_path):
    """--tp-overlap trains through the full driver and matches the plain
    GSPMD --tensor-parallel run's metrics: the overlap is a schedule, not
    a math change."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    base = [
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--patch-size", "7",
        "--tensor-parallel", "2", "--root", str(tmp_path / "data"),
    ]
    ov = run(build_parser().parse_args(
        base + ["--tp-overlap", "--checkpoint-dir", str(tmp_path / "ckpt_o")]))
    tp = run(build_parser().parse_args(
        base + ["--checkpoint-dir", str(tmp_path / "ckpt_t")]))
    assert ov["history"][0]["train_loss"] == pytest.approx(
        tp["history"][0]["train_loss"], rel=1e-4)
    assert ov["history"][0]["test_acc"] == pytest.approx(
        tp["history"][0]["test_acc"], abs=1e-6)


def test_cli_tp_overlap_requires_tp(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--patch-size", "7", "--tp-overlap",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="tensor-parallel >= 2"):
        run(args)


def test_cli_tp_overlap_rejects_indivisible_tokens(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--tensor-parallel", "2", "--tp-overlap",  # patch 4 -> 49 tokens
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="patch-size 7"):
        run(args)
