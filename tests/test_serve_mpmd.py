"""MPMD pipeline serving (serve/pipeline.py): the per-stage param split,
the registry plumbing (validate/template/gate), per-stage program
exactness against the single-device forward (padded, exact-bucket, and
chunked), zero steady-state recompiles per bucket x stage, the
coordinated cross-stage hot-reload swap (no mixed-epoch batch), the
pool's chain groups, the stage-occupancy helper, and the analyzer
cleanliness of the new module."""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
    merge_vit_params,
    split_stage_params,
    split_vit_params,
)
from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.pipeline import (
    PipelineEngine,
    make_pipeline_template,
)
from pytorch_distributed_mnist_tpu.serve.pool import EnginePool
from pytorch_distributed_mnist_tpu.serve.programs import (
    check_checkpoint_layout,
    servable_modes,
    serve_modes,
    validate_serve_mode,
)
from pytorch_distributed_mnist_tpu.utils.profiling import (
    compile_log,
    stage_occupancy,
)

pytestmark = pytest.mark.serve

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def pp_setup():
    model = get_model("vit", compute_dtype=jnp.float32)
    template = make_pipeline_template(model, jax.random.key(0))
    images, _ = synthetic_dataset(32, seed=5)
    return model, template, images


def _direct_labels(model, split_params, raw_images):
    logits = model.apply(merge_vit_params(split_params), jnp.asarray(
        normalize_images(raw_images)), train=False)
    return np.argmax(np.asarray(logits), axis=-1)


# -- the stage split (parallel/pipeline_vit.py) ------------------------------


def test_split_stage_params_boundaries(pp_setup):
    """Stage s holds blocks [s*k, (s+1)*k) BITWISE (the training stage
    axis's boundaries); embed rides stage 0 only, head the last stage
    only."""
    _, template, _ = pp_setup
    split = template.params
    depth = jax.tree_util.tree_leaves(split["blocks"])[0].shape[0]
    stages = split_stage_params(split, 2)
    assert len(stages) == 2
    assert set(stages[0]) == {"blocks", "embed"}
    assert set(stages[1]) == {"blocks", "head"}
    k = depth // 2
    for s, tree in enumerate(stages):
        got = jax.tree_util.tree_leaves(tree["blocks"])
        want = [np.asarray(leaf)[s * k:(s + 1) * k]
                for leaf in jax.tree_util.tree_leaves(split["blocks"])]
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)
    # One stage = the whole stack, embed AND head on it.
    (single,) = split_stage_params(split, 1)
    assert set(single) == {"blocks", "embed", "head"}
    with pytest.raises(ValueError, match="not divisible"):
        split_stage_params(split, 3)


def test_pipeline_registered_and_validates(pp_setup):
    """The registry sees the mode (boot gate vocabulary, argparse
    choices, bench iteration) and the generic divisibility walk reduces
    to depth % stages == 0 over the pipelined template tree."""
    _, template, _ = pp_setup
    assert "pipeline" in serve_modes()
    assert servable_modes("vit") == ["replicated", "pipeline", "tensor"]
    validate_serve_mode("pipeline", "vit", 2, template.params)
    with pytest.raises(ValueError, match=r"dim 0 .* does not"):
        # depth 2 does not split 3 ways; the walk names the blocks leaf.
        validate_serve_mode("pipeline", "vit", 3, template.params)
    with pytest.raises(ValueError, match="no sharding rule table"):
        validate_serve_mode("pipeline", "cnn", 2)


def test_layout_gate_flipped_for_pipeline():
    """The PR 8 gate now names --serve-mode pipeline as the VALID choice
    for a pipeline-trained checkpoint instead of rejecting by name, and
    keeps rejecting every other mode for it."""
    check_checkpoint_layout({"pipeline": 2}, "pipeline", "vit")
    with pytest.raises(ValueError, match="--serve-mode pipeline"):
        check_checkpoint_layout({"pipeline": 2}, "replicated", "vit")
    with pytest.raises(ValueError, match="--serve-mode pipeline"):
        check_checkpoint_layout({"pipeline": 2}, "tensor", "vit")
    # A tensor-trained checkpoint still can't serve pipelined.
    with pytest.raises(ValueError, match="--serve-mode tensor"):
        check_checkpoint_layout({"tensor": 2}, "pipeline", "vit")


# -- per-stage program exactness ---------------------------------------------


def test_pipeline_logits_match_single_device(pp_setup):
    """The chained per-stage programs reproduce the single-device
    forward: allclose logits (independent programs reassociate like the
    mesh ones) and identical argmax, at exact-bucket, padded, and
    chunked-oversize batch shapes."""
    model, template, images = pp_setup
    base = InferenceEngine(model.apply, merge_vit_params(template.params),
                           buckets=(1, 8))
    base.warmup()
    eng = PipelineEngine(model, template.params, jax.local_devices()[:2],
                         buckets=(1, 8))
    eng.warmup()
    assert eng.stage_names() == ["pipeline.s0", "pipeline.s1"]
    for n in (8, 5, 1, 20):  # exact bucket, padded, bucket-1, chunked
        got, _ = eng.logits_with_epoch(images[:n])
        ref, _ = base.logits_with_epoch(images[:n])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(np.argmax(got, -1),
                                      np.argmax(ref, -1))


def test_zero_steady_state_recompiles_per_bucket_and_stage(pp_setup):
    model, template, images = pp_setup
    eng = PipelineEngine(model, template.params, jax.local_devices()[:2],
                         buckets=(1, 8))
    eng.warmup()
    programs = compile_log.stats()["programs"]
    expected = {f"serve_forward_b{b}@pipeline.s{k}"
                for b in (1, 8) for k in (0, 1)}
    assert expected <= set(programs)
    before = {n: programs[n]["backend_compiles"] for n in expected}
    eng.logits(images[:1])
    eng.logits(images[:8])
    eng.logits(images[:5])  # padded
    eng.logits(images[:20])  # chunked through the top bucket
    after = compile_log.stats()["programs"]
    assert {n: after[n]["backend_compiles"] for n in expected} == before


def test_stage_params_live_on_their_own_chips(pp_setup):
    """The HBM story: stage k's params are committed to chip k ONLY —
    no chip holds the whole model."""
    model, template, _ = pp_setup
    devices = jax.local_devices()[:2]
    eng = PipelineEngine(model, template.params, devices, buckets=(8,))
    for k, stage_tree in enumerate(eng._stage_params):
        for leaf in jax.tree_util.tree_leaves(stage_tree):
            assert leaf.devices() == {devices[k]}


# -- coordinated cross-stage hot reload --------------------------------------


def test_swap_is_stale_rejecting_and_atomic_across_stages(pp_setup):
    model, _, _ = pp_setup
    states = {e: make_pipeline_template(model, jax.random.key(e))
              for e in (1, 2)}
    eng = PipelineEngine(model, states[1].params, jax.local_devices()[:2],
                         buckets=(8,), params_epoch=1)
    eng.warmup()
    assert eng.swap_params(states[2].params, epoch=2) is True
    assert eng.params_epoch == 2
    # Stale swap refused on every stage at once.
    assert eng.swap_params(states[1].params, epoch=1) is False
    assert eng.params_epoch == 2


def test_hot_reload_no_mixed_epoch_batch_under_hammering(pp_setup):
    """The acceptance guarantee: a batch never spans two epochs ACROSS
    STAGES — the per-stage swap installs the whole stage list under one
    lock, dispatch snapshots it once, and every reply's epoch tag is a
    single installed epoch with final logits pinned to the direct
    forward of the final checkpoint."""
    model, _, images = pp_setup
    states = {e: make_pipeline_template(model, jax.random.key(e))
              for e in (10, 11, 12)}
    pool = EnginePool(model.apply, states[10].params,
                      devices=jax.local_devices()[:4], buckets=(1, 8),
                      params_epoch=10, serve_mode="pipeline", mesh_size=2,
                      model_name="vit", model=model)
    pool.warmup()

    def complete(handle):
        labels, epoch = pool.predict_complete(handle)
        tag = np.full_like(labels, -1 if epoch is None else epoch)
        return np.stack([labels, tag], axis=1)

    failures = []
    stop = threading.Event()

    def hammer(wid):
        i = 0
        while not stop.is_set():
            stack = pool.preprocess(images[(wid + i) % 24:
                                           (wid + i) % 24 + 4])
            out = batcher.predict(stack, timeout=30.0)
            epochs = set(out[:, 1].tolist())
            if len(epochs) != 1 or not epochs <= {10, 11, 12}:
                failures.append(out[:, 1].tolist())
            i += 1

    with MicroBatcher(None, max_batch=8, max_wait_s=0.002,
                      dispatch_fn=pool.dispatch, complete_fn=complete,
                      max_inflight=5) as batcher:
        threads = [threading.Thread(target=hammer, args=(w,), daemon=True)
                   for w in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        for epoch in (11, 12):
            assert pool.swap_params(states[epoch].params, epoch=epoch) == 2
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(30.0)
    assert not failures, failures[:5]
    labels, epoch = pool.predict_complete(
        pool.dispatch(pool.preprocess(images[:8])))
    assert epoch == 12
    np.testing.assert_array_equal(
        labels, _direct_labels(model, states[12].params, images[:8]))


# -- the pool's chain groups -------------------------------------------------


def test_pipeline_pool_groups_names_and_spans(pp_setup):
    """4 chips at 2 stages = 2 chains (pipeline.g0/g1), each spanning 2
    disjoint chips, stage programs named per chain x stage; answers
    match a replicated pool of the same checkpoint."""
    model, template, images = pp_setup
    pool = EnginePool(model.apply, template.params,
                      devices=jax.local_devices()[:4], buckets=(1, 8),
                      params_epoch=7, serve_mode="pipeline", mesh_size=2,
                      model_name="vit", model=model)
    assert [r.name for r in pool.replicas] == ["pipeline.g0", "pipeline.g1"]
    spans = [set(map(str, r.devices)) for r in pool.replicas]
    assert len(spans[0]) == 2 and spans[0].isdisjoint(spans[1])
    pool.warmup()
    programs = compile_log.stats()["programs"]
    assert {f"serve_forward_b8@pipeline.g{g}.s{k}"
            for g in (0, 1) for k in (0, 1)} <= set(programs)
    repl = EnginePool(model.apply, merge_vit_params(template.params),
                      devices=jax.local_devices()[:4], buckets=(1, 8),
                      params_epoch=7)
    repl.warmup()
    for n in (8, 3):
        got, ge = pool.predict_complete(pool.dispatch(
            pool.preprocess(images[:n])))
        want, we = repl.predict_complete(repl.dispatch(
            repl.preprocess(images[:n])))
        np.testing.assert_array_equal(got, want)
        assert ge == we == 7
    snap = pool.snapshot()
    for row in snap.values():
        assert row["mode"] == "pipeline" and row["stages"] == 2
    assert pool.topology()["pipeline_stages"] == 2


def test_pipeline_pool_requires_model_object(pp_setup):
    _, template, _ = pp_setup
    model = get_model("vit", compute_dtype=jnp.float32)
    with pytest.raises(ValueError, match="model"):
        EnginePool(model.apply, template.params,
                   devices=jax.local_devices()[:2], serve_mode="pipeline",
                   mesh_size=2, model_name="vit")  # model= missing


# -- occupancy helper --------------------------------------------------------


def test_stage_occupancy_units():
    """The bottleneck stage reads 1.0, others their wall's share of the
    bottleneck clock; degenerate inputs return {} (a pipe doing no work
    has no occupancy)."""
    occ = stage_occupancy({"s0": 2.0, "s1": 4.0, "s2": 1.0})
    assert occ == {"s0": 0.5, "s1": 1.0, "s2": 0.25}
    assert stage_occupancy({}) == {}
    assert stage_occupancy({"s0": 0.0}) == {}


def test_stage_step_ms_probe(pp_setup):
    model, template, _ = pp_setup
    eng = PipelineEngine(model, template.params, jax.local_devices()[:2],
                         buckets=(8,))
    eng.warmup()
    walls = eng.stage_step_ms(8, reps=2)
    assert sorted(walls) == ["s0", "s1"]
    assert all(v > 0 for v in walls.values())
    occ = stage_occupancy(walls)
    assert max(occ.values()) == 1.0


# -- analyzer cleanliness ----------------------------------------------------


@pytest.mark.lint
def test_pipeline_module_clean_under_analyzer():
    """serve/pipeline.py pinned clean under the checkers its code could
    plausibly trip: lock discipline (params capture under the engine
    lock vs device work outside), trace purity (the per-stage jitted
    forwards), collective symmetry (no process_index-conditioned
    anything), recompile hazard (bucket lowering)."""
    from tools.analyzer import run_analysis

    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                      "pipeline.py")],
        checkers=["collective-symmetry", "trace-purity",
                  "recompile-hazard", "lock-discipline"],
        baseline=None)
    assert result.findings == []
