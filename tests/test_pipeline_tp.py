"""PP x TP (parallel/pipeline_tp.py): the explicit-Megatron stage body on a
data x stage x model mesh — round-2 VERDICT's first composition hole.

The bar is the same self-consistency the PP-only suite pins: the pipelined
TP program must be numerically the same model as the sequential
``VisionTransformer.apply`` — layouts are an implementation detail, math
is the contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.pipeline_tp import (
    create_pipelined_tp_vit_state,
    make_pipelined_tp_vit_apply,
    merge_vit_params_tp,
    split_vit_params_tp,
)


def _model(depth=4, **kw):
    return get_model("vit", compute_dtype=jnp.float32, depth=depth, **kw)


def _params(model, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, 28, 28, 1)))


def test_split_merge_tp_round_trip():
    model = _model()
    params = _params(model)
    merged = merge_vit_params_tp(
        split_vit_params_tp(params, model.num_heads))
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(merged)[0],
    ):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b),
            err_msg=jax.tree_util.keystr(pa))


@pytest.mark.parametrize(
    "shape,depth",
    [
        ((2, 2, 2), 4),   # DP x PP x TP, 2 blocks/stage
        ((1, 4, 2), 4),   # PP x TP, 1 block/stage
        ((1, 2, 4), 4),   # wide TP: all 4 heads spread over the model axis
    ],
)
def test_pp_tp_forward_matches_sequential(shape, depth):
    model = _model(depth)
    params = _params(model)
    x = jax.random.normal(jax.random.key(1), (16, 28, 28, 1))
    ref = model.apply(params, x)
    mesh = make_mesh(("data", "stage", "model"), shape=shape)
    apply_fn = make_pipelined_tp_vit_apply(
        model, mesh, data_axis="data")
    out = apply_fn(split_vit_params_tp(params, model.num_heads), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pp_tp_grads_match_sequential():
    """Gradients through scan + ppermute + the model-axis psums equal the
    sequential model's — the Megatron partial sums transpose correctly."""
    from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy

    model = _model(depth=2)
    mesh = make_mesh(("data", "stage", "model"), shape=(2, 2, 2))
    x = jax.random.normal(jax.random.key(0), (8, 28, 28, 1), jnp.float32)
    y = jnp.arange(8, dtype=jnp.int32) % 10

    ref_params = _params(model, seed=3)

    def ref_loss(p):
        return cross_entropy(model.apply(p, x), y)

    ref_grads = jax.grad(ref_loss)(ref_params)

    apply_fn = make_pipelined_tp_vit_apply(model, mesh, data_axis="data")
    tp_params = split_vit_params_tp(ref_params, model.num_heads)

    def tp_loss(p):
        return cross_entropy(apply_fn(p, x), y)

    tp_grads = merge_vit_params_tp(jax.grad(tp_loss)(tp_params))
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(tp_grads)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(pa))


def test_pp_tp_state_actually_sharded():
    model = _model(depth=4)
    mesh = make_mesh(("data", "stage", "model"), shape=(2, 2, 2))
    state, sharding = create_pipelined_tp_vit_state(
        model, jax.random.key(0), mesh)
    from jax.sharding import PartitionSpec as P

    qkv = state.params["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.shape == (4, 64, 3, 4, 16)  # (depth, C, 3, H, D) head-major
    assert qkv.sharding.spec == P("stage", None, None, "model", None)
    proj = state.params["blocks"]["attn"]["proj"]["kernel"]
    assert proj.sharding.spec == P("stage", "model", None, None)
    mlp1 = state.params["blocks"]["mlp1"]["kernel"]
    assert mlp1.sharding.spec == P("stage", None, "model")
    # Adam moments mirror the param layout through the same rule pass.
    mu_qkv = state.opt_state.inner_state[0].mu[
        "blocks"]["attn"]["qkv"]["kernel"]
    assert mu_qkv.sharding.spec == P("stage", None, None, "model", None)


@pytest.mark.slow
def test_pp_tp_train_step_matches_unpipelined(tiny_data):
    """One jitted train step on the PP x TP mesh == the plain model's step
    (same init, same batch): loss exact, merged gradients equal."""
    from pytorch_distributed_mnist_tpu.data.loader import make_global_batch
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    model = _model(depth=2)
    images, labels = tiny_data
    batch = {"image": jnp.asarray(images[:32]),
             "label": jnp.asarray(labels[:32])}

    ref_state = create_train_state(model, jax.random.key(0))
    ref_step = make_train_step()
    ref_state, ref_m = ref_step(ref_state, batch)

    mesh = make_mesh(("data", "stage", "model"), shape=(2, 2, 2))
    tp_state, tp_sharding = create_pipelined_tp_vit_state(
        model, jax.random.key(0), mesh)
    tp_step = make_train_step(mesh, state_sharding=tp_sharding)
    tp_state, tp_m = tp_step(tp_state, make_global_batch(
        {k: np.asarray(v) for k, v in batch.items()}, mesh))

    assert float(tp_m.loss_sum) == pytest.approx(float(ref_m.loss_sum),
                                                 rel=1e-5)
    assert float(tp_m.correct) == float(ref_m.correct)


@pytest.mark.slow
def test_pp_tp_zero1_composes():
    """PP x TP x ZeRO-1: the generic base_sharding path adds a data axis
    to moment leaves the TP layout left unsharded — three-strategy
    composition on one mesh."""
    from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    mesh = make_mesh(("data", "stage", "model"), shape=(2, 2, 2))
    x = jax.random.normal(jax.random.key(0), (8, 28, 28, 1), jnp.float32)
    y = jnp.arange(8, dtype=jnp.int32) % 10
    batch = {"image": x, "label": y}
    model = _model(depth=2)

    def run_steps(with_zero):
        state, sharding = create_pipelined_tp_vit_state(
            model, jax.random.key(1), mesh)
        if with_zero:
            state, sharding = shard_state_zero(
                state, mesh, base_sharding=sharding, level=1)
        step = make_train_step(mesh, state_sharding=sharding)
        for _ in range(2):
            state, m = step(state, batch)
        return state, m, sharding

    s0, m0, _ = run_steps(False)
    s1, m1, sh1 = run_steps(True)
    np.testing.assert_allclose(float(m0.loss_sum), float(m1.loss_sum),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    specs = [s.spec for s in jax.tree.leaves(sh1.opt_state)]
    assert any("stage" in str(sp) and "data" in str(sp) for sp in specs)


def test_heads_not_divisible_raises():
    mesh = make_mesh(("data", "stage", "model"), shape=(1, 2, 4))
    # 2 heads cannot spread over a width-4 model axis.
    model = _model(depth=4, num_heads=2)
    with pytest.raises(ValueError, match="heads"):
        make_pipelined_tp_vit_apply(model, mesh)


@pytest.mark.slow
def test_cli_pp_tp_end_to_end(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit",
        "--pipeline-stages", "2", "--tensor-parallel", "2",
        "--epochs", "1", "--batch-size", "64",
        "--synthetic-train-size", "256", "--synthetic-test-size", "128",
        "--seed", "0",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    summary = run(args)
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


def test_cli_pp_sp_still_rejected(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    with pytest.raises(SystemExit, match="sequence-parallel"):
        run(build_parser().parse_args([
            "--dataset", "synthetic", "--model", "vit",
            "--pipeline-stages", "2", "--sequence-parallel", "2",
            "--checkpoint-dir", str(tmp_path),
        ]))
