"""Model zoo: shapes, registry, dtype policy."""

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_mnist_tpu.models import ConvNet, LinearNet, get_model, list_models


def test_registry_contains_both():
    assert "linear" in list_models() and "cnn" in list_models()


def test_registry_unknown_raises():
    with pytest.raises(ValueError, match="unknown model"):
        get_model("resnet9000")


@pytest.mark.parametrize("name", ["linear", "cnn"])
@pytest.mark.parametrize("shape", [(4, 28, 28, 1), (4, 28, 28), (4, 784)])
def test_forward_shapes(name, shape):
    model = get_model(name)
    x = jnp.zeros(shape, jnp.float32)
    params = model.init(jax.random.key(0), x)
    out = model.apply(params, x)
    assert out.shape == (4, 10)
    assert out.dtype == jnp.float32  # logits in f32 for stable xent


def test_linear_param_count_matches_reference_net():
    # Reference Net = Linear(784, 10): 784*10 weights + 10 bias (:123).
    model = LinearNet()
    params = model.init(jax.random.key(0), jnp.zeros((1, 784)))
    n = sum(p.size for p in jax.tree.leaves(params))
    assert n == 784 * 10 + 10


def test_cnn_is_bigger_than_linear():
    cnn = ConvNet()
    params = cnn.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))
    n = sum(p.size for p in jax.tree.leaves(params))
    assert n > 100_000  # conv + dense stack for the 99% target


def test_dtype_flag_cli(tmp_path):
    """--dtype f32 forces full-precision compute; bf16 is the default."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    common = [
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path), "--trainer-mode", "stepwise",
    ]
    s32 = run(build_parser().parse_args(common + ["--dtype", "f32"]))
    sbf = run(build_parser().parse_args(common + ["--dtype", "bf16"]))
    import numpy as np

    assert np.isfinite(s32["history"][0]["train_loss"])
    assert np.isfinite(sbf["history"][0]["train_loss"])
    # different compute precision -> measurably different loss trajectories
    assert s32["history"][0]["train_loss"] != sbf["history"][0]["train_loss"]


def test_dtype_flag_model_kwargs():
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.models import get_model

    m = get_model("cnn", compute_dtype=jnp.float32)
    assert m.compute_dtype == jnp.float32
