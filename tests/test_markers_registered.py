"""Tier-1 guard: every pytest marker used under tests/ is registered.

Since ISSUE 5 the actual logic lives in tpumnist-lint's
``marker-registry`` checker (tools/analyzer/checkers/marker_registry.py)
— this file is the thin tier-1 wrapper that runs it over tests/ and
keeps the historical guard-on-the-guard (a parser that matched nothing
would pass vacuously).

An unregistered marker is a silent hole: ``-m chaos`` style selection
quietly matches nothing (or everything), and pytest's warning scrolls
past in CI — a test marked with a misspelling like ``serv`` would run
in the default profile AND be invisible to the marker-filtered
profiles. (This file never spells the ``pytest . mark . name``
attribute form in prose — the checker would count it as a use.)
"""

import pathlib


from tools.analyzer import run_analysis  # noqa: E402
from tools.analyzer.checkers.marker_registry import (  # noqa: E402
    registered_markers,
)

_TESTS = pathlib.Path(__file__).resolve().parent


def test_every_marker_used_in_tests_is_registered():
    result = run_analysis([str(_TESTS)], checkers=["marker-registry"],
                          baseline=None)
    assert result.ok, "\n".join(f.render() for f in result.findings)
    # The checker actually looked at marker uses (guard on the guard).
    assert result.reports["marker-registry"]["marker_uses"] > 10


def test_known_markers_really_parse():
    """The analyzer's pyproject parser sees the markers we know exist —
    a regex that matched nothing would make the wrapper vacuous."""
    pyproject = _TESTS.parent / "pyproject.toml"
    registered = registered_markers(pyproject.read_text())
    assert {"slow", "chaos", "serve", "lint", "fleet"} <= registered


def test_wrapper_fails_on_a_misspelled_marker(tmp_path):
    """End-to-end drift proof: an unregistered marker in a test file is
    a finding (the pre-ISSUE-5 assertion, now through the analyzer)."""
    bad = tmp_path / "tests" / "test_bad.py"
    bad.parent.mkdir()
    bad.write_text("import pytest\npytestmark = pytest.mark.serv\n")
    (tmp_path / "pyproject.toml").write_text(
        '[tool.pytest.ini_options]\nmarkers = [\n'
        '    "serve: serving subsystem",\n]\n')
    result = run_analysis([str(bad)], checkers=["marker-registry"],
                          baseline=None)
    assert not result.ok
    (finding,) = result.findings
    assert finding.symbol == "serv"
