"""Tier-1 guard: every pytest marker used under tests/ is registered in
pyproject.toml.

An unregistered marker is a silent hole: ``-m chaos`` style selection
quietly matches nothing (or everything), and pytest's warning scrolls
past in CI — a test marked with a misspelling like ``serv`` would run
in the default profile AND be invisible to the marker-filtered
profiles. This guard turns that drift into a red test with the
offending names. (This file itself never spells out the
``pytest  . mark  . name`` attribute form for its examples — the scan
below would flag them.)"""

import pathlib
import re

# Markers pytest itself defines; everything else must be declared.
_BUILTIN = {"parametrize", "skip", "skipif", "xfail", "usefixtures",
            "filterwarnings", "tryfirst", "trylast"}


def _registered_markers(pyproject_text: str) -> set:
    """Parse ``[tool.pytest.ini_options] markers`` without tomllib
    (python 3.10): the entries are quoted "name: description" strings
    inside the markers = [...] list."""
    section = re.search(r"markers\s*=\s*\[(.*?)\]", pyproject_text, re.S)
    assert section, "pyproject.toml has no pytest markers list"
    return set(re.findall(r'"\s*([A-Za-z_]\w*)\s*[:(]', section.group(1)))


def _used_markers(tests_dir: pathlib.Path) -> dict:
    """marker name -> first file using it, from both the decorator and
    the module-level ``pytestmark`` assignment forms."""
    used = {}
    for path in sorted(tests_dir.glob("**/*.py")):
        for match in re.finditer(r"pytest\.mark\.([A-Za-z_]\w*)",
                                 path.read_text()):
            used.setdefault(match.group(1), path.name)
    return used


def test_every_marker_used_in_tests_is_registered():
    tests_dir = pathlib.Path(__file__).resolve().parent
    pyproject = tests_dir.parent / "pyproject.toml"
    registered = _registered_markers(pyproject.read_text())
    used = _used_markers(tests_dir)
    unregistered = {name: where for name, where in used.items()
                    if name not in registered and name not in _BUILTIN}
    assert not unregistered, (
        f"markers used but not registered in pyproject.toml "
        f"[tool.pytest.ini_options] markers: {unregistered}")


def test_known_markers_really_parse():
    """The parser above sees the markers we know exist — a guard on the
    guard (a regex that matched nothing would pass vacuously)."""
    tests_dir = pathlib.Path(__file__).resolve().parent
    registered = _registered_markers(
        (tests_dir.parent / "pyproject.toml").read_text())
    assert {"slow", "chaos", "serve"} <= registered
    used = _used_markers(tests_dir)
    assert {"slow", "chaos", "serve"} <= set(used)
