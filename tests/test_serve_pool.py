"""EnginePool: multi-replica correctness (1-replica and 4-replica pools
answer identically), least-loaded dispatch, per-replica zero-recompile
invariant, swap fan-out with per-replica stale rejection, and the
no-mixed-epoch-within-a-batch guarantee under hot reload."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher
from pytorch_distributed_mnist_tpu.serve.pool import EnginePool
from pytorch_distributed_mnist_tpu.serve.reload import CheckpointWatcher
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog, compile_log

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def linear_setup():
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    images, labels = synthetic_dataset(64, seed=3)
    return model, state, images, labels


def _direct_labels(model, state, raw_images):
    logits = model.apply(state.params, jnp.asarray(
        normalize_images(raw_images)), train=False)
    return np.argmax(np.asarray(logits), axis=-1)


def _drive_pool(pool, request_stacks, max_inflight):
    """Closed-loop drive through the pipelined batcher; returns each
    request's (labels, epoch) in submit order."""
    def complete(handle):
        labels, epoch = pool.predict_complete(handle)
        tag = np.full_like(labels, -1 if epoch is None else epoch)
        return np.stack([labels, tag], axis=1)

    results = []
    with MicroBatcher(None, max_batch=pool.max_batch, max_wait_s=0.002,
                      dispatch_fn=pool.dispatch, complete_fn=complete,
                      max_inflight=max_inflight) as batcher:
        pendings = [batcher.submit(pool.preprocess(stack))
                    for stack in request_stacks]
        for p in pendings:
            out = batcher.result(p, timeout=60.0)
            results.append((out[:, 0].tolist(), sorted(set(out[:, 1]))))
    return results


def test_multi_replica_matches_single_replica(linear_setup):
    """The deterministic correctness pin: the SAME requests through a
    1-replica pool and a 4-replica pool produce identical predictions
    and identical epochs — replica fan-out must be invisible to
    clients."""
    model, state, images, _ = linear_setup
    stacks = [images[i:i + 1 + (i % 3)] for i in range(24)]
    results = {}
    for n in (1, 4):
        pool = EnginePool(model.apply, state.params,
                          devices=jax.local_devices()[:n],
                          buckets=(1, 4, 8), params_epoch=2)
        pool.warmup()
        results[n] = _drive_pool(pool, stacks, max_inflight=n + 1)
    assert results[1] == results[4]
    # And both match the direct forward pass.
    for stack, (labels, epochs) in zip(stacks, results[4]):
        assert labels == _direct_labels(model, state, stack).tolist()
        assert epochs == [2]


def test_dispatch_picks_least_loaded_replica(linear_setup):
    """Four batches dispatched with none completed land on four DIFFERENT
    replicas (the pending count drives placement); completion drains the
    counts back to zero."""
    model, state, images, _ = linear_setup
    log = ServeLog()
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:4], buckets=(4,),
                      serve_log=log)
    pool.warmup()
    handles = [pool.dispatch(pool.preprocess(images[i:i + 2]))
               for i in range(4)]
    assert sorted(h.replica.name for h in handles) \
        == ["r0", "r1", "r2", "r3"]
    snap = pool.snapshot()
    assert all(row["pending"] == 1 for row in snap.values())
    for h in handles:
        labels, _ = pool.predict_complete(h)
        assert labels.shape == (2,)
    assert all(row["pending"] == 0 for row in pool.snapshot().values())
    # ServeLog carries one execution row per replica.
    replicas = log.snapshot()["replicas"]
    assert sorted(replicas) == ["r0", "r1", "r2", "r3"]
    assert all(replicas[r]["batches"] == 1 for r in replicas)


def test_zero_recompiles_per_replica_steady_state(linear_setup):
    """After warmup, serving through every replica adds ZERO compiles to
    any replica's programs — the per-replica CompileLog names make the
    check attributable chip by chip."""
    model, state, images, _ = linear_setup
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:4], buckets=(2, 8))
    pool.warmup()
    programs = compile_log.stats()["programs"]
    expected = {f"serve_forward_b{b}@r{i}" for b in (2, 8)
                for i in range(4)}
    assert expected <= set(programs)
    before = {name: programs[name]["backend_compiles"]
              for name in expected}
    handles = [pool.dispatch(pool.preprocess(images[i:i + 3]))
               for i in range(8)]  # 2 batches per replica, padded to b8
    for h in handles:
        pool.complete(h)
    after = compile_log.stats()["programs"]
    assert {name: after[name]["backend_compiles"] for name in expected} \
        == before


def test_swap_fans_out_with_per_replica_stale_rejection(linear_setup):
    """One fan-out installs on every replica; a stale fan-out installs on
    NONE; and a replica that individually got ahead keeps its newer
    epoch while the laggards catch up."""
    model, state, images, _ = linear_setup
    other = create_train_state(model, jax.random.key(9))
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:3], buckets=(8,),
                      params_epoch=1)
    pool.warmup()
    assert pool.swap_params(other.params, epoch=5) == 3
    assert [r.engine.params_epoch for r in pool.replicas] == [5, 5, 5]
    # Stale fan-out: rejected by every replica, nothing changes.
    assert pool.swap_params(state.params, epoch=3) == 0
    assert [r.engine.params_epoch for r in pool.replicas] == [5, 5, 5]
    np.testing.assert_array_equal(
        pool.predict_complete(pool.dispatch(
            pool.preprocess(images[:8])))[0],
        _direct_labels(model, other, images[:8]))
    # One replica races ahead; a fleet-wide epoch-7 fan-out upgrades only
    # the laggards and leaves the leader alone.
    leader = create_train_state(model, jax.random.key(11))
    assert pool.replicas[1].engine.swap_params(leader.params, epoch=9)
    assert pool.swap_params(other.params, epoch=7) == 2
    assert [r.engine.params_epoch for r in pool.replicas] == [7, 9, 7]


def test_hot_reload_never_mixes_epochs_within_a_batch(linear_setup):
    """Hammer multi-row requests through a 4-replica pipelined pool while
    params hot-swap repeatedly: every reply must carry EXACTLY ONE epoch
    across its rows (params+epoch are captured once per batch on one
    replica), and every epoch must be one that was actually installed."""
    model, state, images, _ = linear_setup
    states = {e: create_train_state(model, jax.random.key(e))
              for e in (10, 11, 12, 13)}
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:4], buckets=(1, 8),
                      params_epoch=10)
    pool.warmup()
    pool.swap_params(states[10].params, epoch=10)

    def complete(handle):
        labels, epoch = pool.predict_complete(handle)
        tag = np.full_like(labels, -1 if epoch is None else epoch)
        return np.stack([labels, tag], axis=1)

    failures = []
    stop = threading.Event()

    def hammer(wid):
        i = 0
        while not stop.is_set():
            stack = pool.preprocess(images[(wid + i) % 32:
                                           (wid + i) % 32 + 4])
            out = batcher.predict(stack, timeout=30.0)
            epochs = set(out[:, 1].tolist())
            if len(epochs) != 1 or not epochs <= {10, 11, 12, 13}:
                failures.append(out[:, 1].tolist())
            i += 1

    with MicroBatcher(None, max_batch=8, max_wait_s=0.002,
                      dispatch_fn=pool.dispatch, complete_fn=complete,
                      max_inflight=5) as batcher:
        threads = [threading.Thread(target=hammer, args=(w,), daemon=True)
                   for w in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.2)  # traffic established before the first swap
        for epoch in (11, 12, 13):
            assert pool.swap_params(states[epoch].params,
                                    epoch=epoch) == 4
            time.sleep(0.1)  # batches in flight across each boundary
        stop.set()
        for t in threads:
            t.join(30.0)
    assert not failures, failures[:5]
    # Steady state: the final swap serves everywhere.
    labels, epoch = pool.predict_complete(
        pool.dispatch(pool.preprocess(images[:8])))
    assert epoch == 13
    np.testing.assert_array_equal(
        labels, _direct_labels(model, states[13], images[:8]))


def test_watcher_fans_out_to_pool(linear_setup, tmp_path):
    """CheckpointWatcher.on_params = pool.swap_params: one host-side load
    installs on every replica; a load that is stale fleet-wide is
    skipped (not recorded as a reload)."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        save_checkpoint,
    )

    model, state, images, _ = linear_setup
    template = create_train_state(model, jax.random.key(0))
    pool = EnginePool(model.apply, template.params,
                      devices=jax.local_devices()[:2], buckets=(8,))
    pool.warmup()
    log = ServeLog()
    watcher = CheckpointWatcher(str(tmp_path), template, pool.swap_params,
                                serve_log=log)
    published = create_train_state(model, jax.random.key(21))
    save_checkpoint(published, epoch=4, best_acc=0.5, is_best=False,
                    directory=str(tmp_path), process_index=0)
    assert watcher.poll_once()
    assert [r.engine.params_epoch for r in pool.replicas] == [4, 4]
    assert log.snapshot()["reloads"] == 1
    np.testing.assert_array_equal(
        pool.predict_complete(pool.dispatch(
            pool.preprocess(images[:8])))[0],
        _direct_labels(model, published, images[:8]))
    # The fleet moves ahead of the directory (e.g. a second directory's
    # watcher): a newer publish that is STALE for the fleet is skipped.
    ahead = create_train_state(model, jax.random.key(22))
    pool.swap_params(ahead.params, epoch=9)
    save_checkpoint(published, epoch=6, best_acc=0.5, is_best=False,
                    directory=str(tmp_path), process_index=0)
    assert not watcher.poll_once()
    assert log.snapshot()["reloads"] == 1  # not recorded
    assert [r.engine.params_epoch for r in pool.replicas] == [9, 9]


def test_pool_snapshot_rows(linear_setup):
    model, state, _, _ = linear_setup
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:2], buckets=(4,),
                      params_epoch=3)
    snap = pool.snapshot()
    assert sorted(snap) == ["r0", "r1"]
    for row in snap.values():
        assert row["pending"] == 0 and row["dispatched"] == 0
        assert row["params_epoch"] == 3
        assert "cpu" in row["device"].lower()


def test_pool_requires_a_device():
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    with pytest.raises(ValueError, match="at least one device"):
        EnginePool(model.apply, state.params, devices=[])
