"""Delta weight distribution (ISSUE 18): content-addressed chunk
store, manifest publish, GC window, serve-side delta fetch, and the
router/watcher seams — all in-process.

The contracts pinned here:
- a manifest round-trips BITWISE against the whole-file layouts, for
  npz and for sharded directories converted across world sizes;
- chunk boundaries are deterministic: adjacent publishes share every
  unchanged leaf's chunks, and a one-leaf change dirties exactly that
  leaf's chunk list;
- the GC window is the prune window: chunks live exactly as long as a
  manifest on disk references them;
- the DeltaFetcher re-quantizes ONLY dirtied leaves (clean leaves keep
  the previous install's QuantLeaf by OBJECT IDENTITY);
- gossip pulls peers-before-source, falling back per chunk;
- the CheckpointWatcher's failure taxonomy extends to delta damage: a
  torn manifest and a missing chunk are both permanent-for-that-publish
  skips, and the next clean publish recovers with no restart.

The loopback-HTTP integration (the /chunks endpoint, --register-dir /
--backends-dir discovery, manifest /rollout) lives in
tests/test_serve_delta_fleet.py; the process-boundary twins in
tools/chaos.py --torn-manifest and --fleet --delta-publish.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.distrib import cas as cas_mod
from pytorch_distributed_mnist_tpu.distrib import fetch as fetch_mod
from pytorch_distributed_mnist_tpu.distrib.cas import (
    ChunkStore,
    build_manifest,
    chunk_leaf,
    read_manifest,
)
from pytorch_distributed_mnist_tpu.distrib.fetch import DeltaFetcher
from pytorch_distributed_mnist_tpu.distrib.publish import (
    gc_chunks,
    publish_arrays,
    publish_from_checkpoint,
    publish_state,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.programs import (
    QuantLeaf,
    get_precision,
)
from pytorch_distributed_mnist_tpu.serve.reload import CheckpointWatcher
from pytorch_distributed_mnist_tpu.train import checkpoint as ck
from pytorch_distributed_mnist_tpu.train.checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.distrib


def _fresh(seed: int = 0):
    model = get_model("linear", compute_dtype=jnp.float32)
    return create_train_state(model, jax.random.key(seed))


def _gathered(state):
    return [np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(ck._state_tree(state))]


def _perturbed(state, delta: float):
    """The SMALLEST params leaf shifted — adjacent-epoch steady state."""
    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    small = min(range(len(leaves)), key=lambda j: leaves[j].size)
    leaves = list(leaves)
    leaves[small] = leaves[small] + delta
    return state.replace(
        params=jax.tree_util.tree_unflatten(treedef, leaves))


# ---------------------------------------------------------------------------
# The chunk store.
# ---------------------------------------------------------------------------


def test_chunk_store_write_once_and_verified(tmp_path):
    store = ChunkStore(str(tmp_path))
    data = b"chunk bytes"
    digest = cas_mod._digest(data)
    assert store.put(digest, data) is True
    assert store.has(digest) and store.get(digest) == data
    # Write-once: a second put of the same content is a no-op.
    assert store.put(digest, data) is False
    # Verified-on-put: corrupt bytes under a wrong name never land
    # (fresh digest — an already-present one short-circuits write-once).
    with pytest.raises(ValueError):
        store.put(cas_mod._digest(b"expected"), b"other bytes")
    assert not store.has(cas_mod._digest(b"expected"))
    with pytest.raises(ValueError, match="missing chunk"):
        store.get("0" * 64)


def test_chunk_leaf_fixed_boundaries():
    data = bytes(range(256)) * 40  # 10240 B
    digests, lengths = chunk_leaf(data, 4096)
    assert lengths == [4096, 4096, 2048]
    assert b"".join([data[0:4096], data[4096:8192],
                     data[8192:]]) == data
    # Empty/scalar leaves still get exactly one chunk (a manifest leaf
    # with zero chunks would be unreconstructable).
    digests0, lengths0 = chunk_leaf(b"", 4096)
    assert len(digests0) == 1 and lengths0 == [0]


# ---------------------------------------------------------------------------
# Manifest round-trips: bitwise vs the whole-file layouts.
# ---------------------------------------------------------------------------


def test_manifest_round_trip_bitwise_vs_npz(tmp_path):
    state = _fresh(seed=0)
    npz = save_checkpoint(state, epoch=3, best_acc=0.25, is_best=False,
                          directory=str(tmp_path), process_index=0)
    manifest = publish_from_checkpoint(npz)
    assert manifest.endswith("checkpoint_3.manifest")
    via_npz = load_checkpoint(npz, _fresh(seed=1))
    via_manifest = load_checkpoint(manifest, _fresh(seed=2))
    assert via_manifest[1:] == via_npz[1:]  # (start_epoch, best_acc)
    for a, b in zip(_gathered(via_npz[0]), _gathered(via_manifest[0])):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("w_save,w_load", [(8, 4), (4, 8)])
def test_manifest_round_trip_sharded_cross_world(tmp_path, w_save, w_load):
    """A sharded .ckpt directory saved at world W converts to a manifest
    that loads at world W' bitwise — the delta plane composes with the
    elastic reshard contract instead of replacing it."""
    mesh = Mesh(np.array(jax.devices()[:w_save]), ("data",))
    state = jax.device_put(_fresh(seed=0), NamedSharding(mesh, P()))
    ckpt = save_checkpoint(state, epoch=2, best_acc=0.5, is_best=False,
                           directory=str(tmp_path), layout="sharded")
    manifest = publish_from_checkpoint(ckpt, str(tmp_path / "out"))
    load_mesh = Mesh(np.array(jax.devices()[:w_load]), ("data",))
    template = jax.device_put(_fresh(seed=1),
                              NamedSharding(load_mesh, P()))
    loaded, start_epoch, best_acc = load_checkpoint(manifest, template)
    assert start_epoch == 3 and best_acc == 0.5
    for want, got in zip(_gathered(state), _gathered(loaded)):
        np.testing.assert_array_equal(want, got)


def test_manifest_rides_resolution_and_meta_gates(tmp_path):
    """latest_checkpoint resolves manifests by the shared epoch pattern
    (npz wins a same-epoch tie), and the meta readers see manifest
    provenance exactly as they see npz provenance."""
    state = _fresh()
    publish_state(state, epoch=4, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert latest_checkpoint(str(tmp_path)).endswith(
        "checkpoint_4.manifest")
    save_checkpoint(state, epoch=4, best_acc=0.5, is_best=False,
                    directory=str(tmp_path), process_index=0)
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint_4.npz")
    publish_state(state, epoch=5, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    path = latest_checkpoint(str(tmp_path))
    assert path.endswith("checkpoint_5.manifest")
    assert ck.checkpoint_world(path) == {
        "processes": jax.process_count(),
        "devices": jax.device_count()}


# ---------------------------------------------------------------------------
# Chunk-boundary stability + the GC window.
# ---------------------------------------------------------------------------


def test_adjacent_publishes_share_unchanged_chunks(tmp_path):
    state = _fresh()
    store = ChunkStore(str(tmp_path))
    publish_state(state, epoch=1, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    before = store.digests()
    publish_state(_perturbed(state, 1e-3), epoch=2, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    m1 = read_manifest(str(tmp_path / "checkpoint_1.manifest"))
    m2 = read_manifest(str(tmp_path / "checkpoint_2.manifest"))
    rec1 = {r["name"]: r["chunks"] for r in m1["leaves"]}
    rec2 = {r["name"]: r["chunks"] for r in m2["leaves"]}
    changed = [n for n in rec1 if rec1[n] != rec2[n]]
    # Exactly the perturbed params leaf differs — the optimizer moments
    # and every other leaf keep their chunk lists verbatim.
    assert len(changed) == 1 and "'params'" in changed[0]
    # And the store grew by exactly the dirty leaf's chunks.
    new = store.digests() - before
    assert new == set(rec2[changed[0]]) - set(rec1[changed[0]])


def test_chunk_boundaries_independent_of_history(tmp_path):
    """The same arrays chunk to the same digests no matter what was
    published before — boundaries are a pure function of the bytes and
    the budget, never of the previous manifest."""
    state = _fresh(seed=3)
    named = [(k, np.asarray(v)) for k, v in
             ck._leaves_with_names(ck._state_tree(state))]
    m_a, _ = build_manifest(named, epoch=1, best_acc=0.0, chunk_mb=0.25)
    m_b, _ = build_manifest(named, epoch=9, best_acc=0.9, chunk_mb=0.25)
    assert ([r["chunks"] for r in m_a["leaves"]]
            == [r["chunks"] for r in m_b["leaves"]])


def test_gc_protects_exactly_the_windowed_manifests(tmp_path):
    state = _fresh()
    store = ChunkStore(str(tmp_path))
    for epoch in range(1, 4):
        publish_state(_perturbed(state, epoch * 1e-3), epoch=epoch,
                      best_acc=0.5, directory=str(tmp_path),
                      keep_last=1, process_index=0)
    names = sorted(p for p in os.listdir(str(tmp_path))
                   if p.endswith(".manifest"))
    # keep_last=1: the window holds the latest epoch and one before it.
    assert names == ["checkpoint_2.manifest", "checkpoint_3.manifest"]
    referenced = set()
    for name in names:
        referenced |= cas_mod.manifest_digests(
            read_manifest(str(tmp_path / name)))
    assert store.digests() == referenced
    # Both survivors still assemble: the window rule really protected
    # every chunk a kept manifest references.
    for name in names:
        cas_mod.load_manifest_arrays(str(tmp_path / name))


def test_torn_manifest_pins_no_chunks(tmp_path):
    publish_arrays([("leaf", np.arange(8, dtype=np.float32))],
                   epoch=1, best_acc=0.0, directory=str(tmp_path))
    store = ChunkStore(str(tmp_path))
    assert len(store.digests()) == 1
    os.remove(str(tmp_path / "checkpoint_1.manifest"))
    with open(str(tmp_path / "checkpoint_2.manifest"), "w") as f:
        f.write('{"epoch": 3, "leaves": [')  # torn mid-write
    assert gc_chunks(str(tmp_path)) > 0
    assert store.digests() == set()


# ---------------------------------------------------------------------------
# save_checkpoint --publish delta.
# ---------------------------------------------------------------------------


def test_save_checkpoint_publish_delta_resumes_bitwise(tmp_path):
    state = _fresh()
    path = save_checkpoint(state, epoch=2, best_acc=0.75, is_best=True,
                           directory=str(tmp_path), process_index=0,
                           publish="delta")
    assert path.endswith("checkpoint_2.manifest")
    assert not os.path.exists(str(tmp_path / "checkpoint_2.npz"))
    assert os.path.exists(str(tmp_path / "model_best.manifest"))
    loaded, start_epoch, best_acc = load_checkpoint(path, _fresh(seed=1))
    assert start_epoch == 3 and best_acc == 0.75
    for a, b in zip(_gathered(state), _gathered(loaded)):
        np.testing.assert_array_equal(a, b)


def test_publish_delta_rejects_sharded_layout(tmp_path):
    with pytest.raises(ValueError, match="publish_from_checkpoint"):
        save_checkpoint(_fresh(), epoch=0, best_acc=0.0, is_best=False,
                        directory=str(tmp_path), process_index=0,
                        layout="sharded", publish="delta")


def test_async_saver_delta_rejects_sharded_loudly(tmp_path):
    saver = ck.AsyncCheckpointer()
    with saver:
        with pytest.raises(ValueError):
            saver.save(_fresh(), epoch=0, best_acc=0.0, is_best=False,
                       directory=str(tmp_path), layout="sharded",
                       publish="delta")
        saver.save(_fresh(), epoch=1, best_acc=0.5, is_best=False,
                   directory=str(tmp_path), publish="delta")
    assert os.path.exists(str(tmp_path / "checkpoint_1.manifest"))


# ---------------------------------------------------------------------------
# The DeltaFetcher: dirty-leaf-only requantize + gossip ordering.
# ---------------------------------------------------------------------------


def test_requantize_touches_only_dirty_leaves(tmp_path):
    """The PR 13 idempotent-quantize contract carried into the fetch
    path: a clean leaf's QuantLeaf rides through BY OBJECT IDENTITY, so
    only dirtied leaves pay quantization on an adjacent publish."""
    state = _fresh()
    p1 = publish_state(state, epoch=1, best_acc=0.5,
                       directory=str(tmp_path), process_index=0)
    p2 = publish_state(_perturbed(state, 1e-3), epoch=2, best_acc=0.5,
                       directory=str(tmp_path), process_index=0)
    fetcher = DeltaFetcher(str(tmp_path),
                           precision=get_precision("int8w"))
    params1, epoch1 = fetcher.load(p1, state)
    assert epoch1 == 1 and fetcher.last["dirty_leaves"] == 2
    flat1 = jax.tree_util.tree_leaves(
        params1, is_leaf=lambda x: isinstance(x, QuantLeaf))
    assert all(isinstance(leaf, QuantLeaf) for leaf in flat1)
    params2, epoch2 = fetcher.load(p2, state)
    assert epoch2 == 2
    assert fetcher.last["dirty_leaves"] == 1
    assert fetcher.last["clean_leaves"] == 1
    flat2 = jax.tree_util.tree_leaves(
        params2, is_leaf=lambda x: isinstance(x, QuantLeaf))
    identical = [a is b for a, b in zip(flat1, flat2)]
    assert sorted(identical) == [False, True]


def test_fetch_pulls_params_only(tmp_path):
    """Serving never ships optimizer moments: the fetch bytes are the
    params leaves', not the full Adam state's."""
    state = _fresh()
    path = publish_state(state, epoch=1, best_acc=0.5,
                         directory=str(tmp_path), process_index=0)
    local = str(tmp_path / "backend")
    fetcher = DeltaFetcher(local, source_dir=str(tmp_path))
    fetcher.load(path, state)
    params_bytes = sum(np.asarray(leaf).nbytes for leaf in
                       jax.tree_util.tree_leaves(state.params))
    state_bytes = sum(a.nbytes for a in _gathered(state))
    assert fetcher.last["bytes_fetched"] == params_bytes < state_bytes


def test_gossip_peers_before_source(tmp_path, monkeypatch):
    state = _fresh()
    path = publish_state(state, epoch=1, best_acc=0.5,
                         directory=str(tmp_path), process_index=0)
    source_store = ChunkStore(str(tmp_path))
    calls = []

    def fake_fetch(base_url, digest, timeout_s=5.0):
        calls.append((base_url, digest))
        if base_url == "http://dead":
            raise OSError("connection refused")
        return source_store.get(digest)

    monkeypatch.setattr(fetch_mod, "fetch_chunk_http", fake_fetch)
    fetcher = DeltaFetcher(str(tmp_path / "b1"),
                           peers=("http://dead", "http://live"),
                           source_dir=str(tmp_path))
    fetcher.load(path, state)
    # Every chunk was attempted over gossip (both peers reachable in
    # rotation order) and none fell through to the source dir.
    assert calls and fetcher.last["bytes_peer"] > 0
    assert fetcher.last["bytes_source"] == 0
    # Peer failure per chunk falls back to the source, still loading.
    monkeypatch.setattr(
        fetch_mod, "fetch_chunk_http",
        lambda *a, **k: (_ for _ in ()).throw(OSError("down")))
    fetcher2 = DeltaFetcher(str(tmp_path / "b2"),
                            peers=("http://dead",),
                            source_dir=str(tmp_path))
    fetcher2.load(path, state)
    assert fetcher2.last["bytes_source"] > 0
    assert fetcher2.last["bytes_peer"] == 0


def test_missing_chunk_error_is_absence_not_corruption(tmp_path):
    state = _fresh()
    path = publish_state(state, epoch=1, best_acc=0.5,
                         directory=str(tmp_path), process_index=0)
    # Simulate a sabotaged publish: one referenced chunk vanishes and
    # no peer/source has it.
    store = ChunkStore(str(tmp_path))
    manifest = read_manifest(path)
    # The PARAMS kernel record, not the optimizer moments' mirror of it
    # (mu/nu leaf names embed the same ['params']['fc']['kernel'] tail).
    params_rec = next(r for r in manifest["leaves"]
                      if r["name"].startswith("['params']")
                      and "kernel" in r["name"])
    os.remove(store.path(params_rec["chunks"][0]))
    fetcher = DeltaFetcher(str(tmp_path))
    with pytest.raises(ValueError, match="missing chunk") as err:
        fetcher.load(path, state)
    # The absence message must NOT collide with the sharded layout's
    # retry-forever "missing shards" taxonomy — this one is permanent
    # for the file at the watcher.
    assert "missing shards" not in str(err.value)
    assert not ck.is_corrupt_checkpoint_error(err.value)


# ---------------------------------------------------------------------------
# Watcher integration: the delta failure taxonomy end to end.
# ---------------------------------------------------------------------------


class _Installs:
    def __init__(self):
        self.epochs = []

    def __call__(self, params, epoch, path):
        self.epochs.append(epoch)
        return True


def test_watcher_skips_torn_manifest_until_clean_publish(tmp_path):
    state = _fresh()
    installs = _Installs()
    fetcher = DeltaFetcher(str(tmp_path))
    watcher = CheckpointWatcher(str(tmp_path), state, installs,
                                loader=fetcher.load)
    publish_state(state, epoch=1, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert watcher.poll_once() and installs.epochs == [1]
    # A torn manifest under the published name: half a JSON file.
    whole = (tmp_path / "checkpoint_1.manifest").read_bytes()
    (tmp_path / "checkpoint_2.manifest").write_bytes(
        whole[:len(whole) // 2])
    assert not watcher.poll_once()
    assert not watcher.poll_once()  # permanent for the file: no retry
    assert installs.epochs == [1]
    publish_state(_perturbed(state, 1e-3), epoch=3, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert watcher.poll_once() and installs.epochs == [1, 3]


def test_watcher_skips_missing_chunk_publish_then_recovers(tmp_path):
    state = _fresh()
    installs = _Installs()
    fetcher = DeltaFetcher(str(tmp_path))
    watcher = CheckpointWatcher(str(tmp_path), state, installs,
                                loader=fetcher.load)
    store = ChunkStore(str(tmp_path))
    publish_state(state, epoch=1, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert watcher.poll_once() and installs.epochs == [1]
    before = store.digests()
    publish_state(_perturbed(state, 1e-3), epoch=2, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    for digest in store.digests() - before:
        os.remove(store.path(digest))
    assert not watcher.poll_once()
    assert not watcher.poll_once()  # permanent for THIS publish
    assert installs.epochs == [1]
    # The next clean publish recovers — and because epoch 3 re-chunks
    # the changed leaf, the missing epoch-2 bytes are never needed.
    publish_state(_perturbed(state, 2e-3), epoch=3, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert watcher.poll_once() and installs.epochs == [1, 3]


def test_watcher_full_file_fallback_resets_delta_cache(tmp_path):
    """A whole-file publish landing in a delta-watched directory loads
    through the byte-identical fallback and resets the diff cache, so
    the NEXT manifest rebuilds every leaf instead of trusting stale
    hashes."""
    state = _fresh()
    installs = _Installs()
    fetcher = DeltaFetcher(str(tmp_path))
    watcher = CheckpointWatcher(str(tmp_path), state, installs,
                                loader=fetcher.load)
    publish_state(state, epoch=1, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert watcher.poll_once()
    save_checkpoint(state, epoch=2, best_acc=0.5, is_best=False,
                    directory=str(tmp_path), process_index=0)
    assert watcher.poll_once() and fetcher.total["full_loads"] == 1
    publish_state(state, epoch=3, best_acc=0.5,
                  directory=str(tmp_path), process_index=0)
    assert watcher.poll_once()
    assert fetcher.last["dirty_leaves"] == 2  # cache was reset
    assert installs.epochs == [1, 2, 3]


# ---------------------------------------------------------------------------
# Router seams: manifest republish + backends-dir discovery.
# ---------------------------------------------------------------------------


def test_republish_with_epoch_rewrites_manifest_json(tmp_path):
    from pytorch_distributed_mnist_tpu.serve.router import (
        epoch_of_checkpoint,
        republish_with_epoch,
    )

    state = _fresh()
    src = publish_state(state, epoch=2, best_acc=0.5,
                        directory=str(tmp_path), process_index=0)
    assert epoch_of_checkpoint(src) == 2
    dest = str(tmp_path / "checkpoint_7.manifest")
    republish_with_epoch(src, dest, epoch=7)
    rebased = read_manifest(dest)
    original = read_manifest(src)
    assert rebased["epoch"] == 8  # stored as epoch+1, the npz convention
    assert rebased["leaves"] == original["leaves"]  # same chunks, bitwise
    loaded, start_epoch, _ = load_checkpoint(dest, _fresh(seed=1))
    assert start_epoch == 8
    for a, b in zip(_gathered(state), _gathered(loaded)):
        np.testing.assert_array_equal(a, b)


def test_health_poller_backends_dir_discovery(tmp_path):
    from pytorch_distributed_mnist_tpu.serve.router import (
        PROBATION,
        Fleet,
        HealthPoller,
    )
    from pytorch_distributed_mnist_tpu.serve.server import (
        _remove_register_record,
        _write_register_record,
    )

    fleet = Fleet()
    static = fleet.add("127.0.0.1:7001")
    poller = HealthPoller(fleet, backends_dir=str(tmp_path))
    record = str(tmp_path / "backend_127-0-0-1_7002.json")
    _write_register_record(record, "http://127.0.0.1:7002")
    # A static member's record must not double-add or mark it reapable.
    _write_register_record(
        str(tmp_path / "backend_127-0-0-1_7001.json"),
        "http://127.0.0.1:7001")
    poller.sync_backends_dir()
    assert fleet.names() == ["127.0.0.1:7001", "127.0.0.1:7002"]
    joined = fleet.get("127.0.0.1:7002")
    assert joined.health.state == PROBATION  # earns healthy like a spawn
    # Idempotent while records are stable.
    poller.sync_backends_dir()
    assert fleet.names() == ["127.0.0.1:7001", "127.0.0.1:7002"]
    # Record removed (drain/shutdown): only the DISCOVERED backend
    # leaves; the static member is the operator's.
    _remove_register_record(record)
    os.remove(str(tmp_path / "backend_127-0-0-1_7001.json"))
    poller.sync_backends_dir()
    assert fleet.names() == ["127.0.0.1:7001"]
    assert fleet.get(static.name) is static
    # A torn record (partial JSON) is skipped, not fatal.
    with open(str(tmp_path / "backend_torn.json"), "w") as f:
        f.write('{"url": "http')
    poller.sync_backends_dir()
    assert fleet.names() == ["127.0.0.1:7001"]


# -- ranged resume (ISSUE 19): torn mid-body chunk fetch ----------------------


def _chunk_peer(data, plan):
    """A scriptable ``GET /chunks/<digest>`` peer. Each request pops one
    ``(mode, arg)`` from ``plan`` (exhausted -> honest "full"):

    - ``("tear", k)``: advertise the full remaining length but close the
      socket after ``k`` body bytes — the mid-body disconnect;
    - ``("ignore-range", None)``: answer a Range request with a plain
      200 and the WHOLE body (a peer that never learned Range);
    - ``("empty-tear", None)``: honor the Range with a 206 header, then
      close before ANY body byte — a resume that makes no progress;
    - ``("full", None)``: serve honestly (206 from the Range offset).

    Returns ``(httpd, requests)`` where ``requests`` records every
    ``(path, range_header)`` seen, for asserting the resume offsets.
    """
    import http.server
    import threading

    requests = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *_a):
            pass

        def do_GET(self):
            rng = self.headers.get("Range")
            requests.append((self.path, rng))
            mode, arg = plan.pop(0) if plan else ("full", None)
            start = 0
            if rng and mode != "ignore-range":
                start = int(rng.split("=", 1)[1].rstrip("-"))
            body = data[start:]
            self.send_response(206 if start else 200)
            self.send_header("Content-Length", str(len(body)))
            if start:
                self.send_header(
                    "Content-Range",
                    f"bytes {start}-{len(data) - 1}/{len(data)}")
            self.end_headers()
            if mode == "tear":
                self.wfile.write(body[:arg])
                self.wfile.flush()
                self.connection.close()
            elif mode == "empty-tear":
                self.connection.close()
            else:
                self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    httpd.handle_error = lambda *_a: None  # torn sockets are the point
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, requests


def _tear_data():
    # > 2 stream pieces (64 KiB each) so the tear lands mid-stream.
    return bytes(range(256)) * 650  # 166400 bytes


def test_fetch_resumes_from_partial_offset_after_midbody_tear():
    from pytorch_distributed_mnist_tpu.distrib.fetch import fetch_chunk_http

    data = _tear_data()
    httpd, requests = _chunk_peer(data, [("tear", 100_000)])
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        assert fetch_chunk_http(url, "deadbeef") == data
    finally:
        httpd.shutdown()
        httpd.server_close()
    # ONE resume, from exactly the partial offset — not from zero.
    assert [r[1] for r in requests] == [None, "bytes=100000-"]
    assert [r[0] for r in requests] == ["/chunks/deadbeef"] * 2


def test_fetch_restarts_when_peer_ignores_range():
    from pytorch_distributed_mnist_tpu.distrib.fetch import fetch_chunk_http

    data = _tear_data()
    httpd, requests = _chunk_peer(
        data, [("tear", 100_000), ("ignore-range", None)])
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        # The peer replays the body from byte 0 with a plain 200: the
        # splice buffer must reset, not concatenate.
        assert fetch_chunk_http(url, "deadbeef") == data
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert [r[1] for r in requests] == [None, "bytes=100000-"]


def test_fetch_raises_after_resume_with_no_progress():
    from pytorch_distributed_mnist_tpu.distrib.fetch import fetch_chunk_http

    data = _tear_data()
    httpd, requests = _chunk_peer(
        data, [("tear", 100_000), ("empty-tear", None)])
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        with pytest.raises(OSError, match="torn chunk fetch"):
            fetch_chunk_http(url, "deadbeef")
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert len(requests) == 2  # no blind retry loop after zero progress
