"""ISSUE 15 loopback acceptance twin: two models served from one pool
under spike loadgen; the SLO autoscaler resizes the hammered plane UP
during the spike and back DOWN after it; zero dropped in-flight
requests; 429 (per-client quota) and 503 (priority shed) replies carry
Retry-After; and every autoscale decision lands as a `serve_autoscale`
line in the shared JSONL sink."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.server import build_parser, create_server
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _publish(ckpt_dir, model_name, epoch, seed):
    model = get_model(model_name, compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


class _Server:
    def __init__(self, args):
        self.httpd = create_server(args)
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post_raw(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read()), dict(exc.headers)


def test_overload_acceptance_twin(tmp_path):
    d1, d2 = tmp_path / "linear", tmp_path / "cnn"
    _publish(d1, "linear", epoch=1, seed=1)
    _publish(d2, "cnn", epoch=2, seed=2)
    metrics = tmp_path / "metrics.jsonl"
    # Two models from one pool; buckets capped at 4 so micro-batching
    # cannot absorb the spike whole; the models' DEFAULT compute dtype
    # (bf16 — emulated and slow on this CPU backend) so one device's
    # cnn capacity sits well under the spike; a tight queue so priority
    # shedding genuinely fires; the autoscaler sampling a 3s rolling
    # window with a short cooldown so both directions fit the test
    # budget; quotas bounding only best_effort, which the spike mix
    # below never sends — the hot client is the only best_effort
    # speaker.
    args = build_parser().parse_args([
        "--model-set", f"linear={d1},cnn={d2}",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,4", "--max-wait-ms", "2", "--max-queue", "8",
        "--serve-devices", "1", "--max-inflight", "2",
        "--poll-interval", "5", "--stats-window-s", "3",
        "--autoscale", "--slo-p95-ms", "150",
        "--autoscale-interval-s", "0.2", "--autoscale-cooldown-s", "1",
        "--autoscale-down-after", "3", "--autoscale-max-devices", "2",
        "--quota-rps", "best_effort=2",
        "--metrics-file", str(metrics),
        "--no-fuse",  # split-plane boot: nothing fused is pinned here
    ])
    srv = _Server(args)
    try:
        # Sanity: both planes pooled at 1 device, each with its own
        # controller.
        stats = srv.get("/stats")
        assert stats["models"]["cnn"]["serve_devices"] == 1
        assert stats["models"]["cnn"]["autoscaler"]["dry_run"] is False

        # -- the spike, aimed at the cnn plane (interactive+batch mix,
        # no best_effort: the quota below stays the hot client's).
        loadgen = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--url", srv.url, "--mode", "open", "--shape", "spike",
             "--rate", "30", "--spike-mult", "16", "--duration", "8",
             "--mix", "interactive=0.7,batch=0.3",
             "--model", "cnn", "--client-id", "spike",
             "--timeout", "30"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

        # While the spike runs: the hot best_effort client must be
        # clipped by its per-client bucket with 429 + Retry-After —
        # BEFORE consuming queue slots the spike is fighting for.
        hot_codes = []
        hot_headers = []
        images = [[0] * 28] * 28
        for _ in range(8):
            code, body, headers = srv.post_raw("/predict", {
                "images": images, "model": "cnn",
                "priority": "best_effort", "client_id": "hog"})
            hot_codes.append(code)
            if code == 429:
                hot_headers.append(headers)
                assert body["error"] == "quota exceeded"
                assert body["retry_after_s"] > 0
        assert 429 in hot_codes
        assert all("Retry-After" in h for h in hot_headers)

        # Scale-UP during the spike (the cnn plane's controller).
        scaled_up = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cnn = srv.get("/stats")["models"]["cnn"]
            if cnn["serve_devices"] == 2 \
                    or cnn["autoscaler"]["scale_ups"]:
                scaled_up = True
                break
            time.sleep(0.2)
        assert scaled_up, "spike never scaled the cnn plane up"

        out, _ = loadgen.communicate(timeout=120)
        report = json.loads(out.strip().splitlines()[-1])
        # Zero dropped in-flight requests: every launched request was
        # ANSWERED — 200, 503 (shed, with Retry-After), or 429.
        assert report["transport_errors"] == 0, report
        answered = (report["ok"] + report["rejected"]
                    + report["quota_rejected"])
        sends = (sum(report["status_counts"].values())
                 + report["transport_errors"])
        assert answered == sends
        # The spike genuinely overloaded the plane (sheds happened),
        # and every shed carried Retry-After.
        assert report["rejected"] > 0
        assert report["retry_after_seen"] >= report["rejected"]
        # Priority order held per class: interactive kept more of its
        # offered share than batch (watermarks 1.0 vs 0.75).
        classes = report["classes"]
        inter = classes["interactive"]
        batch = classes["batch"]
        assert inter["ok"] / inter["sent"] >= batch["ok"] / batch["sent"]

        # Scale-DOWN after the spike drains.
        scaled_down = False
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            cnn = srv.get("/stats")["models"]["cnn"]
            if cnn["serve_devices"] == 1 \
                    and cnn["autoscaler"]["scale_downs"]:
                scaled_down = True
                break
            time.sleep(0.3)
        assert scaled_down, "cnn plane never scaled back down"

        # The linear plane sat out the whole event: still 1 device,
        # zero scale actions (its controller is its own).
        lin = srv.get("/stats")["models"]["linear"]
        assert lin["serve_devices"] == 1
        assert lin["autoscaler"]["scale_ups"] == 0
    finally:
        srv.close()

    # serve_autoscale events in the JSONL sink, both directions,
    # attributed to the cnn plane's source tag.
    lines = [json.loads(line) for line in
             metrics.read_text().splitlines() if line.strip()]
    auto = [rec for rec in lines if rec["kind"] == "serve_autoscale"]
    assert auto, "no serve_autoscale lines in the sink"
    actions = [rec["action"] for rec in auto]
    assert "scale_up" in actions and "scale_down" in actions
    assert all(rec["source"] == "serve/cnn" for rec in auto)
    assert all(rec["model"] == "cnn" for rec in auto)
    assert all(rec["dry_run"] is False for rec in auto)
    # The shared file also carries both planes' serve_stats lines.
    sources = {rec["source"] for rec in lines
               if rec["kind"] == "serve_stats"}
    assert {"serve/cnn", "serve/linear"} <= sources


def test_quota_precedence_over_queue_state(tmp_path):
    """429-vs-503 precedence: an over-quota client is refused by its
    bucket BEFORE touching the queue — the reply is 429 'quota
    exceeded' (not 503 'overloaded') no matter what the queue looks
    like, and carries the bucket's own refill hint."""
    d1 = tmp_path / "linear"
    _publish(d1, "linear", epoch=0, seed=1)
    args = build_parser().parse_args([
        "--checkpoint-dir", str(d1), "--model", "linear",
        "--dtype", "f32", "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8", "--max-wait-ms", "2", "--max-queue", "4",
        "--poll-interval", "5",
        "--quota-rps", "interactive=1",
        "--no-fuse",  # split-plane boot: nothing fused is pinned here
    ])
    srv = _Server(args)
    try:
        images = [[0] * 28] * 28
        codes = []
        for _ in range(6):
            code, body, headers = srv.post_raw("/predict", {
                "images": images, "client_id": "pz",
                "priority": "interactive"})
            codes.append(code)
            if code == 429:
                assert body["error"] == "quota exceeded"
                assert "Retry-After" in headers
        # Burst (2s x 1 rps = 2 tokens) admits the first two, then the
        # bucket — not the queue — refuses.
        assert codes[:2] == [200, 200]
        assert 429 in codes and 503 not in codes
        stats = srv.get("/stats")
        assert stats["quota"]["rejected"] >= 1
        assert stats["classes"]["interactive"]["quota_rejected"] >= 1
        # Quota refusals are the client's overload, not admission
        # control's: the lifetime rejected counter stays 0.
        assert stats["rejected"] == 0
    finally:
        srv.close()
