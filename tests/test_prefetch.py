"""Epoch-gather pipelining (train/trainer.py): prefetched trajectories must
be bit-identical to synchronous ones — overlap is a latency optimization,
never a semantics change (round-2 VERDICT weak #6).
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer


def _setup(seed=0, n=128, bs=32):
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(n) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    train = MNISTDataLoader(images, labels, batch_size=bs, train=True, seed=7)
    test = MNISTDataLoader(images, labels, batch_size=bs, train=False, seed=7)
    return state, train, test


def _run_epochs(prefetch: bool, epochs=3):
    state, train, test = _setup()
    trainer = Trainer(state, train, test, mode="scan")
    trainer.prefetch_enabled = prefetch
    history = []
    for epoch in range(epochs):
        train.set_sample_epoch(epoch)
        loss, acc = trainer.train()
        tloss, tacc = trainer.evaluate()
        history.append((loss.average, acc.accuracy,
                        tloss.average, tacc.accuracy))
    return trainer.state, history


def test_prefetched_trajectory_bitwise_equals_synchronous():
    s_pre, h_pre = _run_epochs(True)
    s_syn, h_syn = _run_epochs(False)
    assert h_pre == h_syn  # exact float equality: same programs, same data
    for a, b in zip(jax.tree.leaves(s_pre.params),
                    jax.tree.leaves(s_syn.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stale_prefetch_discarded_on_epoch_jump():
    """A caller that jumps epochs (resume) invalidates the staged gather;
    the data used must be the jumped-to epoch's, not the predicted one."""
    state, train, test = _setup()
    trainer = Trainer(state, train, test, mode="scan")
    train.set_sample_epoch(0)
    trainer.train()                    # stages epoch 1 in the background
    train.set_sample_epoch(5)          # resume-style jump
    trainer.train()                    # must discard the epoch-1 stage

    # Reference trajectory: same two epochs, no prefetch.
    state2, train2, test2 = _setup()
    t2 = Trainer(state2, train2, test2, mode="scan")
    t2.prefetch_enabled = False
    train2.set_sample_epoch(0)
    t2.train()
    train2.set_sample_epoch(5)
    t2.train()
    for a, b in zip(jax.tree.leaves(trainer.state.params),
                    jax.tree.leaves(t2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_eval_staging_is_cached_and_correct():
    """The eval stage is gathered exactly once and reused; metrics remain
    equal to a fresh-gather evaluation every epoch."""
    state, train, test = _setup()
    trainer = Trainer(state, train, test, mode="scan")
    l1, a1 = trainer.evaluate()
    assert trainer._eval_staged is not None
    staged_id = id(trainer._eval_staged)
    l2, a2 = trainer.evaluate()
    assert id(trainer._eval_staged) == staged_id  # reused, not re-gathered
    assert (l1.average, a1.accuracy) == (l2.average, a2.accuracy)
