"""InferenceEngine: bucket padding exactness, the evaluate-vs-engine
logits pin (one forward-program builder for both), zero steady-state
recompiles, hot-swap atomicity mid-batch."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_forward_program
from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog, compile_log

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def linear_setup():
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    images, labels = synthetic_dataset(64, seed=3)
    return model, state, images, labels


def _direct_logits(model, state, raw_images):
    """The evaluate path's forward: the shared builder applied to the
    training-normalized batch, full precision of the real batch size."""
    fwd = make_forward_program(model.apply)
    return np.asarray(fwd(state.params, jnp.asarray(
        normalize_images(raw_images))))


def test_bucket_padding_does_not_change_real_rows(linear_setup):
    """Padded rows must not perturb real rows' logits, across every
    bucket boundary (1..9 rows against buckets 4/8)."""
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(4, 8))
    engine.warmup()
    for n in range(1, 10):
        got = engine.logits(images[:n])
        want = _direct_logits(model, state, images[:n])
        assert got.shape == want.shape == (n, 10)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_exact_bucket_is_bitwise_identical(linear_setup):
    """n == bucket: identical program, identical shapes -> the engine's
    logits are the eval forward's logits bit for bit."""
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(8,))
    engine.warmup()
    got = engine.logits(images[:8])
    want = _direct_logits(model, state, images[:8])
    np.testing.assert_array_equal(got, want)


def test_evaluate_and_engine_agree(linear_setup):
    """The satellite pin: -e/--evaluate and the serve engine share ONE
    forward-program builder, so their accuracies over the same test set
    are identical — preprocessing, dtype policy, and forward math cannot
    drift apart."""
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.train.trainer import Trainer

    model, state, images, labels = linear_setup
    norm = normalize_images(images)
    loader = MNISTDataLoader(norm, labels.astype(np.int32), batch_size=16,
                             train=False)
    trainer = Trainer(state, loader, loader, mode="scan")
    _, eval_acc = trainer.evaluate()

    engine = InferenceEngine(model.apply, state.params, buckets=(16,))
    engine.warmup()
    preds = engine.predict(images)  # raw uint8 in: engine normalizes
    engine_acc = float((preds == labels).mean())
    np.testing.assert_allclose(engine_acc, eval_acc.accuracy, atol=1e-9)

    # And per-row logits agree with the eval-path program exactly.
    np.testing.assert_allclose(
        engine.logits(images), _direct_logits(model, state, images),
        rtol=1e-6, atol=1e-6)


def test_zero_recompiles_steady_state(linear_setup):
    """After warmup, serving any admissible batch size — including
    oversized chunked batches — triggers ZERO further XLA compiles."""
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(1, 4, 8))
    engine.warmup()
    compiled_programs = {f"serve_forward_b{b}" for b in (1, 4, 8)}
    stats = compile_log.stats()["programs"]
    assert compiled_programs <= set(stats)
    baseline = compile_log.stats()["totals"]["backend_compiles"]
    for n in (1, 2, 3, 4, 5, 8, 11, 16, 20):  # 11/16/20 chunk through 8
        out = engine.logits(images[:n])
        assert out.shape == (n, 10)
    assert compile_log.stats()["totals"]["backend_compiles"] == baseline


def test_oversized_batch_chunks_match_direct(linear_setup):
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(4,))
    engine.warmup()
    got = engine.logits(images[:11])  # 4 + 4 + 3(padded)
    np.testing.assert_allclose(got, _direct_logits(model, state, images[:11]),
                               rtol=1e-6, atol=1e-6)


def test_swap_params_changes_predictions(linear_setup):
    model, state, images, _ = linear_setup
    other = create_train_state(model, jax.random.key(123))
    engine = InferenceEngine(model.apply, state.params, buckets=(8,),
                             params_epoch=0)
    engine.warmup()
    before = engine.logits(images[:8])
    engine.swap_params(other.params, epoch=7)
    assert engine.params_epoch == 7
    after = engine.logits(images[:8])
    assert not np.allclose(before, after)
    np.testing.assert_allclose(
        after, np.asarray(make_forward_program(model.apply)(
            other.params, jnp.asarray(normalize_images(images[:8])))),
        rtol=1e-6, atol=1e-6)


def test_swap_mid_batch_finishes_on_old_params(linear_setup):
    """The hot-reload atomicity contract: a batch captures its params at
    call entry; a swap landing while the forward runs does not leak the
    new params into the in-flight batch, and the next batch sees them."""
    model, state, images, _ = linear_setup
    other = create_train_state(model, jax.random.key(123))
    engine = InferenceEngine(model.apply, state.params, buckets=(8,))
    engine.warmup()
    want_old = engine.logits(images[:8])
    engine.swap_params(state.params)  # reset after the probe above

    entered = threading.Event()
    proceed = threading.Event()
    real = engine._compiled[8]

    def gated(params, x):
        entered.set()
        assert proceed.wait(30.0), "test deadlock"
        return real(params, x)

    engine._compiled[8] = gated
    results = {}

    def infer():
        results["old"] = engine.logits(images[:8])

    t = threading.Thread(target=infer, daemon=True)
    t.start()
    assert entered.wait(10.0)
    engine.swap_params(other.params, epoch=9)  # swap while in flight
    proceed.set()
    t.join(30.0)
    engine._compiled[8] = real
    # The in-flight batch computed with the OLD params it captured...
    np.testing.assert_array_equal(results["old"], want_old)
    # ...and the very next batch runs on the new ones.
    want_new = np.asarray(make_forward_program(model.apply)(
        other.params, jnp.asarray(normalize_images(images[:8]))))
    np.testing.assert_allclose(engine.logits(images[:8]), want_new,
                               rtol=1e-6, atol=1e-6)


def test_batch_histogram_records_buckets(linear_setup):
    model, state, images, _ = linear_setup
    log = ServeLog()
    engine = InferenceEngine(model.apply, state.params, buckets=(2, 8),
                             serve_log=log)
    engine.warmup()
    engine.logits(images[:1])  # -> bucket 2
    engine.logits(images[:2])  # -> bucket 2
    engine.logits(images[:5])  # -> bucket 8
    snap = log.snapshot()
    assert snap["batch_histogram"] == {"2": 2, "8": 1}
    assert snap["batches"] == 3


def test_preprocess_rejects_garbage(linear_setup):
    model, state, _, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(2,))
    with pytest.raises(ValueError, match="expected"):
        engine.preprocess(np.zeros((2, 13, 13), np.uint8))
    with pytest.raises(ValueError, match="expected"):
        engine.preprocess(np.zeros((2, 28, 28, 3), np.float32))


def test_stale_swap_rejected_under_lock(linear_setup):
    """The swap-ordering guarantee, sequential form: once epoch 7 is
    installed, an epoch-3 install attempt is refused and changes
    nothing."""
    model, state, images, _ = linear_setup
    newer = create_train_state(model, jax.random.key(41))
    engine = InferenceEngine(model.apply, state.params, buckets=(8,),
                             params_epoch=5)
    engine.warmup()
    assert engine.swap_params(newer.params, epoch=7) is True
    want = engine.logits(images[:8])
    assert engine.swap_params(state.params, epoch=3) is False
    assert engine.params_epoch == 7
    np.testing.assert_array_equal(engine.logits(images[:8]), want)
    # Epoch-less swaps (fresh init, tests) are exempt from ordering.
    assert engine.swap_params(state.params) is True
    assert engine.params_epoch is None


def test_swap_race_old_never_overwrites_new(linear_setup):
    """The reload/swap ordering hazard, raced: an OLD swap whose (slow,
    unlocked) device_put straddles a NEW swap's install must lose — the
    epoch comparison under the lock, not device_put timing, decides."""
    model, state, images, _ = linear_setup
    old = create_train_state(model, jax.random.key(1))
    new = create_train_state(model, jax.random.key(2))
    engine = InferenceEngine(model.apply, state.params, buckets=(8,),
                             params_epoch=0)
    engine.warmup()
    real_place = engine._place
    old_placed = threading.Event()
    proceed = threading.Event()

    def gated_place(tree):
        placed = real_place(tree)
        if tree is old.params:
            # The old swap pauses BETWEEN its device_put and its
            # install — the exact window the hazard lives in.
            old_placed.set()
            assert proceed.wait(30.0), "test deadlock"
        return placed

    engine._place = gated_place
    outcome = {}
    t = threading.Thread(
        target=lambda: outcome.update(
            old=engine.swap_params(old.params, epoch=3)), daemon=True)
    t.start()
    assert old_placed.wait(10.0)
    assert engine.swap_params(new.params, epoch=7) is True
    proceed.set()
    t.join(10.0)
    engine._place = real_place
    assert outcome["old"] is False  # the stale install was refused
    assert engine.params_epoch == 7
    np.testing.assert_allclose(
        engine.logits(images[:8]),
        np.asarray(make_forward_program(model.apply)(
            new.params, jnp.asarray(normalize_images(images[:8])))),
        rtol=1e-6, atol=1e-6)


def test_exact_bucket_fast_path_skips_staging(linear_setup):
    """n == bucket with float32 C-contiguous input: no staging buffer is
    touched (the no-copy fast path), and the logits stay BITWISE equal
    to the direct eval forward — extending the exactness suite over the
    staging-reuse change."""
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(8,))
    engine.warmup()
    for _ in range(3):
        got = engine.logits(engine.preprocess(images[:8]))
        np.testing.assert_array_equal(
            got, _direct_logits(model, state, images[:8]))
    assert engine.staging_allocated()[8] == 0  # never staged


def test_staging_buffers_reused_not_reallocated(linear_setup):
    """Steady-state padded serving allocates NO per-batch pad buffer: the
    synchronous path holds the per-bucket pool at ONE buffer however
    many batches run, and results stay exact."""
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(4, 8))
    engine.warmup()
    for i in range(12):
        n = 1 + (i % 7)  # every padded size across both buckets
        got = engine.logits(images[:n])
        np.testing.assert_allclose(
            got, _direct_logits(model, state, images[:n]),
            rtol=1e-6, atol=1e-6)
    allocated = engine.staging_allocated()
    assert allocated[4] == 1 and allocated[8] == 1


def test_staging_pinned_until_complete(linear_setup):
    """Dispatch/complete split: a dispatched-but-unfetched batch keeps
    its staging buffer out of the free-list (reusing it would corrupt
    the in-flight input on aliasing backends); completion returns it."""
    model, state, images, _ = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(8,))
    engine.warmup()
    first = engine.dispatch_logits(images[:3])
    assert engine.staging_allocated()[8] == 1
    second = engine.dispatch_logits(images[3:6])  # first still pinned
    assert engine.staging_allocated()[8] == 2  # had to grow, not reuse
    got1, _ = first.complete()
    got2, _ = second.complete()
    np.testing.assert_allclose(got1, _direct_logits(model, state, images[:3]),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(got2, _direct_logits(model, state,
                                                    images[3:6]),
                               rtol=1e-6, atol=1e-6)
    # Both released: the next padded batch reuses, the pool stays at 2.
    engine.logits(images[:2])
    assert engine.staging_allocated()[8] == 2


def test_device_pinned_engine_matches_default(linear_setup):
    """An engine pinned to a non-default device computes the same
    program: logits identical to the default-placement engine, and its
    compiled executables live on that device."""
    model, state, images, _ = linear_setup
    device = jax.local_devices()[3]
    pinned = InferenceEngine(model.apply, state.params, buckets=(8,),
                             device=device, name="r3")
    pinned.warmup()
    got = pinned.logits(images[:8])
    np.testing.assert_array_equal(got,
                                  _direct_logits(model, state, images[:8]))
    # The pinned engine's programs are attributed per replica name.
    stats = compile_log.stats()["programs"]
    assert "serve_forward_b8@r3" in stats
