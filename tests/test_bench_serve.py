"""End-to-end smoke of ``bench.py --mode serve`` on a forced 4-device
CPU backend: the report must carry the replica scaling curve and the
pipeline on/off speedup with the per-replica zero-recompile verdicts —
so the serving BENCH schema can't silently rot while CI only exercises
the in-process pieces."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.serve, pytest.mark.slow]


def test_bench_serve_reports_scaling_and_pipeline_fields():
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "BENCH_FORCE_CPU": "1",
        # Small drives: this asserts SCHEMA, not throughput. The compile
        # cache stays off — the bench child both writes and re-reads
        # entries in one process, the exact pattern DESIGN.md 6c bans.
        "BENCH_SERVE_REQUESTS": "64",
        "BENCH_SERVE_POOL_REQUESTS": "64",
        "BENCH_SERVE_FUSED_REQUESTS": "48",
        "BENCH_SERVE_CONCURRENCY": "8",
        "BENCH_FLEET_SECONDS": "0.6",
        "BENCH_FLEET_PAIRS": "2",
        "BENCH_FLEET_REQUESTS": "24",
        "BENCH_ECONOMICS_SECONDS": "0.6",
        "BENCH_ECONOMICS_REQUESTS": "48",
        "BENCH_COMPILE_CACHE": "",
        "TPUMNIST_COMPILE_CACHE": "",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode", "serve"],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])

    assert report["metric"] == "mnist_serve_requests_per_sec"
    assert report.get("error") is None
    assert report["value"] > 0
    assert report["n_chips"] == 4

    # The replica scaling curve: one point per replica count, each with
    # a positive rate and a per-point zero-recompile verdict.
    scaling = report["replica_scaling"]
    assert [pt["replicas"] for pt in scaling] == [1, 2, 4]
    for pt in scaling:
        assert pt["requests_per_sec"] > 0
        assert pt["zero_steady_state_recompiles"] is True

    # Pipeline on/off speedup at the full pool, and the fleet-wide
    # recompile verdict.
    assert isinstance(report["pipeline_speedup"], (int, float))
    assert report["pipeline_speedup"] > 0
    assert report["zero_steady_state_recompiles"] is True
    assert report["zero_steady_state_recompiles_per_replica"] is True

    # Per-replica compile rows really are per replica in the stats blob.
    programs = report["compile_stats"]["programs"]
    assert any(name.endswith("@r3") for name in programs)
    assert any(name.endswith("@r0") for name in programs)

    # The sharded block: one entry per mode (tensor x vit, expert x
    # moe_mlp) with the ABBA-paired vs-replicated ratio, the
    # mesh-scaling curve at fixed chip count, and the per bucket x mode
    # zero-recompile verdict. This CPU run is a forced-multi-device
    # world with the Eigen isolation, so it must carry the
    # BENCH_r05-style fallback caveat.
    sharded = report["sharded"]
    assert report["cpu_serve_devices_isolated"] is True
    assert "CPU fallback" in sharded["caveat"]
    for mode, model_name in (("tensor", "vit"), ("expert", "moe_mlp")):
        block = sharded[mode]
        assert block["model"] == model_name
        assert block["requests_per_sec"] > 0
        assert block["vs_replicated"] > 0
        assert len(block["pairs"]) == 4
        curve = block["mesh_scaling"]
        assert [pt["mesh_devices"] for pt in curve] == [1, 2, 4]
        assert [pt["mesh_groups"] for pt in curve] == [4, 2, 1]
        assert all(pt["requests_per_sec"] > 0 for pt in curve)
        assert block["zero_steady_state_recompiles"] is True
    # Per bucket x mode compile rows landed under the @{mode} names.
    assert any("@tensor" in name for name in programs)
    assert any("@expert" in name for name in programs)

    # The MPMD pipeline block (ISSUE 12): one chain of per-chip stage
    # programs, the window>=stages vs window-1 stage-overlap speedup
    # (ABBA pairs), per-stage step walls + occupancy with the bottleneck
    # stage at 1.0, and the per bucket x stage zero-recompile verdict.
    # The engine-factory mode must NOT appear in the SPMD sharded block.
    assert "pipeline" not in sharded
    pp = report["pipeline_serving"]
    assert pp["model"] == "vit" and pp["stages"] == 2
    assert pp["window"] == 3 and pp["chains"] == 1
    assert isinstance(pp["stage_overlap_speedup"], (int, float))
    assert pp["stage_overlap_speedup"] > 0
    assert len(pp["pairs"]) == 5
    assert pp["requests_per_sec"] > 0
    assert sorted(pp["stage_step_ms"]) == ["s0", "s1"]
    occ = pp["stage_occupancy"]
    assert sorted(occ) == ["s0", "s1"] and max(occ.values()) == 1.0
    assert pp["zero_steady_state_recompiles"] is True
    # This CPU run must carry the BENCH_r05-style fallback caveat:
    # host-thread transfers say nothing about ICI.
    assert "CPU fallback" in pp["caveat"]
    assert "nothing about ICI" in pp["caveat"]
    # Per bucket x stage compile rows landed under the @pipeline names.
    assert any("@pipeline.s0" in name for name in programs)
    assert any("@pipeline.s1" in name for name in programs)

    # The precision sweep (ISSUE 14): one entry per registered
    # quantized precision with the ABBA-paired vs-f32 ratio, the
    # eval-batch agreement/accuracy deltas, and the per bucket x mode x
    # precision zero-recompile verdicts; CPU runs carry the BENCH_r05-
    # style caveat (host int8 says little about the TPU MXU/ICI).
    sweep = report["precision_sweep"]
    assert "CPU fallback" in sweep["caveat"]
    assert "MXU" in sweep["caveat"]
    assert isinstance(sweep["f32_accuracy"], float)
    for prec in ("bf16", "int8w", "int8"):
        block = sweep[prec]
        assert block["vs_f32"] > 0 and len(block["pairs"]) == 4
        assert block["requests_per_sec"] > 0
        assert 0.9 <= block["argmax_agreement_vs_f32"] <= 1.0
        assert isinstance(block["accuracy_delta_vs_f32"], float)
        assert block["max_logit_delta_vs_f32"] >= 0
        assert block["zero_steady_state_recompiles"] is True
    # Every registered mode x quantized precision got a verdict (the
    # LIVE registry, engine-factory modes included).
    modes = sweep["modes"]
    for mode in ("tensor", "expert", "pipeline"):
        for prec in ("bf16", "int8w", "int8"):
            assert modes[f"{mode}.{prec}"][
                "zero_steady_state_recompiles"] is True
    # Per bucket x precision compile rows landed under the .{prec} names.
    assert any(name.endswith("@bf16") for name in programs)
    assert any("@tensor.int8w" in name for name in programs)
    assert any("@pipeline.int8.s0" in name for name in programs)

    # The whole-program block (ISSUE 16): one fused ViT engine serving
    # both routes — the ABBA-paired fused-over-split ratio, the
    # host-work collapse, the staged-bytes ratio (float32 vs raw uint8
    # = 4x), donated-staging retirement, and the zero-recompile verdict
    # across BOTH planes. The fused compile rows carry the .fused tag
    # inside the bucket segment.
    wp = report["whole_program"]
    assert wp["model"] == "vit" and wp["images_per_request"] == 8
    assert wp["fused_over_split_speedup"] > 0
    assert len(wp["pairs"]) == 4
    assert wp["requests_per_sec"] > 0
    host = wp["host_preprocess_ms_per_request"]
    # The collapse itself: raw passthrough beats host normalization.
    assert host["fused"] < host["split"]
    bytes_ = wp["h2d_bytes_per_request"]
    assert bytes_["split"] == 8 * 28 * 28 * 4
    assert bytes_["fused"] == 8 * 28 * 28
    assert bytes_["ratio"] == 4.0
    assert wp["model_flops_per_image"] > 0
    assert wp["mfu"] is None  # no honest peak to divide by on CPU
    assert wp["donated_staging_retired"]["8"] > 0  # JSON keys: strings
    assert wp["zero_steady_state_recompiles"] is True
    assert "CPU fallback" in wp["caveat"]
    assert any(".fused@wp" in name for name in programs)

    # The overload block (ISSUE 15): goodput-vs-offered-load curve
    # through the priority batcher, per-class completions + p99, the
    # 70%-of-peak and interactive-below-batch verdicts, and the
    # autoscaler-actuation recompile verdict — all of which FAIL the
    # bench (exit 1) when violated.
    over = report["overload"]
    assert over["capacity_rps"] > 0
    assert [pt["offered_x"] for pt in over["points"]] == [1, 2, 5, 10]
    for pt in over["points"]:
        assert pt["offered_rps"] > 0
        assert pt["goodput_rps"] > 0
        assert set(pt["classes"]) <= {"interactive", "batch",
                                      "best_effort"}
    assert over["peak_goodput_rps"] > 0
    assert over["goodput_holds_at_overload"] is True
    assert over["interactive_p99_below_batch_p99"] is True
    top = over["points"][-1]
    # 10x offered load really was overload: most of it was shed, and
    # best_effort shed proportionally hardest (the watermark order).
    assert top["shed"] > top["completed"]
    auto = over["autoscale"]
    assert auto["actuated"] is True
    assert auto["zero_steady_state_recompiles_across_resizes"] is True
    assert [d["action"] for d in auto["resizes"]] == [
        "scale_up", "scale_down"]
    assert "CPU fallback" in over["caveat"]

    # The fleet block (ISSUE 17): two real loopback backends behind a
    # real router — the ABBA-paired routed-vs-direct overhead, the
    # open-loop goodput curve THROUGH the router (same 70%-of-peak
    # shed-not-collapse rule as the single-process block), and the
    # per-backend zero-recompile verdict across every routed drive.
    fleet = report["fleet"]
    assert fleet["ok"] is True
    assert fleet["backends"] == 2
    over_f = fleet["router_overhead"]
    assert over_f["pairs"] == 2
    assert over_f["direct_p50_ms"] > 0
    assert over_f["routed_p50_ms"] > 0
    assert over_f["p50_overhead_ratio"] > 0
    assert over_f["p99_overhead_ratio"] > 0
    good = fleet["goodput"]
    assert good["capacity_rps"] > 0
    assert len(good["points"]) == 2
    assert good["points"][0]["offered_x"] == 1.0
    assert good["points"][-1]["offered_x"] > 1.0
    assert all(pt["goodput_rps"] > 0 for pt in good["points"])
    assert good["holds_at_overload"] is True
    # Side-by-side with the single-process overload verdict.
    assert good["single_process_fraction_of_peak"] == \
        over["goodput_at_top_fraction_of_peak"]
    assert fleet["zero_steady_state_recompiles_per_backend"] is True
    assert fleet["router_stats"]["routable"] == 2
    assert "CPU fallback" in fleet["caveat"]

    # The economics block (ISSUE 19): zipf-duplicate drive through the
    # response cache — measured hit/miss p99 split, the warm-cache
    # goodput curve holding the 96%-of-peak bar at ~10x, the collapse
    # ratio, the live server cache + measured cost table, and the
    # zero-recompile verdict on the cached path.
    econ = report["economics"]
    assert econ["ok"] is True
    zd = econ["zipf_drive"]
    assert zd["zipf_exponent"] == 1.1
    assert zd["hit_rate"] > 0
    assert zd["hit_p99_ms"] > 0 and zd["miss_p99_ms"] > 0
    assert zd["hit_is_cheap"] is True
    assert zd["enforced_bar"] == 1.0  # the CPU bar; 0.1 on TPU
    good_e = econ["goodput"]
    assert good_e["capacity_rps"] > 0
    # The top point targets 10x but the open-loop rate is clamped at
    # 1500 rps, so on a fast cached path offered_x lands lower.
    assert good_e["points"][0]["offered_x"] == 1.0
    assert good_e["points"][-1]["offered_x"] > 1.0
    assert good_e["holds_at_overload"] is True
    assert good_e["single_process_fraction_of_peak"] == \
        over["goodput_at_top_fraction_of_peak"]
    assert econ["zero_steady_state_recompiles"] is True
    assert econ["collapse_ratio"] >= 0
    assert econ["server_cache"]["hits"] > 0
    assert econ["cost_model"]["buckets"] == [1, 8]
    assert "CPU fallback" in econ["caveat"]


def test_bench_serve_overload_verdicts_fail_loudly():
    """The overload verdicts really carry teeth: the injected failure
    hook (mirroring BENCH_ZERO_INJECT_RECOMPILE) must turn the line
    into exit 1 with the overload error named."""
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "BENCH_FORCE_CPU": "1",
        "BENCH_SERVE_REQUESTS": "64",
        "BENCH_SERVE_POOL_REQUESTS": "64",
        "BENCH_SERVE_CONCURRENCY": "8",
        "BENCH_SERVE_PRECISION_REQUESTS": "32",
        "BENCH_OVERLOAD_SECONDS": "0.5",
        "BENCH_OVERLOAD_POINTS": "1,2",
        "BENCH_OVERLOAD_INJECT_FAIL": "1",
        "BENCH_FLEET_SECONDS": "0.5",
        "BENCH_FLEET_PAIRS": "2",
        "BENCH_FLEET_REQUESTS": "16",
        "BENCH_FLEET_INJECT_FAIL": "1",
        "BENCH_ECONOMICS_SECONDS": "0.5",
        "BENCH_ECONOMICS_REQUESTS": "32",
        "BENCH_ECONOMICS_INJECT_FAIL": "1",
        "BENCH_COMPILE_CACHE": "",
        "TPUMNIST_COMPILE_CACHE": "",
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode",
         "serve"],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "overload" in report["error"]
    assert report["overload"]["goodput_holds_at_overload"] is False
    # The fleet injection hook carries teeth too (the overload error
    # outranks it in the message, but the verdict and exit gate hold).
    assert report["fleet"]["ok"] is False
    assert report["economics"]["ok"] is False
