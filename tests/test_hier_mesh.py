"""Two-tier (DCN x ICI) hierarchical meshes and the tier-aware ZeRO
schedule.

The contract: ``make_hier_mesh`` builds data-major ``('dcn', 'ici',
...)`` meshes from real slice topology (``device.slice_index``) or the
emulated ``TPUMNIST_DCN_SLICES`` map, ``data_replica_coords`` groups
hosts by the COMPOSED data axis, model axes pin inside one slice
(DCN-straddling layouts rejected with flag language), and the two-tier
ZeRO schedule — reduce-scatter over ``ici``, owner-shard all-reduce
over ``dcn``, allgather back over ``ici``, per-tier bucket budgets —
changes WHERE communication happens, never WHAT the training computes:
a 2x2 emulated hierarchy is trajectory-equal to the flat 4-device
propagation AND overlap paths, end to end through the cli, and the
same checkpoints load both ways.
"""

import os
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import (
    DCN_SLICES_ENV,
    HIER_DATA_AXES,
    _slice_blocks,
    data_replica_coords,
    data_sharding,
    device_slice_map,
    infer_dcn_slices,
    is_hier_mesh,
    make_hier_mesh,
    make_mesh,
    resolve_data_axis,
)
from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
    _dcn_bucket_plan,
    _shard_dims,
    make_comm_only_program,
    make_overlap_train_epoch,
    make_overlap_train_step,
    make_param_gather,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


def _batch(seed, n=64):
    r = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(r.normal(size=(n, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(r.integers(0, 10, size=(n,)), jnp.int32),
    }


def _assert_trees_close(a, b, rtol=2e-4, atol=2e-5):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


# -- make_hier_mesh: shape matrix --------------------------------------------


def test_hier_mesh_shapes():
    for slices, ici in [(2, 4), (4, 2), (8, 1)]:
        mesh = make_hier_mesh(slices)
        assert mesh.axis_names == HIER_DATA_AXES
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) \
            == {"dcn": slices, "ici": ici}
        assert is_hier_mesh(mesh)
    assert not is_hier_mesh(make_mesh(("data",)))


def test_hier_mesh_device_subset_2x2():
    mesh = make_hier_mesh(2, devices=jax.devices()[:4])
    assert mesh.devices.shape == (2, 2)
    # Emulated slices are contiguous blocks of the given order — the
    # data-major layout every sharder here assumes.
    assert [d.id for d in mesh.devices.flat] == [0, 1, 2, 3]


def test_hier_mesh_model_axes_nest_inside_a_slice():
    mesh = make_hier_mesh(2, extra_axes=("model",), extra_shape=(2,))
    assert mesh.axis_names == ("dcn", "ici", "model")
    assert mesh.devices.shape == (2, 2, 2)
    # The model group is the innermost (fastest-varying) block: both of
    # a group's chips come from one slice.
    for s in range(2):
        slice_ids = {d.id for d in mesh.devices[s].flat}
        assert slice_ids == set(range(s * 4, s * 4 + 4))


def test_hier_mesh_rejection_matrix():
    with pytest.raises(ValueError, match="split into"):
        make_hier_mesh(3)
    with pytest.raises(ValueError, match=">= 1"):
        make_hier_mesh(0)
    with pytest.raises(ValueError, match="straddle"):
        # 4 slices of 2 chips cannot nest a width-4 model group.
        make_hier_mesh(4, extra_axes=("model",), extra_shape=(4,))
    with pytest.raises(ValueError, match="collides"):
        make_hier_mesh(2, extra_axes=("dcn",), extra_shape=(2,))
    with pytest.raises(ValueError, match="pair up"):
        make_hier_mesh(2, extra_axes=("model",), extra_shape=())
    with pytest.raises(ValueError, match="slice topology"):
        make_hier_mesh()  # no env, no slice_index: nothing to build on


def test_env_resolution(monkeypatch):
    monkeypatch.setenv(DCN_SLICES_ENV, "2")
    assert infer_dcn_slices() == 2
    mesh = make_hier_mesh()
    assert mesh.devices.shape == (2, 4)
    monkeypatch.setenv(DCN_SLICES_ENV, "nope")
    with pytest.raises(ValueError, match=DCN_SLICES_ENV):
        infer_dcn_slices()
    monkeypatch.delenv(DCN_SLICES_ENV)
    assert infer_dcn_slices() == 1  # CPU devices report no slice_index


def _fake(slice_index=None, pid=0, did=0):
    return SimpleNamespace(slice_index=slice_index, process_index=pid,
                           id=did)


def test_slice_blocks_orders_real_topology_slice_major():
    devs = [_fake(1, did=2), _fake(0, did=0), _fake(1, did=3),
            _fake(0, did=1)]
    ordered = _slice_blocks(devs, 2)
    assert [d.slice_index for d in ordered] == [0, 0, 1, 1]
    with pytest.raises(ValueError, match="distinct slice_index"):
        _slice_blocks(devs, 4)  # only 2 real slices exist
    uneven = [_fake(0), _fake(0), _fake(0), _fake(1)]
    with pytest.raises(ValueError, match="unequal slice sizes"):
        _slice_blocks(uneven, 2)


def test_validate_dcn_slices_catches_real_topology_mismatch():
    """The pre-construction validation cli.py runs: a slice count that
    DIVIDES the device count but contradicts the real slice topology
    must still be rejected (or, under an elastic rebuild, trigger the
    flat fallback) — not surface as a raw traceback at mesh build."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import (
        validate_dcn_slices,
    )

    devs = [_fake(i // 4, did=i) for i in range(8)]  # 2 real slices x 4
    validate_dcn_slices(2, devs)  # matches: fine
    with pytest.raises(ValueError, match="distinct slice_index"):
        validate_dcn_slices(4, devs)  # divides 8, contradicts topology
    with pytest.raises(ValueError, match="split into"):
        validate_dcn_slices(3, devs)
    validate_dcn_slices(2)  # the real (emulation-free) world: 8 CPU devs


# -- the composed data axis ---------------------------------------------------


def _grid(shape, proc_of_flat):
    n = int(np.prod(shape))
    devs = np.array(
        [SimpleNamespace(process_index=proc_of_flat(i)) for i in range(n)],
        dtype=object,
    ).reshape(shape)
    return devs


def test_data_replica_coords_composed_axis():
    # hier (dcn=2, ici=2) over 2 hosts, one slice per host: each host
    # covers a contiguous half of the composed data axis.
    fake = SimpleNamespace(axis_names=("dcn", "ici"),
                           devices=_grid((2, 2), lambda i: i // 2))
    assert data_replica_coords(fake, process_index=0) == (2, 0)
    assert data_replica_coords(fake, process_index=1) == (2, 1)
    # 4 single-device hosts: identity on the composed axis.
    fake4 = SimpleNamespace(axis_names=("dcn", "ici"),
                            devices=_grid((2, 2), lambda i: i))
    assert [data_replica_coords(fake4, process_index=p)
            for p in range(4)] == [(4, 0), (4, 1), (4, 2), (4, 3)]


def test_data_replica_coords_hier_model_axis():
    # (dcn=2, ici=1, model=2) over 2 hosts: a host's two chips differ
    # only along 'model' — one data replica per host.
    fake = SimpleNamespace(axis_names=("dcn", "ici", "model"),
                           devices=_grid((2, 1, 2), lambda i: i // 2))
    assert data_replica_coords(fake, process_index=0) == (2, 0)
    assert data_replica_coords(fake, process_index=1) == (2, 1)


def test_data_replica_coords_hier_real_mesh_single_process():
    assert data_replica_coords(make_hier_mesh(2), process_index=0) == (1, 0)


def test_data_sharding_and_resolve_on_hier_mesh():
    hier = make_hier_mesh(2)
    flat = make_mesh(("data",))
    assert resolve_data_axis(hier) == HIER_DATA_AXES
    assert resolve_data_axis(flat) == "data"
    assert resolve_data_axis(hier, "model") == "model"
    assert data_sharding(hier).spec == P(HIER_DATA_AXES)
    assert data_sharding(flat).spec == P("data")


def test_device_slice_map_emulated(monkeypatch):
    devs = jax.devices()
    assert device_slice_map(devs) is None  # no topology at all
    monkeypatch.setenv(DCN_SLICES_ENV, "2")
    assert device_slice_map(devs) == [0, 0, 0, 0, 1, 1, 1, 1]
    assert device_slice_map(devs[2:6]) == [0, 0, 1, 1]
    monkeypatch.setenv(DCN_SLICES_ENV, "3")  # does not divide: no map
    assert device_slice_map(devs) is None


def test_chaos_env_name_pinned():
    # tools/chaos.py spells the env out to stay jax-import-free.
    from tools import chaos

    assert chaos.DCN_SLICES_ENV == DCN_SLICES_ENV


def test_chaos_kill_slice_composes_fault_specs(monkeypatch):
    """``chaos.py --kill-slice S`` = SIGKILL every host of emulated
    slice S: the env + multi-fault composition the slice-loss twin in
    tests/test_elastic_chaos.py drives directly."""
    from tools import chaos

    # Register the keys main() mutates so monkeypatch restores them.
    monkeypatch.setenv("TPUMNIST_FAULT", "sentinel")
    monkeypatch.setenv(DCN_SLICES_ENV, "sentinel")
    monkeypatch.setenv("TPUMNIST_AGREEMENT_TIMEOUT", "300")
    captured = {}

    def fake_supervise(nprocs, cli_args, **kw):
        captured["nprocs"] = nprocs
        captured["fault"] = os.environ.get("TPUMNIST_FAULT")
        captured["slices"] = os.environ.get(DCN_SLICES_ENV)
        return 0

    monkeypatch.setattr(chaos, "supervise", fake_supervise)
    rc = chaos.main(["--elastic", "--dcn-slices", "2", "--kill-slice", "1",
                     "--nprocs", "4", "--", "--dataset", "synthetic"])
    assert rc == 0 and captured["nprocs"] == 4
    assert captured["slices"] == "2"
    # Slice 1 of 2 over 4 hosts = hosts 2 and 3, mid-epoch kills.
    assert captured["fault"] == "train_step:2:kill:5,train_step:3:kill:5"
    with pytest.raises(SystemExit, match="elastic"):
        chaos.main(["--kill-slice", "0", "--dcn-slices", "2"])
    with pytest.raises(SystemExit, match="divide"):
        chaos.main(["--elastic", "--dcn-slices", "3", "--nprocs", "4"])
    with pytest.raises(SystemExit, match="not one of"):
        chaos.main(["--elastic", "--dcn-slices", "2", "--kill-slice", "2",
                    "--nprocs", "4"])


# -- the DCN bucket plan budgets SHARD bytes ---------------------------------


def test_dcn_bucket_plan_budgets_shard_bytes():
    class _Leaf:
        def __init__(self, shape):
            self.shape = shape
            self.dtype = np.dtype(np.float32)

    # Two 1-MiB leaves: full-size they need a bucket each at 1 MiB, but
    # their 1/4 shards pack together into one 1-MiB DCN bucket.
    leaves = [_Leaf((1024, 256)), _Leaf((512, 512))]
    dims = _shard_dims(leaves, 4, "ici")
    from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
        bucket_plan,
    )

    assert len(bucket_plan(leaves, 1.0)) == 2
    assert len(_dcn_bucket_plan(leaves, dims, 4, 1.0)) == 1


# -- trajectory equality: 2x2 hier vs the flat 4-mesh ------------------------


@pytest.mark.parametrize("sharding", ["plain", "zero1", "zero3"])
def test_hier_propagation_matches_flat(sharding):
    """The acceptance matrix's propagation half: the SAME GSPMD step on
    the 2x2 emulated hierarchy and on the flat 4-mesh, 3 steps,
    params/moments/metrics equal (fp-order tolerance)."""
    devs = jax.devices()[:4]
    flat = make_mesh(("data",), devices=devs)
    hier = make_hier_mesh(2, devices=devs)
    model = get_model("linear", compute_dtype=jnp.float32)

    def build(mesh):
        st = create_train_state(model, jax.random.key(0))
        if sharding == "plain":
            return st, None
        return shard_state_zero(
            st, mesh, level=3 if sharding == "zero3" else 1)

    f_state, f_sh = build(flat)
    h_state, h_sh = build(hier)
    f_step = make_train_step(flat, state_sharding=f_sh)
    h_step = make_train_step(hier, state_sharding=h_sh)
    for i in range(3):
        b = _batch(i)
        f_state, fm = f_step(f_state, b)
        h_state, hm = h_step(h_state, b)
    np.testing.assert_allclose(float(fm.loss_sum), float(hm.loss_sum),
                               rtol=1e-5)
    assert float(fm.count) == float(hm.count)
    _assert_trees_close(f_state.params, h_state.params)
    _assert_trees_close(f_state.opt_state, h_state.opt_state)


@pytest.mark.parametrize("level", [1, 3])
def test_two_tier_overlap_matches_flat_overlap_and_propagation(level):
    """THE acceptance equivalence: the two-tier overlapped schedule on
    the 2x2 emulated hierarchy vs the flat 4-device overlap path vs the
    flat propagation path — independent per-tier buckets exercised
    (bucket_mb_dcn != bucket_mb), same trajectory everywhere."""
    devs = jax.devices()[:4]
    flat = make_mesh(("data",), devices=devs)
    hier = make_hier_mesh(2, devices=devs)
    model = get_model("linear", compute_dtype=jnp.float32)

    prop, prop_sh = shard_state_zero(
        create_train_state(model, jax.random.key(0)), flat, level=level)
    prop_step = make_train_step(flat, state_sharding=prop_sh)

    fo, _ = shard_state_zero(
        create_train_state(model, jax.random.key(0)), flat, level=level)
    fo_step = make_overlap_train_step(fo, flat, level=level, bucket_mb=0.5)
    fo_g = make_param_gather(flat)(fo.params) if level == 3 else None

    tt, _ = shard_state_zero(
        create_train_state(model, jax.random.key(0)), hier, level=level)
    tt_step = make_overlap_train_step(tt, hier, level=level, bucket_mb=0.5,
                                      bucket_mb_dcn=0.125)
    tt_g = make_param_gather(hier)(tt.params) if level == 3 else None

    for i in range(3):
        b = _batch(i)
        prop, pm = prop_step(prop, b)
        if level == 3:
            fo, fo_g, fom = fo_step(fo, fo_g, b)
            tt, tt_g, ttm = tt_step(tt, tt_g, b)
        else:
            fo, fom = fo_step(fo, b)
            tt, ttm = tt_step(tt, b)
    np.testing.assert_allclose(float(pm.loss_sum), float(ttm.loss_sum),
                               rtol=1e-5)
    np.testing.assert_allclose(float(fom.loss_sum), float(ttm.loss_sum),
                               rtol=1e-5)
    assert float(pm.count) == float(ttm.count) == float(fom.count)
    _assert_trees_close(prop.params, tt.params)
    _assert_trees_close(fo.params, tt.params)
    _assert_trees_close(prop.opt_state, tt.opt_state)


def test_two_tier_scan_epoch_and_carry_invariant():
    """ZeRO-3 two-tier through the scan epoch: trajectory equal to the
    flat overlap epoch, and the carried gathered copy leaving the epoch
    IS allgather(shards) — the invariant the Trainer relies on."""
    devs = jax.devices()[:4]
    flat = make_mesh(("data",), devices=devs)
    hier = make_hier_mesh(2, devices=devs)
    model = get_model("linear", compute_dtype=jnp.float32)
    r = np.random.default_rng(7)
    batches = {
        "image": jnp.asarray(r.normal(size=(4, 64, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(r.integers(0, 10, size=(4, 64)), jnp.int32),
    }

    f, _ = shard_state_zero(
        create_train_state(model, jax.random.key(1)), flat, level=3)
    f_epoch = make_overlap_train_epoch(f, flat, level=3, bucket_mb=0.5)
    f_g = make_param_gather(flat)(f.params)
    f, f_g, fm = f_epoch(f, f_g, batches)

    h, _ = shard_state_zero(
        create_train_state(model, jax.random.key(1)), hier, level=3)
    h_epoch = make_overlap_train_epoch(h, hier, level=3, bucket_mb=0.5,
                                       bucket_mb_dcn=0.25)
    h_g = make_param_gather(hier)(h.params)
    copies = jax.tree_util.tree_map(jnp.copy, batches)
    h, h_g, hm = h_epoch(h, h_g, copies)

    np.testing.assert_allclose(float(fm.loss_sum), float(hm.loss_sum),
                               rtol=1e-5)
    _assert_trees_close(f.params, h.params)
    full = make_param_gather(hier)(h.params)
    for a, c in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(h_g)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


def test_hier_state_layout_shards_over_ici_only():
    """The hierarchical ZeRO layout: shard specs name 'ici' alone —
    replicated across slices (the 2004.13336 multi-pod partition), so
    only 1/ici_size owner shards ever cross DCN."""
    hier = make_hier_mesh(2)
    model = get_model("linear", compute_dtype=jnp.float32)
    state, sharding = shard_state_zero(
        create_train_state(model, jax.random.key(0)), hier, level=3)
    axes_used = set()
    for ns in jax.tree_util.tree_leaves(sharding):
        for entry in ns.spec:
            if entry is not None:
                axes_used.add(entry)
    assert axes_used == {"ici"}


# -- per-tier comm twins ------------------------------------------------------


def test_comm_only_tier_programs():
    hier = make_hier_mesh(2, devices=jax.devices()[:4])
    model = get_model("linear", compute_dtype=jnp.float32)
    z, _ = shard_state_zero(
        create_train_state(model, jax.random.key(0)), hier, level=3)
    full = make_param_gather(hier)(z.params)
    for tier in (None, "ici", "dcn"):
        prog = make_comm_only_program(z, hier, bucket_mb=0.5,
                                      bucket_mb_dcn=0.25, tier=tier)
        assert np.isfinite(float(prog(full))), tier


def test_comm_only_tier_rejected_on_flat_mesh():
    flat = make_mesh(("data",), devices=jax.devices()[:4])
    model = get_model("linear", compute_dtype=jnp.float32)
    z, _ = shard_state_zero(
        create_train_state(model, jax.random.key(0)), flat, level=3)
    with pytest.raises(ValueError, match="hierarchical"):
        make_comm_only_program(z, flat, tier="ici")
    with pytest.raises(ValueError, match="tier must be"):
        make_comm_only_program(z, make_hier_mesh(2), tier="bogus")


# -- cli: end to end ----------------------------------------------------------


def _cli_args(tmp_path, extra, epochs=2):
    from pytorch_distributed_mnist_tpu.cli import build_parser

    return build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear",
        "--epochs", str(epochs),
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ] + extra)


def test_cli_dcn_slices_zero_overlap_matches_flat(tmp_path):
    """--dcn-slices 2 end to end under --zero-overlap: the full driver's
    history equals the flat run's, per-tier buckets wired through."""
    from pytorch_distributed_mnist_tpu.cli import run

    flat = run(_cli_args(tmp_path / "a",
                         ["--optimizer-sharding", "zero1",
                          "--zero-overlap"]))
    hier = run(_cli_args(tmp_path / "b",
                         ["--optimizer-sharding", "zero1", "--zero-overlap",
                          "--dcn-slices", "2",
                          "--zero-bucket-mb-dcn", "1"]))
    assert "train_epoch_zero_overlap" in hier["compile_stats"]["programs"]
    for hf, hh in zip(flat["history"], hier["history"]):
        np.testing.assert_allclose(hf["train_loss"], hh["train_loss"],
                                   rtol=1e-4)
        np.testing.assert_allclose(hf["test_acc"], hh["test_acc"],
                                   rtol=1e-6)


@pytest.mark.slow
def test_cli_dcn_slices_zero3_stepwise_matches_flat(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import run

    flat = run(_cli_args(tmp_path / "a",
                         ["--optimizer-sharding", "zero3", "--zero-overlap",
                          "--trainer-mode", "stepwise"]))
    hier = run(_cli_args(tmp_path / "b",
                         ["--optimizer-sharding", "zero3", "--zero-overlap",
                          "--trainer-mode", "stepwise",
                          "--dcn-slices", "2"]))
    for hf, hh in zip(flat["history"], hier["history"]):
        np.testing.assert_allclose(hf["train_loss"], hh["train_loss"],
                                   rtol=1e-4)


def test_cli_hier_checkpoint_loads_on_flat_world(tmp_path):
    """'Same checkpoints load both ways': 2 epochs trained on the
    hierarchical mesh, then a FLAT resume for epoch 3 — the flat world
    loads the hier-written checkpoint without ceremony and the resumed
    epoch's metrics match an uninterrupted flat run's at the suite's
    standard cross-path tolerance (the hier and flat meshes reduce in
    different fp orders, so bitwise equality is not the contract)."""
    from pytorch_distributed_mnist_tpu.cli import run

    full = run(_cli_args(tmp_path / "flat",
                         ["--optimizer-sharding", "zero1", "--resume",
                          "auto"], epochs=3))
    run(_cli_args(tmp_path / "x",
                  ["--optimizer-sharding", "zero1", "--resume", "auto",
                   "--dcn-slices", "2"], epochs=2))
    resumed = run(_cli_args(tmp_path / "x",
                            ["--optimizer-sharding", "zero1",
                             "--resume", "auto"], epochs=3))
    assert resumed["start_epoch"] == 2 and resumed["epochs_run"] == 1
    row_full, row_res = full["history"][2], resumed["history"][0]
    assert row_res["epoch"] == 2
    for key in ("train_loss", "train_acc", "test_loss", "test_acc"):
        np.testing.assert_allclose(row_res[key], row_full[key], rtol=2e-4,
                                   err_msg=key)


def test_cli_flat_checkpoint_loads_on_hier_world(tmp_path):
    """The reverse direction: a FLAT-trained checkpoint resumes on the
    hierarchical mesh (the elastic grow-into-multi-slice shape)."""
    from pytorch_distributed_mnist_tpu.cli import run

    run(_cli_args(tmp_path / "x",
                  ["--optimizer-sharding", "zero1", "--resume", "auto"],
                  epochs=2))
    resumed = run(_cli_args(tmp_path / "x",
                            ["--optimizer-sharding", "zero1",
                             "--resume", "auto", "--dcn-slices", "2"],
                            epochs=3))
    assert resumed["start_epoch"] == 2 and resumed["epochs_run"] == 1


@pytest.mark.parametrize("extra, match", [
    (["--dcn-slices", "3"], "split into"),
    (["--dcn-slices", "-1"], "dcn-slices"),
    (["--dcn-slices", "2", "--trainer-mode", "explicit"], "explicit"),
    (["--dcn-slices", "2", "--loss", "fused"], "fused"),
    (["--dcn-slices", "2", "--model", "vit", "--pipeline-stages", "2"],
     "pipeline"),
    (["--dcn-slices", "2", "--model", "vit", "--sequence-parallel", "2",
      "--patch-size", "7"], "sequence-parallel"),
    (["--dcn-slices", "2", "--model", "moe_mlp", "--expert-parallel", "4",
      "--moe-dispatch", "capacity"], "capacity"),
    (["--dcn-slices", "4", "--model", "moe_mlp", "--expert-parallel", "4"],
     "straddle"),
    (["--dcn-slices", "2", "--model", "vit", "--tensor-parallel", "2",
      "--attention", "flash"], "flash"),
    (["--zero-bucket-mb-dcn", "1"], "zero-overlap"),
    (["--optimizer-sharding", "zero1", "--zero-overlap",
      "--zero-bucket-mb-dcn", "-1"], "zero-bucket-mb-dcn"),
])
def test_cli_dcn_rejection_matrix(tmp_path, extra, match):
    from pytorch_distributed_mnist_tpu.cli import run

    with pytest.raises(SystemExit, match=match):
        run(_cli_args(tmp_path, extra))


@pytest.mark.slow
def test_cli_dcn_slices_tensor_parallel_matches_flat(tmp_path):
    """TP pins to the ICI tier: the GSPMD rule table composes with the
    hierarchical mesh and the trajectory equals the flat TP run."""
    from pytorch_distributed_mnist_tpu.cli import run

    flat = run(_cli_args(tmp_path / "a",
                         ["--model", "vit", "--tensor-parallel", "2"]))
    hier = run(_cli_args(tmp_path / "b",
                         ["--model", "vit", "--tensor-parallel", "2",
                          "--dcn-slices", "2"]))
    for hf, hh in zip(flat["history"], hier["history"]):
        np.testing.assert_allclose(hf["train_loss"], hh["train_loss"],
                                   rtol=1e-4)


# -- analyzer cleanliness -----------------------------------------------------


@pytest.mark.lint
def test_mesh_and_zero_overlap_modules_clean_under_analyzer():
    """The satellite pin: the hierarchical mesh machinery and the
    two-tier schedule stay clean under the checkers whose invariants
    they most plausibly violate."""
    from tools.analyzer import run_analysis

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "pytorch_distributed_mnist_tpu", "parallel")
    result = run_analysis(
        [os.path.join(pkg, "mesh.py"), os.path.join(pkg, "zero_overlap.py")],
        checkers=["collective-symmetry", "trace-purity",
                  "recompile-hazard", "lock-discipline"],
    )
    assert not result.findings, [
        f"{f.path}:{f.line} [{f.checker}] {f.message}"
        for f in result.findings
    ]
