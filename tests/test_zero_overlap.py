"""Overlapped ZeRO (parallel/zero_overlap.py) on the 8-device mesh.

The contract: the explicit bucketized reduce-scatter/allgather schedule
changes WHEN communication happens, never WHAT the training computes.
Overlapped and propagation paths share one state layout and must agree
numerically — ZeRO-1 and ZeRO-3, per-step and scan epoch, with and
without gradient accumulation — the carried gathered params always equal
``allgather(state.params)``, checkpoints written under the overlapped
path resume bit-compatibly, the default (no ``--zero-overlap``) path is
untouched, and the module itself is clean under the analyzer's
collective-symmetry / trace-purity / recompile-hazard checkers.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
from pytorch_distributed_mnist_tpu.parallel.zero_overlap import (
    bucket_plan,
    make_comm_only_program,
    make_overlap_train_epoch,
    make_overlap_train_step,
    make_param_gather,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import (
    make_train_epoch,
    make_train_step,
)


def _batch(seed, n=64):
    r = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(r.normal(size=(n, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(r.integers(0, 10, size=(n,)), jnp.int32),
    }


def _assert_trees_close(a, b, rtol=2e-4, atol=2e-5):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


# -- bucket plan -------------------------------------------------------------


class _Leaf:
    def __init__(self, shape, dtype=np.float32):
        self.shape = shape
        self.dtype = np.dtype(dtype)


def test_bucket_plan_size_ordered_and_budgeted():
    leaves = [_Leaf((10,)), _Leaf((1024, 256)), _Leaf((1024,)),
              _Leaf((512, 512))]
    plan = bucket_plan(leaves, bucket_mb=1.0)  # 1 MiB: each big leaf = 1 MiB
    # Largest leaves first (1 and 3 are both exactly 1 MiB: flat-index
    # tie-break), each filling its own bucket; the small leaves share.
    assert plan == [[1], [3], [2, 0]]
    # Every leaf appears exactly once.
    assert sorted(i for b in plan for i in b) == [0, 1, 2, 3]


def test_bucket_plan_oversize_leaf_gets_own_bucket():
    leaves = [_Leaf((4096, 1024)), _Leaf((4,))]
    plan = bucket_plan(leaves, bucket_mb=1.0)
    assert plan[0] == [0]  # 16 MiB leaf alone, budget notwithstanding


def test_bucket_plan_deterministic_and_validates():
    leaves = [_Leaf((64, 64)) for _ in range(6)]
    assert bucket_plan(leaves, 0.02) == bucket_plan(leaves, 0.02)
    with pytest.raises(ValueError, match="bucket_mb"):
        bucket_plan(leaves, 0.0)


# -- numerical equivalence vs the propagation path ---------------------------


@pytest.mark.parametrize("level", [1, 3])
def test_overlap_step_matches_propagation(mesh8, level):
    """3 overlapped steps == 3 propagation-scheduled steps on the same
    state layout — same params, moments, and metrics (fp-order tol)."""
    model = get_model("linear", compute_dtype=jnp.float32)
    ref = create_train_state(model, jax.random.key(0))
    ref, ref_sh = shard_state_zero(ref, mesh8, level=level)
    ref_step = make_train_step(mesh8, state_sharding=ref_sh)

    z = create_train_state(model, jax.random.key(0))
    z, _ = shard_state_zero(z, mesh8, level=level)
    step = make_overlap_train_step(z, mesh8, level=level, bucket_mb=0.5)
    gathered = make_param_gather(mesh8)(z.params) if level == 3 else None

    for i in range(3):
        b = _batch(seed=i)
        ref, rm = ref_step(ref, b)
        if level == 3:
            z, gathered, zm = step(z, gathered, b)
        else:
            z, zm = step(z, b)
    np.testing.assert_allclose(float(rm.loss_sum), float(zm.loss_sum),
                               rtol=1e-5)
    assert float(rm.count) == float(zm.count)
    _assert_trees_close(ref.params, z.params)
    _assert_trees_close(ref.opt_state, z.opt_state)
    # The layout really is shared: both paths' params carry identical
    # shardings leaf for leaf.
    def _trim(spec):  # P('data') and P('data', None) are the same layout
        entries = tuple(spec)
        while entries and entries[-1] is None:
            entries = entries[:-1]
        return entries

    for a, c in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(z.params)):
        assert _trim(a.sharding.spec) == _trim(c.sharding.spec)


@pytest.mark.slow
@pytest.mark.parametrize("level", [1, 3])
def test_overlap_step_matches_propagation_cnn(mesh8, level):
    """The conv model exercises multi-bucket plans (4 weight leaves of
    very different sizes) and the dim-0-vs-dim-3 shard choices."""
    model = get_model("cnn", compute_dtype=jnp.float32)
    ref = create_train_state(model, jax.random.key(0))
    ref, ref_sh = shard_state_zero(ref, mesh8, level=level)
    ref_step = make_train_step(mesh8, state_sharding=ref_sh)

    z = create_train_state(model, jax.random.key(0))
    z, _ = shard_state_zero(z, mesh8, level=level)
    step = make_overlap_train_step(z, mesh8, level=level, bucket_mb=1.0)
    gathered = make_param_gather(mesh8)(z.params) if level == 3 else None

    for i in range(3):
        b = _batch(seed=i)
        ref, rm = ref_step(ref, b)
        if level == 3:
            z, gathered, zm = step(z, gathered, b)
        else:
            z, zm = step(z, b)
    np.testing.assert_allclose(float(rm.loss_sum), float(zm.loss_sum),
                               rtol=1e-5)
    _assert_trees_close(ref.params, z.params)
    _assert_trees_close(ref.opt_state, z.opt_state)


@pytest.mark.parametrize("level", [1, 3])
def test_overlap_scan_epoch_matches_propagation(mesh8, level):
    model = get_model("linear", compute_dtype=jnp.float32)
    ref = create_train_state(model, jax.random.key(1))
    ref, ref_sh = shard_state_zero(ref, mesh8, level=level)
    z = create_train_state(model, jax.random.key(1))
    z, _ = shard_state_zero(z, mesh8, level=level)

    r = np.random.default_rng(7)
    batches = {
        "image": jnp.asarray(r.normal(size=(4, 64, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(r.integers(0, 10, size=(4, 64)), jnp.int32),
    }
    ref_epoch = make_train_epoch(mesh8, state_sharding=ref_sh)
    z_epoch = make_overlap_train_epoch(z, mesh8, level=level, bucket_mb=0.5)
    ref, rm = ref_epoch(ref, batches)
    copies = jax.tree_util.tree_map(jnp.copy, batches)
    if level == 3:
        gathered = make_param_gather(mesh8)(z.params)
        z, gathered, zm = z_epoch(z, gathered, copies)
        # Carry invariant: the gathered copy leaving the epoch IS the
        # allgather of the updated shards.
        full = make_param_gather(mesh8)(z.params)
        for a, c in zip(jax.tree_util.tree_leaves(full),
                        jax.tree_util.tree_leaves(gathered)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    else:
        z, zm = z_epoch(z, copies)
    assert float(rm.count) == float(zm.count)
    np.testing.assert_allclose(float(rm.loss_sum), float(zm.loss_sum),
                               rtol=1e-5)
    _assert_trees_close(ref.params, z.params)


def test_overlap_grad_accum_composition(mesh8):
    """--grad-accum > 1 under the overlapped plane: the accum scan's
    per-example-sum gradients feed the bucketized reduce-scatter and the
    result still equals the propagation path's accumulated step."""
    model = get_model("linear", compute_dtype=jnp.float32)
    ref = create_train_state(model, jax.random.key(2))
    ref, ref_sh = shard_state_zero(ref, mesh8, level=1)
    ref_step = make_train_step(mesh8, state_sharding=ref_sh, grad_accum=2)

    z = create_train_state(model, jax.random.key(2))
    z, _ = shard_state_zero(z, mesh8, level=1)
    step = make_overlap_train_step(z, mesh8, level=1, bucket_mb=0.5,
                                   grad_accum=2)
    for i in range(2):
        b = _batch(seed=10 + i)
        ref, rm = ref_step(ref, b)
        z, zm = step(z, b)
    np.testing.assert_allclose(float(rm.loss_sum), float(zm.loss_sum),
                               rtol=1e-5)
    assert float(rm.count) == float(zm.count)
    _assert_trees_close(ref.params, z.params)
    _assert_trees_close(ref.opt_state, z.opt_state)


def test_comm_only_program_runs_collective_sequence(mesh8):
    """The bench's comm twin compiles and returns a finite scalar (the
    DCE anchor folding every reduce-scatter/allgather result)."""
    model = get_model("linear", compute_dtype=jnp.float32)
    z = create_train_state(model, jax.random.key(0))
    z, _ = shard_state_zero(z, mesh8, level=3)
    full = make_param_gather(mesh8)(z.params)
    comm = make_comm_only_program(z, mesh8, bucket_mb=0.5)
    assert np.isfinite(float(comm(full)))


# -- CLI wiring --------------------------------------------------------------


def _cli_args(tmp_path, extra):
    from pytorch_distributed_mnist_tpu.cli import build_parser

    return build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--epochs", "2",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ] + extra)


def test_cli_zero_overlap_matches_propagation(tmp_path):
    """--zero-overlap end to end (scan): the full driver's history equals
    the propagation run's, and the default path compiles its usual
    program names (no overlap program leaks into a run that never asked
    for one)."""
    from pytorch_distributed_mnist_tpu.cli import run

    base = run(_cli_args(tmp_path / "a",
                         ["--optimizer-sharding", "zero1"]))
    assert "train_epoch" in base["compile_stats"]["programs"]
    assert "train_epoch_zero_overlap" not in base["compile_stats"]["programs"]

    ov = run(_cli_args(tmp_path / "b",
                       ["--optimizer-sharding", "zero1", "--zero-overlap"]))
    assert "train_epoch_zero_overlap" in ov["compile_stats"]["programs"]
    for h_base, h_ov in zip(base["history"], ov["history"]):
        np.testing.assert_allclose(h_base["train_loss"], h_ov["train_loss"],
                                   rtol=1e-4)
        np.testing.assert_allclose(h_base["test_acc"], h_ov["test_acc"],
                                   rtol=1e-6)


@pytest.mark.slow
def test_cli_zero_overlap_zero3_stepwise(tmp_path):
    """ZeRO-3 overlapped through the stepwise path: the Trainer's
    explicit gathered-param carry across step boundaries, equal to the
    scan run's trajectory."""
    from pytorch_distributed_mnist_tpu.cli import run

    scan = run(_cli_args(tmp_path / "a",
                         ["--optimizer-sharding", "zero3",
                          "--zero-overlap"]))
    stepw = run(_cli_args(tmp_path / "b",
                          ["--optimizer-sharding", "zero3", "--zero-overlap",
                           "--trainer-mode", "stepwise"]))
    assert "train_step_zero_overlap" in stepw["compile_stats"]["programs"]
    for h_a, h_b in zip(scan["history"], stepw["history"]):
        np.testing.assert_allclose(h_a["train_loss"], h_b["train_loss"],
                                   rtol=1e-4)


@pytest.mark.parametrize("extra, match", [
    ([], "zero1 or zero3"),
    (["--optimizer-sharding", "zero1", "--trainer-mode", "explicit"],
     "explicit"),
    (["--optimizer-sharding", "zero1", "--loss", "fused"], "fused"),
    (["--optimizer-sharding", "zero1", "--epoch-gather", "device"],
     "epoch-gather host"),
    (["--optimizer-sharding", "zero1", "--zero-bucket-mb", "0"],
     "zero-bucket-mb"),
])
def test_cli_zero_overlap_rejects_bad_compositions(tmp_path, extra, match):
    from pytorch_distributed_mnist_tpu.cli import run

    with pytest.raises(SystemExit, match=match):
        run(_cli_args(tmp_path, ["--zero-overlap"] + extra))


def test_trainer_rejects_overlap_without_zero_sharding(mesh8, tiny_data):
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.train.trainer import Trainer

    images, labels = tiny_data
    loader = MNISTDataLoader(images, labels, batch_size=64, train=True)
    state = create_train_state(get_model("linear"), jax.random.key(0))
    with pytest.raises(ValueError, match="ZeRO state sharding"):
        Trainer(state, loader, loader, mesh=mesh8, zero_overlap=True)


def test_external_state_install_invalidates_gathered_carry(mesh8, tiny_data):
    """The ZeRO-3 gathered-param carry is DERIVED state: any outside
    ``trainer.state = ...`` install (resume, LR update, tests) must drop
    it, or every later forward silently runs on the old weights. The
    internal step loop keeps its own matching carry."""
    from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
    from pytorch_distributed_mnist_tpu.train.trainer import Trainer

    images, labels = tiny_data
    loader = MNISTDataLoader(images, labels, batch_size=64, train=True,
                             seed=0)
    state = create_train_state(get_model("linear", compute_dtype=jnp.float32),
                               jax.random.key(0))
    state, sharding = shard_state_zero(state, mesh8, level=3)
    trainer = Trainer(state, loader, loader, mesh=mesh8, mode="stepwise",
                      state_sharding=sharding, zero_overlap=True,
                      zero_level=3)
    trainer.train()
    assert trainer._zero_gathered is not None  # carry survives the epoch

    # Same treedef (the compiled program pins pytree statics, tx
    # included): an outside install is a same-shape state with other
    # values — the resume shape.
    fresh = trainer.state.replace(params=jax.tree_util.tree_map(
        lambda p: p * 0.5, trainer.state.params))
    trainer.state = fresh
    assert trainer._zero_gathered is None  # setter dropped the stale copy
    trainer.train()  # re-derives from the INSTALLED params and trains
    gathered = trainer._zero_gathered
    full = make_param_gather(mesh8)(trainer.state.params)
    for a, c in zip(jax.tree_util.tree_leaves(full),
                    jax.tree_util.tree_leaves(gathered)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))
    trainer.close()


# -- checkpoint round-trip under overlapped ZeRO-3 ---------------------------


def test_checkpoint_roundtrip_overlapped_zero3(tmp_path):
    """Save mid-run under the overlapped ZeRO-3 plane (async writer, so
    the host snapshot races the next epoch's donated buffers — the
    hazard train/checkpoint.py:190 documents), `--resume auto`, and the
    resumed epochs' metrics equal an uninterrupted run's exactly."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    def args(ckpt, epochs):
        return build_parser().parse_args([
            "--dataset", "synthetic", "--model", "linear",
            "--batch-size", "64", "--synthetic-train-size", "256",
            "--synthetic-test-size", "128", "--seed", "0",
            "--optimizer-sharding", "zero3", "--zero-overlap",
            "--async-checkpoint", "--resume", "auto",
            "--checkpoint-dir", str(ckpt), "--epochs", str(epochs),
            "--root", str(tmp_path / "data"),
        ])

    full = run(args(tmp_path / "full", 3))
    run(args(tmp_path / "cut", 2))                 # interrupted at epoch 2
    resumed = run(args(tmp_path / "cut", 3))       # picks up checkpoint_1
    assert resumed["start_epoch"] == 2 and resumed["epochs_run"] == 1
    row_full = full["history"][2]
    row_res = resumed["history"][0]
    assert row_res["epoch"] == 2
    for key in ("train_loss", "train_acc", "test_loss", "test_acc"):
        np.testing.assert_allclose(row_res[key], row_full[key], rtol=1e-6,
                                   err_msg=key)


# -- analyzer cleanliness ----------------------------------------------------


@pytest.mark.lint
def test_zero_overlap_module_clean_under_analyzer():
    """The satellite contract: the new data plane passes the three
    checkers whose invariants it most plausibly violates — host-symmetry
    of collectives, purity of the traced bodies, and AOT shape
    stability."""
    from tools.analyzer import run_analysis

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = run_analysis(
        [os.path.join(repo, "pytorch_distributed_mnist_tpu", "parallel",
                      "zero_overlap.py")],
        checkers=["collective-symmetry", "trace-purity",
                  "recompile-hazard"],
    )
    assert not result.findings, [
        f"{f.path}:{f.line} [{f.checker}] {f.message}"
        for f in result.findings
    ]
