"""Real multi-process DP execution (2 CPU processes, localhost rendezvous).

The reference actually runs N OS processes (``mp.spawn``,
``/root/reference/multi_proc_single_gpu.py:284-285``); SURVEY.md section 4
asks for subprocess multi-host coverage. This spawns 2 worker processes
(tests/multiproc_worker.py), each owning ONE local CPU device, rendezvousing
through ``jax.distributed.initialize`` — exercising the
``make_array_from_process_local_data`` loader branch, disjoint per-host
sampler shards, cross-process metric reduction, and process-0-only
checkpoint writes, none of which a single-process 8-device mesh can reach.

Also covers the env-based launch detection used on real pods/clusters
(``parallel/distributed.py``), as pure unit tests.
"""

import json
import os
import subprocess
import sys

import pytest

# The launcher module owns the one-CPU-device-per-child env construction
# and the free-port helper; the manual-worker tests reuse them so the
# fiddly XLA_FLAGS stripping never drifts between the two.
from pytorch_distributed_mnist_tpu.parallel.launcher import (
    _child_env,
    free_port as _free_port,
)

_WORKER = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_workers(ckpt_dir, extra=(), nprocs=2):
    """Launch ``nprocs`` worker ranks, wait, assert rc 0; return
    (summaries, outs).

    The one copy of the Popen/communicate/kill/SUMMARY-parse dance every
    multi-process test needs — fixes to timeout or output handling land
    here once.
    """
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), str(nprocs), str(port),
             str(ckpt_dir)] + list(extra),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=_child_env(), cwd=_REPO,
        )
        for rank in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
    summaries = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("SUMMARY")]
        assert lines, f"no SUMMARY line in:\n{out[-4000:]}"
        summaries.append(json.loads(lines[-1][len("SUMMARY"):]))
    return summaries, outs


@pytest.mark.slow
def test_two_process_dp_epoch(tmp_path):
    port = _free_port()
    ckpt = str(tmp_path / "ckpts")
    env = _child_env()
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port), ckpt],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=_REPO,
        )
        for rank in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"

    summaries = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("SUMMARY")]
        assert lines, f"no SUMMARY line in:\n{out[-4000:]}"
        summaries.append(json.loads(lines[-1][len("SUMMARY"):]))

    s0 = next(s for s in summaries if s["rank"] == 0)
    s1 = next(s for s in summaries if s["rank"] == 1)
    # A real 2-process world with one device each.
    assert s0["process_count"] == 2 and s1["process_count"] == 2
    assert s0["device_count"] == 2 and s1["device_count"] == 2
    # SPMD: replicated metrics agree bit-for-bit across processes.
    assert s0["best_acc"] == pytest.approx(s1["best_acc"], abs=0.0)
    assert s0["train_loss"] == pytest.approx(s1["train_loss"], abs=0.0)
    # Process 0 wrote the per-epoch checkpoint (+ best copy); the worker
    # lists the directory AFTER its own run, so rank 1 seeing files only
    # proves the shared dir — the process-0-only gate is save_checkpoint
    # returning None for rank 1, covered by it not erroring on a read-only
    # view. The files themselves must exist exactly once.
    assert "checkpoint_0.npz" in s0["checkpoint_files"]
    assert "model_best.npz" in s0["checkpoint_files"]


def test_env_detection_nothing(monkeypatch):
    from pytorch_distributed_mnist_tpu.parallel.distributed import (
        _multiprocess_env_detected,
    )

    for var in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS",
                "TPU_WORKER_HOSTNAMES", "SLURM_NTASKS",
                "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        monkeypatch.delenv(var, raising=False)
    assert not _multiprocess_env_detected()


@pytest.mark.parametrize(
    "var,value,expect",
    [
        ("JAX_COORDINATOR_ADDRESS", "10.0.0.2:8476", True),
        ("MEGASCALE_COORDINATOR_ADDRESS", "10.0.0.2:8080", True),
        ("TPU_WORKER_HOSTNAMES", "t0,t1,t2,t3", True),
        ("TPU_WORKER_HOSTNAMES", "t0", False),
        ("SLURM_NTASKS", "4", True),
        ("SLURM_NTASKS", "1", False),
        ("SLURM_NTASKS", "garbage", False),
        ("OMPI_COMM_WORLD_SIZE", "2", True),
    ],
)
def test_env_detection(monkeypatch, var, value, expect):
    from pytorch_distributed_mnist_tpu.parallel.distributed import (
        _multiprocess_env_detected,
    )

    for v in ("JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS",
              "TPU_WORKER_HOSTNAMES", "SLURM_NTASKS",
              "OMPI_COMM_WORLD_SIZE", "PMI_SIZE"):
        monkeypatch.delenv(v, raising=False)
    monkeypatch.setenv(var, value)
    assert _multiprocess_env_detected() is expect


@pytest.mark.slow
@pytest.mark.parametrize("nprocs", [2, 4])
def test_spawn_launcher_cli(tmp_path, capfd, nprocs):
    """``tpu-mnist --spawn N``: the reference's mp.spawn mode (:284-285) as
    a flag. main() forks N local host processes that rendezvous on a free
    loopback port and run the full driver; rc 0 means every rank trained,
    reduced metrics, and rank 0 wrote the checkpoints. N=4 exercises a
    wider world than the 2-process tests above — 4-way disjoint sampler
    shards, 4-participant collectives over the loopback coordinator."""
    from pytorch_distributed_mnist_tpu.cli import main

    ckpt = str(tmp_path / "ckpts")
    with pytest.raises(SystemExit) as exc:
        main([
            "--spawn", str(nprocs),
            "--dataset", "synthetic", "--model", "linear",
            "--epochs", "1", "--batch-size", "64",
            "--synthetic-train-size", "256", "--synthetic-test-size", "128",
            "--trainer-mode", "stepwise", "--seed", "0",
            "--checkpoint-dir", ckpt,
        ])
    assert exc.value.code == 0
    assert "checkpoint_0.npz" in os.listdir(ckpt)
    assert "model_best.npz" in os.listdir(ckpt)
    # rank 0 streamed to this terminal; its epoch log proves a real run
    out = capfd.readouterr().out
    assert "Epoch: 0" in out


def test_spawn_flag_conflicts():
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["--spawn", "2", "--coordinator", "127.0.0.1:1234"])
    assert "cannot combine" in str(exc.value.code)


def test_spawn_one_clean_error():
    """--spawn 1 must die with flag-level language (SystemExit), not a
    bare ValueError traceback from the launcher (round-2 ADVICE)."""
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["--spawn", "1"])
    assert "at least 2 processes" in str(exc.value.code)


def test_no_prefix_abbreviation():
    """allow_abbrev=False: '--spaw 2' must be rejected outright — an
    abbreviated spawn flag would survive strip_spawn_flag's literal match
    and poison the children's argv (round-2 ADVICE)."""
    from pytorch_distributed_mnist_tpu.cli import build_parser

    with pytest.raises(SystemExit):
        build_parser().parse_args(["--spaw", "2"])


def test_strip_spawn_flag():
    from pytorch_distributed_mnist_tpu.parallel.launcher import (
        strip_spawn_flag,
    )

    assert strip_spawn_flag(["--spawn", "4", "--epochs", "2"]) == [
        "--epochs", "2"]
    assert strip_spawn_flag(["--spawn=4", "--epochs", "2"]) == [
        "--epochs", "2"]
    assert strip_spawn_flag(["--epochs", "2"]) == ["--epochs", "2"]


@pytest.mark.slow
def test_two_process_pipeline_zero1_train_and_resume(tmp_path):
    """Multi-host PP x ZeRO-1 — the composition the CLI rejected through
    round 2. The pipeline state is now placed exactly once, onto the
    composed stage x data layout (create_pipelined_vit_state(place=False)
    + shard_state_zero), so 2 real processes train the pipelined ViT,
    write the sharded .ckpt from both ranks, and a second 2-process run
    resumes from it."""
    pp_flags = ["--model", "vit", "--pipeline-stages", "2",
                "--optimizer-sharding", "zero1", "--batch-size", "32",
                "--synthetic-train-size", "64", "--synthetic-test-size", "32"]
    first, _ = _spawn_workers(tmp_path / "ckpts", pp_flags)
    assert all(s["epochs_run"] == 1 for s in first)
    # Ground truth: the same config in ONE process over 2 virtual devices.
    # The mesh is data=1 x stage=2, so both hosts feed the identical full
    # batch (data_replica_coords); before that grouping existed each host
    # fed a disjoint half and this comparison was impossible — multi-host
    # PP silently trained on different data than its single-host twin.
    oracle = _single_process_oracle(pp_flags, 2, tmp_path / "oracle")
    assert first[0]["train_loss"] == pytest.approx(
        oracle["train_loss"], rel=1e-5)
    # Cross-process-sharded moments force the sharded directory layout,
    # with shard files from BOTH ranks.
    ckpt0 = tmp_path / "ckpts" / "checkpoint_0.ckpt"
    assert ckpt0.is_dir()
    names = sorted(os.listdir(ckpt0))
    assert any(n.startswith("shards_p00000") for n in names)
    assert any(n.startswith("shards_p00001") for n in names)

    second, _ = _spawn_workers(
        tmp_path / "ckpts", pp_flags + ["--resume", "auto", "--epochs", "2"])
    # Resumed at epoch 1 (one more epoch, not two): restore landed on the
    # composed layout across both hosts.
    assert all(s["epochs_run"] == 1 for s in second)
    assert all(s["start_epoch"] == 1 for s in second)


def _single_process_oracle(flags, n_devices, ckpt_dir):
    """Run the worker's exact config in ONE fresh process over
    ``n_devices`` virtual CPU devices; return {train_loss, test_acc}.
    The ground truth the 2-process runs must reproduce: same data, same
    global batch, same programs — only the collective transport differs.

    Pinned defaults (dataset/trainer-mode/epochs/seed) come FIRST and the
    caller's ``flags`` after, so caller flags override them — the same
    last-wins precedence ``_spawn_workers`` gives its extras over the
    worker defaults. Callers must still pass the model/batch/size flags
    they passed the workers (multiproc_worker.py's --model linear /
    --batch-size 64 / 256-sample defaults are NOT replicated here)."""
    # Start from the launcher's child env (preserves ambient XLA_FLAGS,
    # strips only the device-count flag — the workers being compared
    # against run under exactly this env) and re-append our count, so
    # oracle and workers never drift on XLA configuration.
    env = _child_env()
    env["XLA_FLAGS"] = (
        f"{env['XLA_FLAGS']} "
        f"--xla_force_host_platform_device_count={n_devices}").strip()
    script = (
        "import json, jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from pytorch_distributed_mnist_tpu.cli import build_parser, run\n"
        "s = run(build_parser().parse_args([\n"
        "    '--dataset', 'synthetic', '--trainer-mode', 'stepwise',\n"
        "    '--epochs', '1', '--seed', '0',\n"
        f"    '--checkpoint-dir', {str(ckpt_dir)!r}]\n"
        f"    + {list(flags)!r}))\n"
        "print('SUMMARY' + json.dumps({'train_loss':"
        " s['history'][0]['train_loss'],"
        " 'test_acc': s['history'][0]['test_acc']}))\n"
    )
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=600,
                          env=env, cwd=_REPO)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-4000:]
    line = [l for l in proc.stdout.splitlines()
            if l.startswith("SUMMARY")][-1]
    return json.loads(line[len("SUMMARY"):])


@pytest.mark.slow
def test_two_process_tensor_parallel_matches_single(tmp_path):
    """Multi-host TP: the model axis spans the 2 processes (mesh
    data=1 x model=2), so BOTH hosts must feed the identical full batch —
    the data_replica_coords grouping (parallel/mesh.py). The oracle is
    the same config run in ONE process over 2 virtual devices: identical
    data, identical program, so the training trajectory must agree to
    f32 reduction tolerance. Before the grouping fix the loader fed each
    host a disjoint half-shard (DistributedSampler semantics), silently
    assembling a 'replicated' batch whose replicas disagreed — this test
    pins the repaired semantics end to end."""
    tp_flags = ["--model", "vit", "--tensor-parallel", "2",
                "--batch-size", "32",
                "--synthetic-train-size", "64", "--synthetic-test-size", "32"]
    two_proc, _ = _spawn_workers(tmp_path / "ckpts", tp_flags)
    # replicated metrics agree bit-for-bit across the two hosts
    assert two_proc[0]["train_loss"] == pytest.approx(
        two_proc[1]["train_loss"], abs=0.0)

    # Oracle: one process, two virtual CPU devices, same flags/seed.
    # Same data, same global batch, same step count; only the psum's
    # cross-process transport differs. f32 reduction-order tolerance.
    oracle = _single_process_oracle(tp_flags, 2, tmp_path / "oracle")
    assert two_proc[0]["train_loss"] == pytest.approx(
        oracle["train_loss"], rel=1e-5)
    assert two_proc[0]["test_acc"] == pytest.approx(
        oracle["test_acc"], abs=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_two_process_sequence_parallel_matches_single(tmp_path, impl):
    """Multi-host SP: the seq axis spans the 2 processes (mesh
    data=1 x seq=2), so the ring attention's ppermute hops (or the
    Ulysses all_to_alls) cross a REAL process link — the transport the
    virtual-device tests and dryrun phases 2/6 cannot reach — and both
    hosts must feed the identical full batch (data_replica_coords).
    Trajectory pinned to the single-process 2-virtual-device oracle.
    Completes the multi-process twin matrix for SURVEY section 2c: DP,
    TP, EP, PP x TP x ZeRO, ZeRO-3, and now both SP impls."""
    sp_flags = ["--model", "vit", "--patch-size", "7",
                "--sequence-parallel", "2",
                "--sequence-parallel-impl", impl,
                "--batch-size", "32",
                "--synthetic-train-size", "64", "--synthetic-test-size", "32"]
    two_proc, _ = _spawn_workers(tmp_path / "ckpts", sp_flags)
    assert two_proc[0]["train_loss"] == pytest.approx(
        two_proc[1]["train_loss"], abs=0.0)

    oracle = _single_process_oracle(sp_flags, 2, tmp_path / "oracle")
    assert two_proc[0]["train_loss"] == pytest.approx(
        oracle["train_loss"], rel=1e-5)
    assert two_proc[0]["test_acc"] == pytest.approx(
        oracle["test_acc"], abs=1e-6)


@pytest.mark.slow
def test_four_process_pp_tp_zero1_matches_single(tmp_path):
    """The deepest multi-host composition in the matrix: PP x TP x
    ZeRO-1 over 4 real processes — mesh data=1 x stage=2 x model=2 with
    BOTH non-data axes spanning process boundaries, so the GPipe
    ppermute hops AND the Megatron stage-body psums cross real process
    links, all four hosts feed the identical full batch
    (data_replica_coords groups them into one data replica), and the
    stage x model x data-sharded moments force the sharded .ckpt layout
    from every rank. Trajectory pinned to the same config in one
    process over 4 virtual devices."""
    flags = ["--model", "vit", "--pipeline-stages", "2",
             "--tensor-parallel", "2", "--optimizer-sharding", "zero1",
             "--batch-size", "32",
             "--synthetic-train-size", "64", "--synthetic-test-size", "32"]
    four, _ = _spawn_workers(tmp_path / "ckpts", flags, nprocs=4)
    assert all(s["process_count"] == 4 for s in four)
    # replicated metrics bit-identical on every host
    for s in four[1:]:
        assert s["train_loss"] == pytest.approx(
            four[0]["train_loss"], abs=0.0)
    # cross-host-sharded state -> sharded directory layout, all 4 ranks
    ckpt0 = tmp_path / "ckpts" / "checkpoint_0.ckpt"
    assert ckpt0.is_dir()
    names = sorted(os.listdir(ckpt0))
    for rank in range(4):
        assert any(n.startswith(f"shards_p0000{rank}") for n in names)

    oracle = _single_process_oracle(flags, 4, tmp_path / "oracle")
    assert four[0]["train_loss"] == pytest.approx(
        oracle["train_loss"], rel=1e-5)
    assert four[0]["test_acc"] == pytest.approx(
        oracle["test_acc"], abs=1e-6)


@pytest.mark.slow
def test_two_process_expert_parallel_matches_single(tmp_path):
    """Multi-host EP: the expert axis spans the 2 processes (mesh
    data=1 x expert=2) — each host computes only its local experts and
    the combine's expert-sum AllReduce crosses the process boundary.
    Both hosts feed the identical full batch (data_replica_coords), and
    the trajectory must match the same config in one process over 2
    virtual devices."""
    ep_flags = ["--model", "moe_mlp", "--expert-parallel", "2",
                "--batch-size", "32",
                "--synthetic-train-size", "64", "--synthetic-test-size", "32"]
    two_proc, _ = _spawn_workers(tmp_path / "ckpts", ep_flags)
    assert two_proc[0]["train_loss"] == pytest.approx(
        two_proc[1]["train_loss"], abs=0.0)
    oracle = _single_process_oracle(ep_flags, 2, tmp_path / "oracle")
    assert two_proc[0]["train_loss"] == pytest.approx(
        oracle["train_loss"], rel=1e-5)
    assert two_proc[0]["test_acc"] == pytest.approx(
        oracle["test_acc"], abs=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("async_ckpt", [False, True],
                         ids=["sync", "async"])
def test_two_process_zero1_sharded_checkpoint_roundtrip(tmp_path, async_ckpt):
    """Multi-host ZeRO-1: moments sharded ACROSS processes -> the npz path
    cannot save them (np.asarray would raise on non-addressable leaves);
    the sharded .ckpt directory must be written by BOTH processes and
    restore in a second 2-process run. This executes the exact crash path
    from the round-2 review finding (checkpoint.py + multi-host zero1).
    The async variant drives the round-4 deferred-publish path: shard
    writes on each host's worker thread, the publish barrier at the next
    main-thread drain — both REAL processes must still converge on one
    published directory."""

    def spawn(extra):
        if async_ckpt:
            extra = list(extra) + ["--async-checkpoint"]
        return _spawn_workers(tmp_path / "ckpts", extra)[0]

    first = spawn(["--optimizer-sharding", "zero1"])
    ckpt_dir = tmp_path / "ckpts"
    # the sharded DIRECTORY layout was chosen automatically, and both
    # processes contributed shard files
    assert (ckpt_dir / "checkpoint_0.ckpt").is_dir()
    names = sorted(os.listdir(ckpt_dir / "checkpoint_0.ckpt"))
    assert "meta.json" in names
    assert "index_p00000.json" in names and "index_p00001.json" in names
    assert any(n.startswith("shards_p00000") for n in names)
    assert any(n.startswith("shards_p00001") for n in names)

    second = spawn([
        "--optimizer-sharding", "zero1", "--epochs", "2",
        "--resume", str(ckpt_dir / "checkpoint_0.ckpt"),
    ])
    # the resumed world restored across hosts and continued training;
    # replicated metrics still agree bit-for-bit
    assert second[0]["train_loss"] == pytest.approx(
        second[1]["train_loss"], abs=0.0)
    # resume continued at epoch 1, so the resumed run improves on (or at
    # least evolves from) the first epoch's loss deterministically
    assert second[0]["train_loss"] != first[0]["train_loss"]


@pytest.mark.slow
@pytest.mark.parametrize("async_ckpt", [False, True],
                         ids=["sync", "async"])
def test_two_process_ckpt_write_fault_fails_all_ranks(tmp_path, async_ckpt):
    """Round-4/5 advisor (checkpoint.py): one host's sharded write
    failing must fail EVERY host — at the write itself (sync) or at the
    next drain (async) — never strand the healthy host in the
    timeout-less publish barrier. Rank 1's shard-file write is
    fault-injected (see multiproc_worker.py); with the write-ok
    agreement, rank 1 exits on the injected OSError and rank 0 exits on
    the peer-failure RuntimeError — before the fix, rank 0 would hang in
    sync_global_devices until this test's communicate() timeout."""
    port = _free_port()
    ckpt = str(tmp_path / "ckpts")
    env = dict(_child_env(), TPUMNIST_TEST_CKPT_FAULT_RANK="1")
    flags = ["--optimizer-sharding", "zero1", "--epochs", "2"]
    if async_ckpt:
        flags.append("--async-checkpoint")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port), ckpt]
            + flags,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=_REPO,
        )
        for rank in range(2)
    ]
    outs = [None] * len(procs)
    try:
        for i, p in enumerate(procs):
            try:
                outs[i], _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                pass  # recorded as None; asserted below after cleanup
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all(o is not None for o in outs), (
        "a rank hung in the publish barrier; collected output:\n"
        + "\n---\n".join((o or "<hung>")[-2000:] for o in outs))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode not in (0, None), (
            f"rank {rank} should have failed:\n{out[-4000:]}")
    # Each rank names its own failure mode.
    assert "injected checkpoint write fault" in outs[1]
    assert "failed on host(s) [1]" in outs[0]


@pytest.mark.slow
@pytest.mark.parametrize("async_ckpt", [False, True],
                         ids=["sync", "async"])
def test_two_process_ckpt_publish_fault_fails_all_ranks(tmp_path,
                                                        async_ckpt):
    """Round-5 audit twin of the write-fault test, one phase later:
    process 0's publish body failing (e.g. the real
    not-a-shared-filesystem RuntimeError) must fail BOTH ranks — before
    the publish-phase agreement, rank 0 raised alone while rank 1
    blocked forever in the trailing ckpt_publish barrier. The async
    variant drives the drain-time publish (wait() -> _sharded_publish),
    pinning the guarantee for --async-checkpoint too."""
    port = _free_port()
    ckpt = str(tmp_path / "ckpts")
    env = dict(_child_env(), TPUMNIST_TEST_CKPT_FAULT_PUBLISH="1")
    flags = ["--optimizer-sharding", "zero1"]
    if async_ckpt:
        flags += ["--async-checkpoint", "--epochs", "2"]
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port), ckpt]
            + flags,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=_REPO,
        )
        for rank in range(2)
    ]
    outs = [None] * len(procs)
    try:
        for i, p in enumerate(procs):
            try:
                outs[i], _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all(o is not None for o in outs), (
        "a rank hung past the publish-phase agreement; collected output:\n"
        + "\n---\n".join((o or "<hung>")[-2000:] for o in outs))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode not in (0, None), (
            f"rank {rank} should have failed:\n{out[-4000:]}")
    assert "injected checkpoint publish fault" in outs[0]
    assert "publish for epoch 0 failed on host(s) [0]" in outs[1]


@pytest.mark.slow
def test_two_process_resume_divergence_fails_loudly(tmp_path):
    """Round-5 audit: agreeing on the resume PATH is not enough — a host
    whose (stale-NFS) view lacks the agreed checkpoint would silently
    train fresh at epoch 0 while peers resume at N, running different
    collective programs (silent hang). With the resume-outcome
    agreement, both ranks exit with the same loud SystemExit instead.
    Rank 1's try_resume is blinded (see multiproc_worker.py)."""
    ckpt = tmp_path / "ckpts"
    _spawn_workers(ckpt, ["--optimizer-sharding", "zero1"])  # epoch 0 ckpt

    port = _free_port()
    env = dict(_child_env(), TPUMNIST_TEST_RESUME_HIDE_RANK="1")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port), str(ckpt),
             "--optimizer-sharding", "zero1", "--epochs", "2",
             "--resume", "auto"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=_REPO,
        )
        for rank in range(2)
    ]
    outs = [None] * len(procs)
    try:
        for i, p in enumerate(procs):
            try:
                outs[i], _ = p.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all(o is not None for o in outs), (
        "a rank hung instead of exiting on resume divergence:\n"
        + "\n---\n".join((o or "<hung>")[-2000:] for o in outs))
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode not in (0, None), (
            f"rank {rank} should have failed:\n{out[-4000:]}")
        assert "resume outcome diverged across hosts" in out, out[-2000:]


@pytest.mark.slow
def test_two_process_zero3_matches_single_and_resumes(tmp_path):
    """Multi-host ZeRO-3: PARAMS (not just moments) shard across the 2
    processes, so every step AllGathers weights across the real process
    link and the checkpoint must use the sharded layout from both ranks.
    Trajectory pinned to the single-process 2-virtual-device oracle, and
    a second 2-process run resumes from the cross-host-sharded .ckpt."""
    # One flag list for workers AND oracle (the worker's own defaults
    # cover these, but the oracle's do not — a single source of truth
    # keeps the two configs from drifting).
    z3_flags = ["--optimizer-sharding", "zero3",
                "--model", "linear", "--batch-size", "64",
                "--synthetic-train-size", "256",
                "--synthetic-test-size", "128"]
    first, _ = _spawn_workers(tmp_path / "ckpts", z3_flags)
    assert first[0]["train_loss"] == pytest.approx(
        first[1]["train_loss"], abs=0.0)
    ckpt0 = tmp_path / "ckpts" / "checkpoint_0.ckpt"
    assert ckpt0.is_dir()
    names = sorted(os.listdir(ckpt0))
    assert any(n.startswith("shards_p00000") for n in names)
    assert any(n.startswith("shards_p00001") for n in names)

    oracle = _single_process_oracle(z3_flags, 2, tmp_path / "oracle")
    assert first[0]["train_loss"] == pytest.approx(
        oracle["train_loss"], rel=1e-5)

    second, _ = _spawn_workers(
        tmp_path / "ckpts", z3_flags + ["--resume", "auto", "--epochs", "2"])
    assert all(s["start_epoch"] == 1 and s["epochs_run"] == 1
               for s in second)


@pytest.mark.slow
def test_two_process_resume_auto(tmp_path):
    """--resume auto across a real 2-process world: run 1 trains fresh,
    run 2 resolves the newest checkpoint on process 0, broadcasts the
    choice (cli.py), and both ranks resume at the same epoch."""

    def spawn(extra):
        return _spawn_workers(tmp_path / "ckpts", extra)[1]

    spawn(["--resume", "auto"])
    assert "checkpoint_0.npz" in os.listdir(tmp_path / "ckpts")
    outs = spawn(["--resume", "auto", "--epochs", "2"])
    # both ranks loaded the SAME checkpoint process 0 resolved
    for out in outs:
        assert "loaded checkpoint" in out and "checkpoint_0.npz" in out


@pytest.mark.slow
def test_preemption_kill_and_auto_resume(tmp_path):
    """Failure recovery end to end: SIGKILL a training process after its
    first checkpoint lands, relaunch the SAME command line with
    --resume auto, and the job finishes from where it died (SURVEY.md
    section 5: restart-from-checkpoint is the recovery model)."""
    import time

    ckpt = tmp_path / "ckpts"
    # Enough epochs/data that the tail is still running when the kill
    # lands (epoch 0 also absorbs compile, so checkpoint_0 appears well
    # before the end); if the victim still finishes first, the test
    # skips rather than passing vacuously.
    cmd = [
        sys.executable, "-m", "pytorch_distributed_mnist_tpu",
        "--dataset", "synthetic", "--model", "linear",
        "--epochs", "6", "--batch-size", "64",
        "--synthetic-train-size", "4096", "--synthetic-test-size", "512",
        "--trainer-mode", "stepwise", "--seed", "0",
        "--checkpoint-dir", str(ckpt), "--resume", "auto",
    ]
    env = _child_env()
    victim = subprocess.Popen(cmd, env=env, cwd=_REPO,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            if (ckpt / "checkpoint_0.npz").exists():
                break
            if victim.poll() is not None:
                out = victim.communicate()[0]
                raise AssertionError(f"victim exited early:\n{out[-3000:]}")
            time.sleep(0.5)
        else:
            raise AssertionError("no checkpoint appeared within 300s")
        victim.kill()  # SIGKILL: no cleanup, the preemption case
    finally:
        if victim.poll() is None:
            victim.kill()
        victim.communicate()

    if (ckpt / "checkpoint_5.npz").exists():
        pytest.skip("victim finished before the kill landed; the "
                    "mid-run recovery path was not exercised")

    done = subprocess.run(cmd, env=env, cwd=_REPO, capture_output=True,
                          text=True, timeout=600)
    assert done.returncode == 0, done.stdout[-3000:] + done.stderr[-2000:]
    assert "loaded checkpoint" in done.stdout
    # the relaunch actually trained the missing tail (at least one epoch
    # line), never redid epoch 0, and every epoch's checkpoint exists
    assert "Epoch: " in done.stdout
    assert "Epoch: 0/6" not in done.stdout
    names = set(os.listdir(ckpt))
    assert {f"checkpoint_{e}.npz" for e in range(6)}.issubset(names)


@pytest.mark.slow
def test_spawn_launcher_propagates_child_failure(capfd):
    """A failing rank must fail the launch (nonzero exit) and surface the
    failed child's output, not report success."""
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit) as exc:
        # --patch-size 5 parses fine in the parent (int) but every child's
        # run() rejects it (28 % 5 != 0) — a genuine in-child failure.
        main(["--spawn", "2", "--dataset", "synthetic", "--model", "vit",
              "--patch-size", "5"])
    assert exc.value.code not in (0, None)
    err = capfd.readouterr().err
    assert "spawned process 1 failed" in err  # non-rank-0 log replayed
