"""Gradient accumulation (train/steps.py make_accum_train_step_fn):
N-way accumulated step == the full-batch step, on one device and on the
mesh, plus the CLI flag.

The reference steps the optimizer once per loader batch
(``/root/reference/multi_proc_single_gpu.py:90-92``); accumulation keeps
that cadence while splitting the forward/backward into micro-batches, so
the equivalence contract is exact gradient equality (up to f32 summation
order).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import (
    make_train_step,
)


def _batch(tiny_data, n=64):
    images, labels = tiny_data
    return {"image": jnp.asarray(images[:n]), "label": jnp.asarray(labels[:n])}


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_step_matches_full_batch(tiny_data, accum):
    model = get_model("linear", compute_dtype=jnp.float32)
    batch = _batch(tiny_data)

    ref = create_train_state(model, jax.random.key(0))
    ref, ref_m = make_train_step()(ref, batch)

    acc = create_train_state(model, jax.random.key(0))
    acc, acc_m = make_train_step(grad_accum=accum)(acc, batch)

    assert float(acc_m.loss_sum) == pytest.approx(float(ref_m.loss_sum),
                                                  rel=1e-6)
    assert int(acc_m.count) == int(ref_m.count) == 64
    for a, b in zip(jax.tree.leaves(ref.params), jax.tree.leaves(acc.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_accum_on_mesh_matches_replicated(mesh8, tiny_data):
    from pytorch_distributed_mnist_tpu.data.loader import make_global_batch

    model = get_model("linear", compute_dtype=jnp.float32)
    batch = _batch(tiny_data)

    ref = create_train_state(model, jax.random.key(0))
    ref, ref_m = make_train_step()(ref, batch)

    acc = create_train_state(model, jax.random.key(0))
    step = make_train_step(mesh8, grad_accum=2)
    gbatch = make_global_batch(
        {k: np.asarray(v) for k, v in batch.items()}, mesh8
    )
    acc, acc_m = step(acc, gbatch)

    assert float(acc_m.loss_sum) == pytest.approx(float(ref_m.loss_sum),
                                                  rel=1e-5)
    for a, b in zip(jax.tree.leaves(ref.params), jax.tree.leaves(acc.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_indivisible_batch_raises(tiny_data):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    batch = _batch(tiny_data, n=30)
    with pytest.raises(ValueError, match="not divisible"):
        make_train_step(grad_accum=4)(state, batch)


def test_cli_grad_accum_end_to_end(tmp_path):
    """--grad-accum through the full driver (scan mode: accumulation scan
    nested inside the epoch scan), same metrics as the plain run."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    base = [
        "--dataset", "synthetic", "--model", "linear", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--root", str(tmp_path / "data"),
    ]
    acc = run(build_parser().parse_args(
        base + ["--grad-accum", "4",
                "--checkpoint-dir", str(tmp_path / "ckpt_a")]))
    ref = run(build_parser().parse_args(
        base + ["--checkpoint-dir", str(tmp_path / "ckpt_r")]))
    # rel 1e-3: the CLI models compute in bf16, where micro-batch summation
    # order shifts the loss ~1e-4; exact f32 equality is pinned by the unit
    # tests above.
    assert acc["history"][0]["train_loss"] == pytest.approx(
        ref["history"][0]["train_loss"], rel=1e-3)
    assert acc["history"][0]["test_acc"] == pytest.approx(
        ref["history"][0]["test_acc"], abs=1e-6)
