"""Fixture suite: the collective-symmetry checker.

Firing twins model the structural-hang class (a collective some hosts
skip); non-firing twins are the sanctioned patterns the codebase uses —
symmetric ``process_count()`` guards and branch-on-the-result.
"""


import pytest


from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src):
    return analyze_snippet(src, checkers=["collective-symmetry"])


# -- firing ------------------------------------------------------------------


def test_fires_on_collective_under_process_index_branch():
    src = """
from pytorch_distributed_mnist_tpu.runtime import supervision
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def publish(epoch):
    if process_index() == 0:
        supervision.allgather_records("publish", True)
"""
    (f,) = _findings(src)
    assert f.line == 7 and f.symbol == "publish"
    assert "host-dependent" in f.message


def test_fires_on_collective_in_host_dependent_loop():
    src = """
def drain():
    for _ in range(process_index()):
        agree("drain_tick")
"""
    (f,) = _findings(src)
    assert "trip count" in f.message


def test_fires_on_collective_under_host_dependent_while():
    src = """
def spin():
    while process_index() > 0:
        _agree_phase_ok(None, 0, "x", "d")
"""
    assert len(_findings(src)) == 1


def test_fires_in_else_branch_too():
    src = """
def f():
    if process_index() == 0:
        lead()
    else:
        allgather_records("follower_only", True)
"""
    assert len(_findings(src)) == 1


def test_fires_on_collective_after_host_conditioned_early_return():
    """The most natural way to write the bug: host 0 bails out early and
    never reaches the collective its peers block in — the hazard is the
    code AFTER the branch, not inside it."""
    src = """
def publish(ok):
    if process_index() == 0:
        return None
    return allgather_records("phase", ok)
"""
    (f,) = _findings(src)
    assert f.line == 5 and "early return/raise" in f.message


def test_fires_on_pid_variable_guard():
    """The codebase's dominant idiom binds the index first —
    ``pid = process_index()`` then branching on ``pid`` must be treated
    exactly like a literal process_index() test (taint through simple
    assignment)."""
    src = """
def publish(ok):
    pid = process_index()
    if pid != 0:
        return None
    return _agree_phase_ok(None, 0, "publish", ok)
"""
    (f,) = _findings(src)
    assert "early return/raise" in f.message


def test_fires_on_mixed_exit_kinds():
    """One arm leaves the function, the other only the loop: the
    returning hosts never reach a later collective the loop-exiting
    hosts do — exit KINDS must match, not just exit-ness."""
    src = """
def f(xs, ok):
    for x in xs:
        if process_index() == 0:
            return None
        else:
            break
    return agree("phase", ok)
"""
    (f,) = _findings(src)
    assert f.line == 8 and "early return/raise" in f.message


def test_fires_when_the_branch_itself_rebinds_the_tainted_name():
    """The test is judged BEFORE the branch body runs: a clean rebind
    inside the guarded arm must not retroactively hide the divergence
    (the hosts already parted ways on the tainted value)."""
    src = """
def f(ok):
    pid = process_index()
    if pid:
        pid = 0
        return None
    return allgather_records("x", ok)
"""
    (f,) = _findings(src)
    assert "early return/raise" in f.message


def test_fires_on_tuple_unpack_and_annotated_pid_bindings():
    """Taint flows through positional unpack (only the element bound to
    process_index()) and annotated assignments."""
    unpack = """
def f(ok):
    pid, other = process_index(), 1
    if pid == 0:
        return None
    return allgather_records("x", ok)
"""
    ann = """
def h(ok):
    pid: int = process_index()
    if pid == 0:
        return None
    return allgather_records("x", ok)
"""
    for src in (unpack, ann):
        (f,) = _findings(src)
        assert "early return/raise" in f.message


# -- non-firing --------------------------------------------------------------


def test_silent_on_clean_tuple_unpack():
    """Positional unpack taints per element: a clean first element stays
    clean even when unpacked alongside other values."""
    src = """
def g(ok):
    pid, other = 0, compute()
    if pid == 0:
        return None
    return allgather_records("x", ok)
"""
    assert _findings(src) == []


def test_silent_when_branch_assigns_taint_but_test_is_clean():
    """Divergence needs a host-dependent TEST; assigning a tainted name
    inside a branch on a clean value is not a host split."""
    src = """
def g(flag, ok):
    if flag:
        flag2 = process_index()
        return None
    return allgather_records("x", ok)
"""
    assert _findings(src) == []


def test_silent_on_rebound_clean_pid_variable():
    """Taint ends at a clean rebinding: the name no longer carries a
    host-dependent value."""
    src = """
def agreed(ok):
    pid = process_index()
    pid = 0
    if pid != 0:
        return None
    return agree("phase", ok)
"""
    assert _findings(src) == []


def test_silent_after_loop_when_break_vs_continue_diverged_inside():
    """break/continue divergence ends with its loop (hosts rejoin at the
    loop exit); only collectives still inside the loop are asymmetric."""
    src = """
def k(xs, ok):
    for x in xs:
        if process_index() == 0:
            break
        else:
            continue
    return agree("phase", ok)
"""
    assert _findings(src) == []


def test_silent_when_every_arm_of_the_guard_exits():
    """Both arms leave the function: no host reaches the code after the
    branch, so a collective elsewhere is not made asymmetric by it."""
    src = """
def route(ok):
    if process_index() == 0:
        return serve(ok)
    else:
        return train(ok)

def other(ok):
    return allgather_records("phase", ok)
"""
    assert _findings(src) == []


def test_silent_on_symmetric_early_return():
    """``if process_count() <= 1: return`` then the collective — the
    sanctioned single-process fast path must stay clean."""
    src = """
def agreed(ok):
    if process_count() <= 1:
        return []
    records = prepare(ok)
    return allgather_records("phase", records)
"""
    assert _findings(src) == []


def test_silent_on_symmetric_process_count_guard():
    src = """
def agreed(ok):
    if process_count() <= 1:
        return []
    return allgather_records("phase", ok)
"""
    assert _findings(src) == []


def test_silent_on_branch_on_the_result():
    """The sanctioned shape: every host runs the collective; per-host
    work happens AFTER, conditioned on the agreed outcome."""
    src = """
def publish(epoch):
    err = None
    if process_index() == 0:
        err = do_local_publish()
    failed = agree("publish", err)
    if process_index() == 0 and not failed:
        cleanup_tmp()
"""
    assert _findings(src) == []


def test_silent_on_nested_def_defined_under_guard_but_symmetric():
    """A function *defined* under a host guard is only defined there —
    where it runs is its callers' concern (the checker resets hazard
    context at scope boundaries)."""
    src = """
def f():
    if process_index() == 0:
        def helper():
            return allgather_records("x", True)
        register(helper)
"""
    assert _findings(src) == []


def test_silent_on_plain_symmetric_collective():
    src = """
def vote(ok):
    records = allgather_records("dataset_load", ok)
    raise_if_poisoned(records, "the dataset agreement")
    return records
"""
    assert _findings(src) == []


# -- the shard_map-reduce-scatter shape (ISSUE 7, parallel/zero_overlap.py) --


def test_fires_on_host_collective_beside_shard_map_reduce_scatter():
    """The overlapped-ZeRO shape gone wrong: a driver that builds the
    shard_map'd reduce-scatter body AND runs a host agreement under a
    process_index() guard. The device collective is SPMD (every device
    participates by construction); the HOST collective under the guard
    is still the structural hang, and the checker must see it through
    the surrounding shard_map machinery."""
    src = """
import jax
from jax import lax

def make_zero_step(mesh, state):
    def body(st, batch):
        g = compute_grads(st, batch)
        return lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)

    step = jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
    if process_index() == 0:
        allgather_records("zero_step_ready", True)
    return step
"""
    (f,) = _findings(src)
    assert f.symbol == "make_zero_step"
    assert "allgather_records" in f.message


def test_fires_on_early_return_before_agreement_in_rs_driver():
    """Early-return form: one host leaves the reduce-scatter driver
    before the shard-layout agreement its peers block in."""
    src = """
from jax import lax

def place_and_agree(state, mesh):
    if process_index() != 0:
        return state
    sharded = reduce_scatter_all_buckets(state)
    agree("zero_layout", None)
    return sharded
"""
    (f,) = _findings(src)
    assert "early" in f.message


def test_silent_on_clean_shard_map_reduce_scatter_body():
    """The sanctioned zero_overlap shape: device collectives inside the
    shard_map body (psum_scatter / all_gather fenced by
    optimization_barrier), host agreement outside any host-conditioned
    branch, process_count() fast path exempt."""
    src = """
import jax
from jax import lax

def make_zero_step(mesh, plan):
    def body(st, batch):
        grads = compute_grads(st, batch)
        token = zero_token()
        for bucket in plan:
            fenced = lax.optimization_barrier(tuple(grads[i] for i in bucket) + (token,))
            token = fenced[-1]
            grads = [lax.psum_scatter(g, "data", scatter_dimension=0, tiled=True)
                     for g in fenced[:-1]]
        return [lax.all_gather(g, "data", axis=0, tiled=True) for g in grads]

    if process_count() <= 1:
        return jax.jit(body)
    records = allgather_records("zero_plan", True)
    raise_if_poisoned(records, "the bucket-plan agreement")
    return jax.shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
"""
    assert _findings(src) == []


def test_silent_on_branching_on_rs_agreement_result():
    """Branch-on-the-result beside the device collective: the agreement
    runs on every host; only the follow-up work is host-local."""
    src = """
from jax import lax

def publish_zero_shards(state):
    shard = lax.psum_scatter(state, "data", scatter_dimension=0, tiled=True)
    records = allgather_records("zero_publish", True)
    if process_index() == 0 and all(r.ok for r in records):
        write_manifest(shard)
    return shard
"""
    assert _findings(src) == []


# -- the serving-mesh lowering shape (ISSUE 8, serve/programs.py) ------------


def test_fires_on_layout_agreement_under_process_index_in_mesh_boot():
    """A multi-host serve boot gone wrong: only host 0 runs the
    checkpoint-layout agreement after building the mesh groups — peers
    block in the allgather forever."""
    src = """
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def boot_sharded_plane(devices, mesh_size, layout):
    groups = build_group_placements(devices, mesh_size)
    if process_index() == 0:
        allgather_records("serve_layout", layout)
    return groups
"""
    (f,) = _findings(src)
    assert f.symbol == "boot_sharded_plane"
    assert "allgather_records" in f.message


def test_fires_on_early_return_before_mesh_ready_agreement():
    """Early-return form: a non-zero host leaves the mesh-group builder
    before the readiness agreement its peers wait in."""
    src = """
def build_and_agree(devices, mesh_size):
    if process_index() != 0:
        return None
    groups = build_group_placements(devices, mesh_size)
    agree("mesh_groups_ready", len(groups))
    return groups
"""
    (f,) = _findings(src)
    assert "early" in f.message


def test_silent_on_single_process_mesh_group_build():
    """The sanctioned programs.py shape: mesh building and pjit
    lowering run identically on every process; the only host collective
    sits outside any process_index-conditioned branch."""
    src = """
import jax
from jax.sharding import Mesh

def build_groups(devices, mesh_size, axis):
    groups = [Mesh(devices[i:i + mesh_size], (axis,))
              for i in range(0, len(devices), mesh_size)]
    allgather_records("mesh_groups_ready", len(groups))
    return groups
"""
    assert _findings(src) == []


def test_silent_on_log_only_process_index_branch_before_agreement():
    """A process_index() branch that only logs (both arms fall through)
    does not make the later agreement asymmetric."""
    src = """
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def boot_sharded_plane(devices, mesh_size):
    groups = build_group_placements(devices, mesh_size)
    if process_index() == 0:
        print(f"sharded plane: {len(groups)} mesh groups")
    allgather_records("serve_layout", True)
    return groups
"""
    assert _findings(src) == []


# -- the elastic shrink shape (ISSUE 10, runtime/elastic.py) -----------------


def test_fires_on_membership_agreement_on_lowest_survivor_only():
    """The elastic shape gone wrong: after a PeerFailure, 'agree' the
    shrunk membership by running the agreement collective on the lowest
    surviving rank only — the other survivors never arrive, and the
    shrink becomes a second hang. (The sanctioned design never runs a
    post-failure collective at all: survivors vote through records the
    SUPERVISOR reads, runtime/elastic.py.)"""
    src = """
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def agree_membership(survivors):
    if process_index() == min(survivors):
        allgather_records("membership", True)
    return survivors
"""
    (f,) = _findings(src)
    assert f.symbol == "agree_membership"


def test_fires_on_rebuild_barrier_with_member_dependent_trips():
    """Rebuild-time drain whose collective trip count depends on this
    host's rank: generation members run different numbers of
    agreements — the count-misalignment hang."""
    src = """
def drain_rebuild(members):
    while process_index() > members[0]:
        agree("rebuild_tick")
        members = members[1:]
"""
    (f,) = _findings(src)
    assert "host-dependent while" in f.message


def test_silent_on_survivor_record_write_under_pid_branch():
    """The sanctioned worker-side shrink shape: the survivor RECORD is
    host-local file I/O (each host writes its own vote; no collective
    anywhere on the unwind path), so a process_index-conditioned branch
    around it is clean — and a symmetric agreement BEFORE the failure
    window stays clean beside it."""
    src = """
import json
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def unwind_with_vote(directory, error):
    records = allgather_records("ckpt_publish", True)
    if process_index() in getattr(error, "hosts", []):
        return None
    with open(f"{directory}/survivor_r{process_index()}.json", "w") as f:
        json.dump({"rank": process_index()}, f)
    return records
"""
    assert _findings(src) == []


def test_fires_on_joiner_conditioned_grow_rendezvous():
    """The grow rendezvous gone wrong (ISSUE 11): running the agreement
    collective only when rank 0 SEES pending joiners — every other rank
    skips it (they can't see the joins), and the worlds' collective
    counts diverge the moment a join record lands. The sanctioned shape
    agrees rank 0's observation unconditionally and branches on the
    agreed detail."""
    src = """
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def grow_check(pending_joins):
    if process_index() == 0 and pending_joins:
        allgather_records("grow_check", True)
        return True
    return False
"""
    (f,) = _findings(src)
    assert f.symbol == "grow_check"


def test_silent_on_rank0_listing_with_symmetric_rendezvous():
    """The sanctioned grow rendezvous (runtime/elastic.py::
    maybe_grow_rendezvous): only the host-local DIR LISTING is
    rank-0-gated; the agreement collective runs unconditionally on
    every rank, and every rank acts on the agreed detail — all yield
    or none do."""
    src = """
import os
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def grow_check(directory):
    joins = []
    if process_index() == 0:
        joins = sorted(os.listdir(directory))
    records = allgather_records("grow_check", True, ",".join(joins))
    return records[0].detail != ""
"""
    assert _findings(src) == []


def test_silent_on_world_size_guarded_shrink_note():
    """The rebuilt-world bootstrap: process_count() guards are the
    sanctioned symmetric fast path, and the world_shrunk event record
    is host-local."""
    src = """
from pytorch_distributed_mnist_tpu.parallel.distributed import process_count

def note_rebuilt_world(old_members, new_members):
    if process_count() <= 1:
        return record_world_shrunk(old_members, new_members, 1)
    records = allgather_records("rebuild_ready", True)
    raise_if_poisoned(records, "the rebuild bootstrap")
    return record_world_shrunk(old_members, new_members, 1)
"""
    assert _findings(src) == []


# -- MPMD pipeline-serving shapes (serve/pipeline.py, ISSUE 12) --------------


def test_fires_on_stage_split_agreement_under_process_index():
    """FIRING: a per-stage param split done only on one host, with the
    layout agreement inside the branch — every other host skips the
    collective and the split worlds hang."""
    src = """
from pytorch_distributed_mnist_tpu.runtime import supervision
from pytorch_distributed_mnist_tpu.parallel.distributed import process_index

def install_stage_params(params, n_stages):
    if process_index() == 0:
        stages = [slice_stage(params, s) for s in range(n_stages)]
        supervision.agree("stage_split_ok")
        return stages
"""
    findings = _findings(src)
    assert findings and any("host-dependent" in f.message
                            for f in findings)


def test_silent_on_symmetric_stage_split_then_agreement():
    """NON-FIRING twin: every host splits identically (host-local array
    slicing, no rank in sight) and the agreement runs unconditionally —
    the shipped serve-plane shape, where the split is per-chip work and
    nothing is process_index-conditioned."""
    src = """
from pytorch_distributed_mnist_tpu.runtime import supervision

def install_stage_params(params, n_stages):
    stages = [slice_stage(params, s) for s in range(n_stages)]
    supervision.agree("stage_split_ok")
    return stages
"""
    assert _findings(src) == []


# -- hierarchical (DCN x ICI) collective shapes (PR 13) ----------------------


def test_fires_on_tier_agreement_gated_to_slice_leaders():
    """FIRING: the tempting two-tier shape — run the cross-slice (DCN)
    agreement on 'slice leaders' only. Host-side agreements are
    fixed-width allgathers over EVERY rank; a tier-conditioned call
    strands the non-leader hosts exactly like any process_index gate."""
    src = """
from pytorch_distributed_mnist_tpu.runtime import supervision

def dcn_tier_publish(ok, hosts_per_slice):
    if process_index() % hosts_per_slice == 0:
        supervision.allgather_records("dcn_publish", ok)
"""
    (f,) = _findings(src)
    assert "host-dependent" in f.message


def test_fires_on_slice_index_early_return_before_tier_agreement():
    """FIRING: slice 0's hosts bail out before the DCN-tier agreement —
    the early-return form of the same strand (the hazard is the
    collective AFTER the branch)."""
    src = """
def cross_slice_reduce(ok, hosts_per_slice):
    my_slice = process_index() // hosts_per_slice
    if my_slice == 0:
        return None
    return allgather_records("dcn_reduce", ok)
"""
    (f,) = _findings(src)
    assert "early return/raise" in f.message


def test_silent_on_symmetric_two_tier_schedule():
    """NON-FIRING: the shipped shape (parallel/zero_overlap.py's host
    twin) — every rank runs the ICI-tier agreement then the DCN-tier
    agreement, in order, unconditionally. Tiers change what each
    collective carries, never who runs it."""
    src = """
from pytorch_distributed_mnist_tpu.runtime import supervision

def two_tier_update(ok):
    supervision.allgather_records("ici_reduce_scatter", ok)
    supervision.allgather_records("dcn_shard_allreduce", ok)
    supervision.allgather_records("ici_allgather", ok)
"""
    assert _findings(src) == []


def test_silent_on_world_size_guarded_tier_agreement():
    """NON-FIRING: the sanctioned symmetric guard — a single-process
    (or single-slice) world skips the tier agreement on EVERY host via
    process_count(), which cannot diverge across hosts."""
    src = """
def maybe_dcn_agree(ok, n_slices):
    if process_count() <= 1 or n_slices <= 1:
        return []
    return allgather_records("dcn_shard_allreduce", ok)
"""
    assert _findings(src) == []


# -- ISSUE 18: manifest publish agreement ------------------------------------


def test_fires_on_manifest_agreement_under_process_index():
    """FIRING twin: confirming a delta publish with a collective only
    on the writing host — every other host blocks in the agreement
    process 0 never enters (or vice versa). The structural-hang class
    the delta publish must not reintroduce."""
    src = """
def publish_manifest(manifest, epoch, ok):
    if process_index() == 0:
        write_manifest(manifest, epoch)
        return allgather_records("manifest_published", ok)
"""
    (f,) = _findings(src)
    assert "host-dependent" in f.message


def test_silent_on_rank0_manifest_write_with_symmetric_agreement():
    """NON-FIRING twin: the sanctioned shape (publish_state's gate) —
    process 0 alone does the local file work, then EVERY host runs the
    same agreement on the outcome. The branch guards pure IO; the
    collective is unconditional."""
    src = """
def publish_manifest(manifest, epoch, ok):
    if process_index() == 0:
        write_manifest(manifest, epoch)
    return allgather_records("manifest_published", ok)
"""
    assert _findings(src) == []
