"""Batcher state machine: deadline flush, full-batch flush, admission
control under a stalled engine, error propagation, latency accounting.
All in-process with stub infer functions — no device, no sockets."""

import threading
import time

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher, Overloaded
from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog

pytestmark = pytest.mark.serve


def _rows(n, base=0.0):
    """n distinct single-feature rows (row i carries base + i)."""
    return (np.arange(n, dtype=np.float32) + base).reshape(n, 1)


class RecordingInfer:
    """Identity infer stub that records the row count of every batch."""

    def __init__(self):
        self.batch_sizes = []
        self.lock = threading.Lock()

    def __call__(self, images):
        with self.lock:
            self.batch_sizes.append(images.shape[0])
        return images

    def total_batches(self):
        with self.lock:
            return len(self.batch_sizes)


def test_deadline_flush_coalesces_trickle():
    """Requests trickling in under the deadline ride ONE batch; the flush
    happens at the deadline, not at max_batch."""
    infer = RecordingInfer()
    with MicroBatcher(infer, max_batch=64, max_wait_s=0.25) as b:
        pendings = [b.submit(_rows(1, base=i)) for i in range(3)]
        results = [b.result(p, timeout=10.0) for p in pendings]
    for i, r in enumerate(results):
        np.testing.assert_array_equal(r, _rows(1, base=i))
    # All three arrived well inside the 250ms window -> coalesced. Allow 2
    # batches for scheduling jitter (worker waking between submits), but a
    # per-request batch would mean coalescing is broken.
    assert infer.total_batches() <= 2


def test_full_batch_flushes_before_deadline():
    """max_batch rows waiting -> the batch flushes immediately; a 10s
    deadline must not be what releases it."""
    infer = RecordingInfer()
    t0 = time.perf_counter()
    with MicroBatcher(infer, max_batch=8, max_wait_s=10.0) as b:
        pendings = [b.submit(_rows(1, base=i)) for i in range(8)]
        for i, p in enumerate(pendings):
            np.testing.assert_array_equal(b.result(p, timeout=10.0),
                                          _rows(1, base=i))
    assert time.perf_counter() - t0 < 5.0  # nowhere near the deadline
    assert max(infer.batch_sizes) == 8


def test_multi_row_requests_keep_row_mapping():
    """Requests of different sizes coalesce; each gets exactly its own
    rows back (slice bookkeeping)."""
    infer = RecordingInfer()
    with MicroBatcher(infer, max_batch=16, max_wait_s=0.05) as b:
        pa = b.submit(_rows(3, base=100))
        pb = b.submit(_rows(5, base=200))
        ra = b.result(pa, timeout=10.0)
        rb = b.result(pb, timeout=10.0)
    np.testing.assert_array_equal(ra, _rows(3, base=100))
    np.testing.assert_array_equal(rb, _rows(5, base=200))


def test_requests_never_split_across_batches():
    """A request whose rows would straddle max_batch waits for the next
    batch whole — results map back by contiguous slices."""
    infer = RecordingInfer()
    with MicroBatcher(infer, max_batch=4, max_wait_s=0.05) as b:
        pendings = [b.submit(_rows(3, base=100 * i)) for i in range(3)]
        for i, p in enumerate(pendings):
            np.testing.assert_array_equal(b.result(p, timeout=10.0),
                                          _rows(3, base=100 * i))
    assert all(s <= 4 for s in infer.batch_sizes)


def test_admission_control_rejects_when_stalled():
    """A stalled engine fills the bounded queue; the next submit raises
    Overloaded IMMEDIATELY (no work done for it), and everything already
    admitted completes once the engine recovers."""
    started = threading.Event()
    release = threading.Event()
    log = ServeLog()

    def stalled(images):
        started.set()
        assert release.wait(30.0), "test deadlock"
        return images

    with MicroBatcher(stalled, max_batch=2, max_wait_s=0.001,
                      max_queue=3, serve_log=log) as b:
        first = b.submit(_rows(1))
        assert started.wait(10.0)  # worker is now wedged inside infer_fn
        admitted = [b.submit(_rows(1, base=i + 1)) for i in range(3)]
        t0 = time.perf_counter()
        with pytest.raises(Overloaded):
            b.submit(_rows(1, base=99))
        assert time.perf_counter() - t0 < 1.0  # rejected, not queued
        release.set()
        b.result(first, timeout=10.0)
        for p in admitted:
            b.result(p, timeout=10.0)
    snap = log.snapshot()
    assert snap["rejected"] == 1
    assert snap["requests"] == 4  # the rejected request never completes


def test_infer_error_propagates_to_every_rider():
    def boom(images):
        raise RuntimeError("engine on fire")

    with MicroBatcher(boom, max_batch=8, max_wait_s=0.01) as b:
        pa, pb = b.submit(_rows(1)), b.submit(_rows(1))
        for p in (pa, pb):
            with pytest.raises(RuntimeError, match="engine on fire"):
                b.result(p, timeout=10.0)


def test_latency_accounting():
    log = ServeLog()
    with MicroBatcher(lambda x: x, max_batch=4, max_wait_s=0.001,
                      serve_log=log) as b:
        for i in range(5):
            b.predict(_rows(2, base=i), timeout=10.0)
    snap = log.snapshot()
    assert snap["requests"] == 5
    assert snap["images"] == 10
    lat = snap["latency_ms"]
    assert lat["count"] == 5
    assert lat["p50"] >= 0.0 and lat["p99"] >= lat["p50"]
    assert lat["max"] >= lat["p99"]
    # queue wait is part of latency, never more than it
    assert snap["queue_wait_ms"]["p50"] <= lat["p50"] + 1e-6


def test_timed_out_request_is_dropped_not_executed():
    """A caller that gave up (TimeoutError) must not cost device work or
    pollute stats: its still-queued request is dropped, and the freed
    queue slot goes back to admission control."""
    started = threading.Event()
    release = threading.Event()
    infer = RecordingInfer()
    log = ServeLog()

    def stalled(images):
        started.set()
        assert release.wait(30.0), "test deadlock"
        return infer(images)

    with MicroBatcher(stalled, max_batch=1, max_wait_s=0.001,
                      max_queue=2, serve_log=log) as b:
        first = b.submit(_rows(1, base=0))
        assert started.wait(10.0)  # worker wedged; queue is empty again
        doomed = b.submit(_rows(1, base=77))
        with pytest.raises(TimeoutError):
            b.result(doomed, timeout=0.1)
        survivor = b.submit(_rows(1, base=5))
        release.set()
        np.testing.assert_array_equal(b.result(first, timeout=10.0),
                                      _rows(1, base=0))
        np.testing.assert_array_equal(b.result(survivor, timeout=10.0),
                                      _rows(1, base=5))
    # Two batches executed (first + survivor); the abandoned request was
    # dropped before execution and never entered the stats.
    assert infer.total_batches() == 2
    snap = log.snapshot()
    assert snap["requests"] == 2  # doomed is not a phantom completion
    assert snap["images"] == 2


def test_oversized_follower_does_not_flush_small_request_early():
    """Trigger/take consistency: a small request followed by an
    oversized one must keep its full coalescing window (the oversized
    request cannot co-batch, so it must not count toward the flush
    threshold)."""
    infer = RecordingInfer()
    with MicroBatcher(infer, max_batch=4, max_wait_s=0.3) as b:
        t0 = time.perf_counter()
        small = b.submit(_rows(1, base=0))
        big = b.submit(_rows(9, base=100))  # > max_batch: rides alone
        np.testing.assert_array_equal(b.result(small, timeout=10.0),
                                      _rows(1, base=0))
        waited = time.perf_counter() - t0
        np.testing.assert_array_equal(b.result(big, timeout=10.0),
                                      _rows(9, base=100))
    # The 1-row request held its window open for co-riders instead of
    # flushing the moment the un-batchable 9-row arrived.
    assert waited >= 0.2, waited
    assert infer.batch_sizes[0] == 1 and 9 in infer.batch_sizes


def test_close_drains_queue():
    """close() after submits must complete them, not strand callers."""
    b = MicroBatcher(lambda x: x, max_batch=4, max_wait_s=5.0).start()
    pendings = [b.submit(_rows(1, base=i)) for i in range(3)]
    b.close()
    for i, p in enumerate(pendings):
        np.testing.assert_array_equal(b.result(p, timeout=1.0),
                                      _rows(1, base=i))
    with pytest.raises(RuntimeError, match="shut down"):
        b.submit(_rows(1))


def test_submit_rejects_non_stacks():
    with MicroBatcher(lambda x: x, max_batch=4) as b:
        with pytest.raises(ValueError, match="stack"):
            b.submit(np.zeros(28, np.float32))
