"""Elastic runtime unit tests (fast, tier-1): the worker-side membership
vote (survivor records), the supervisor's pure membership planning, flag
plumbing, and the world_shrunk observability event. The real
multi-process shrink twins live in tests/test_elastic_chaos.py."""

import json
import os

import pytest

from pytorch_distributed_mnist_tpu.runtime import elastic, supervision
from pytorch_distributed_mnist_tpu.runtime.elastic import (
    DIR_ENV,
    GEN_ENV,
    MEMBERS_ENV,
    PREV_ENV,
    is_transport_suspect,
    plan_next_world,
    strip_elastic_flags,
    write_survivor_record,
)
from pytorch_distributed_mnist_tpu.utils.profiling import failure_events

pytestmark = pytest.mark.elastic


def _peer_failure(hosts=(1,), phase="ckpt_publish", reason="died"):
    return supervision.PeerFailure(
        "PeerFailure: test", hosts=list(hosts), phase=phase, reason=reason)


def _elastic_env(monkeypatch, tmp_path, gen=0, members="0,1"):
    monkeypatch.setenv(DIR_ENV, str(tmp_path))
    monkeypatch.setenv(GEN_ENV, str(gen))
    monkeypatch.setenv(MEMBERS_ENV, members)
    monkeypatch.delenv(PREV_ENV, raising=False)


# -- worker side: the membership vote ---------------------------------------


def test_survivor_record_written_for_peer_failure(monkeypatch, tmp_path):
    _elastic_env(monkeypatch, tmp_path, gen=2, members="0,3,5")
    path = write_survivor_record(_peer_failure(hosts=[1], phase="train@4"))
    assert path == elastic.record_path(str(tmp_path), 2, 0)
    with open(path) as f:
        rec = json.load(f)
    assert rec["generation"] == 2 and rec["rank"] == 0
    assert rec["host"] == 0  # members[rank]
    assert rec["dead_ranks"] == [1] and rec["dead_hosts"] == [3]
    assert rec["phase"] == "train@4"


def test_survivor_record_for_transport_shaped_error(monkeypatch, tmp_path):
    """A peer death surfacing inside a DEVICE program (a step's psum)
    arrives as a raw runtime error, not a PeerFailure — still a
    survivorship vote, with the dead set left for the supervisor to
    infer from who else exited recordless."""
    _elastic_env(monkeypatch, tmp_path)
    exc = ValueError(
        "UNKNOWN: Gloo AllGather failed: [external/gloo/...] "
        "Connection reset by peer [127.0.0.1]:36237")
    prev_phase = supervision.set_phase("train@1")
    try:
        path = write_survivor_record(exc)
    finally:
        supervision.set_phase(prev_phase)
    assert path is not None
    with open(path) as f:
        rec = json.load(f)
    assert rec["dead_ranks"] == [] and rec["dead_hosts"] == []
    # The record names where the world DIED, not the membership phase
    # the unwind itself enters (a transport error has no .phase of its
    # own — the pre-unwind lifecycle phase is the right attribution).
    assert rec["phase"] == "train@1"


@pytest.mark.parametrize("error", [
    RuntimeError("division by zero in my own staging code"),
    KeyboardInterrupt(),
    SystemExit("resume outcome diverged across hosts"),
])
def test_no_record_for_non_survivor_errors(monkeypatch, tmp_path, error):
    """A host failing on its OWN error (or an agreed symmetric exit, or
    the operator's ctrl-C) must not vote itself back into the world."""
    _elastic_env(monkeypatch, tmp_path)
    assert write_survivor_record(error) is None
    assert os.listdir(tmp_path) == []


def test_no_record_outside_elastic_worker(monkeypatch, tmp_path):
    monkeypatch.delenv(DIR_ENV, raising=False)
    assert write_survivor_record(_peer_failure()) is None


def test_record_write_failure_is_swallowed(monkeypatch, tmp_path, capsys):
    """The record write runs on an unwind path: an IO failure must warn
    and return None (the supervisor counts this rank dead — strictly a
    smaller world), never mask the run's own exception."""
    target = tmp_path / "not_a_dir"
    target.write_text("a file where the rendezvous dir should be")
    monkeypatch.setenv(DIR_ENV, str(target))
    monkeypatch.setenv(GEN_ENV, "0")
    monkeypatch.setenv(MEMBERS_ENV, "0,1")
    assert write_survivor_record(_peer_failure()) is None
    assert "could not be written" in capsys.readouterr().err


def test_elastic_rebuild_fault_point_fires_in_record_path(
        monkeypatch, tmp_path):
    """The mid-rebuild chaos hook: a fault injected at elastic_rebuild
    fires exactly in the survivor-record window (a second failure
    DURING the shrink)."""
    _elastic_env(monkeypatch, tmp_path)
    monkeypatch.setenv(supervision.FAULT_ENV, "elastic_rebuild:0:raise")
    monkeypatch.setattr(supervision, "_fault_parsed", False)
    try:
        with pytest.raises(supervision.InjectedFault):
            write_survivor_record(_peer_failure())
        assert os.listdir(tmp_path) == []  # died before the vote landed
    finally:
        monkeypatch.setattr(supervision, "_fault_parsed", False)
        monkeypatch.delenv(supervision.FAULT_ENV)


def test_transport_suspect_classifier():
    assert is_transport_suspect(ValueError("Gloo AllReduce failed"))
    assert is_transport_suspect(RuntimeError("connection reset by peer"))
    assert is_transport_suspect(
        RuntimeError("coordination service heartbeat failure"))
    assert not is_transport_suspect(ValueError("shapes do not match"))
    assert not is_transport_suspect(OSError("no space left on device"))


# -- worker side: the world_shrunk event ------------------------------------


def test_note_rebuilt_world_records_event(monkeypatch, tmp_path):
    _elastic_env(monkeypatch, tmp_path, gen=1, members="0,2")
    monkeypatch.setenv(PREV_ENV, "0,1,2")
    failure_events.reset()
    elastic.note_rebuilt_world()
    events = [e for e in failure_events.snapshot()
              if e["kind"] == "world_shrunk"]
    assert len(events) == 1
    assert events[0]["old_members"] == [0, 1, 2]
    assert events[0]["new_members"] == [0, 2]
    assert events[0]["generation"] == 1


def test_note_rebuilt_world_noop_outside_rebuild(monkeypatch, tmp_path):
    failure_events.reset()
    # Generation 0 (no PREV): nothing shrank yet.
    _elastic_env(monkeypatch, tmp_path)
    elastic.note_rebuilt_world()
    # Not an elastic worker at all.
    for env in (DIR_ENV, GEN_ENV, MEMBERS_ENV, PREV_ENV):
        monkeypatch.delenv(env, raising=False)
    elastic.note_rebuilt_world()
    assert [e for e in failure_events.snapshot()
            if e["kind"] == "world_shrunk"] == []


# -- supervisor side: pure membership planning ------------------------------


def test_plan_survivors_from_records_and_clean_exits():
    # rank 0 finished (rc 0), rank 1 voted (record), rank 2 SIGKILLed.
    survivors, dead = plan_next_world(3, [0, 75, -9], [1])
    assert survivors == [0, 1] and dead == [2]


def test_plan_recordless_nonzero_exit_is_dead():
    # rank 1 exited on its own error without a record: not a survivor.
    survivors, dead = plan_next_world(2, [1, 1], [0])
    assert survivors == [0] and dead == [1]


def test_plan_record_outranks_exit_code():
    # A survivor killed during teardown (hard exit 75 / supervisor
    # straggler kill -9) still survives: the record is the proof.
    survivors, dead = plan_next_world(2, [-9, -9], [0])
    assert survivors == [0] and dead == [1]


def test_plan_no_survivors():
    survivors, dead = plan_next_world(2, [-9, 1], [])
    assert survivors == [] and dead == [0, 1]


def test_plan_symmetric_failure_shrinks_nothing():
    # Everyone voted survivor (all PeerFailure'd on ... nothing dead?)
    # — plan reports no dead rank; supervise() treats that as a
    # non-shrink failure and propagates.
    survivors, dead = plan_next_world(2, [1, 1], [0, 1])
    assert survivors == [0, 1] and dead == []


# -- supervisor side: flag plumbing and validation --------------------------


def test_strip_elastic_flags():
    argv = ["--spawn", "3", "--elastic", "--min-world", "2",
            "--model", "linear", "--min-world=1", "--elastic"]
    assert strip_elastic_flags(argv) == ["--spawn", "3", "--model",
                                         "linear"]


def test_strip_resume():
    argv = ["--resume", "auto", "--model", "linear",
            "--resume=/some/path.npz"]
    assert elastic._strip_resume(argv) == ["--model", "linear"]


def test_supervise_validates_inputs():
    with pytest.raises(ValueError, match=">= 2"):
        elastic.supervise(1, [])
    with pytest.raises(ValueError, match="min-world"):
        elastic.supervise(2, [], min_world=0)
    with pytest.raises(ValueError, match="exceeds"):
        elastic.supervise(2, [], min_world=3)


def test_cli_rejects_elastic_without_spawn():
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit, match="requires --spawn"):
        main(["--elastic", "--dataset", "synthetic"])


def test_cli_rejects_min_world_over_spawn():
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit, match="exceeds the initial world"):
        main(["--elastic", "--spawn", "2", "--min-world", "3"])
