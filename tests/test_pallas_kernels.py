"""Pallas kernels vs their XLA/optax oracles (interpret mode on CPU).

Every kernel runs in interpreter mode off-TPU (the kernels gate on
``jax.default_backend()``), so these tests exercise the identical kernel
bodies that compile on real chips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_mnist_tpu.ops.attention import full_attention
from pytorch_distributed_mnist_tpu.ops.pallas.adam import fused_adam_leaf, pallas_adam
from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention
from pytorch_distributed_mnist_tpu.ops.pallas.matmul_i8 import (
    int8_dot_general,
    matmul_i8,
    quantize_dynamic_i8,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state, make_optimizer
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


# ---------------------------------------------------------------- fused adam

@pytest.mark.parametrize("shape", [(7,), (32, 10), (3, 3, 8, 5), ()])
def test_fused_adam_leaf_matches_optax(shape):
    """Kernel == optax.adam update for one leaf, any shape incl. scalar."""
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=shape), jnp.float32)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8

    tx = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    state = tx.init(p)
    want_delta, state = tx.update(g, state, p)

    m = jnp.zeros(shape, jnp.float32)
    v = jnp.zeros(shape, jnp.float32)
    hypers = jnp.asarray(
        [lr, b1, b2, eps, 1 / (1 - b1), 1 / (1 - b2), 1 - b1, 1 - b2, 0.0],
        jnp.float32,
    )
    delta, m1, v1 = fused_adam_leaf(g, m, v, hypers)
    adam_state = state[0]  # optax.adam = chain(scale_by_adam, scale)
    np.testing.assert_allclose(delta, want_delta, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(m1, adam_state.mu, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(v1, adam_state.nu, rtol=1e-6, atol=1e-8)


def test_pallas_adam_transform_matches_optax_over_steps():
    """Full transform: 5 steps on a pytree track optax.adam elementwise."""
    rng = np.random.default_rng(1)
    params = {
        "w": jnp.asarray(rng.normal(size=(13, 4)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
    }
    ref_tx = optax.adam(1e-2)
    pal_tx = pallas_adam(1e-2)
    ref_state, pal_state = ref_tx.init(params), pal_tx.init(params)
    ref_p = pal_p = params
    for i in range(5):
        g = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params
        )
        ref_u, ref_state = ref_tx.update(g, ref_state, ref_p)
        pal_u, pal_state = pal_tx.update(g, pal_state, pal_p)
        ref_p = optax.apply_updates(ref_p, ref_u)
        pal_p = optax.apply_updates(pal_p, pal_u)
    for a, b in zip(jax.tree_util.tree_leaves(ref_p),
                    jax.tree_util.tree_leaves(pal_p)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_adam_pallas_trains_end_to_end():
    """A jitted train step with the fused optimizer learns on a fixed batch."""
    model = get_model("cnn")
    state = create_train_state(model, jax.random.key(0), optimizer="adam_pallas")
    step = make_train_step()
    rng = np.random.default_rng(2)
    batch = {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m.loss_sum) / float(m.count))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_adam_pallas_checkpoint_state_shape_matches_adam():
    """Same opt_state pytree as stock adam -> checkpoints interchangeable."""
    model = get_model("linear")
    s1 = create_train_state(model, jax.random.key(0), optimizer="adam")
    s2 = create_train_state(model, jax.random.key(0), optimizer="adam_pallas")
    t1 = jax.tree_util.tree_structure(s1.opt_state)
    t2 = jax.tree_util.tree_structure(s2.opt_state)
    assert t1 == t2


# ----------------------------------------------------------- flash attention

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 256])
def test_flash_attention_matches_dense(causal, t):
    ks = jax.random.split(jax.random.key(3), 3)
    q, k, v = (jax.random.normal(kk, (2, t, 4, 32), jnp.float32) for kk in ks)
    out = flash_attention(q, k, v, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 256])
def test_flash_attention_grad_matches_dense(causal, t):
    """Fused Pallas backward (dq/dk/dv kernels) == vjp of the dense oracle."""
    ks = jax.random.split(jax.random.key(4), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("block", [64, 256])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_block_override(block, causal):
    """Numerics are block-size invariant (fwd AND bwd): the ``block``
    override exists so tools/sweep_flash.py can tune the tile edge on
    chip — any size must produce the same attention, including when the
    block exceeds T (256 > 192: single padded tile) and when it divides
    T unevenly (64 into 192)."""
    t = 192
    ks = jax.random.split(jax.random.key(9), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block=block) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    out = flash_attention(q, k, v, causal=causal, block=block)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_flash_attention_block_bounds_rejected():
    """The block override is bounded on both ends: non-multiple-of-8
    below, and >512 above (the block^2 f32 VMEM scratch would blow the
    ~16 MB/core budget with an opaque Mosaic error instead of this
    message — round-3 advisor finding)."""
    ks = jax.random.split(jax.random.key(9), 3)
    q, k, v = (jax.random.normal(kk, (1, 64, 2, 16), jnp.float32)
               for kk in ks)
    with pytest.raises(ValueError, match="multiple of 8"):
        flash_attention(q, k, v, block=20)
    with pytest.raises(ValueError, match="<= 512"):
        flash_attention(q, k, v, block=1024)


@pytest.mark.parametrize("t", [49, 200])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grad_unaligned_lengths(t, causal):
    """Backward kernels mask padded rows/cols exactly like the forward."""
    ks = jax.random.split(jax.random.key(6), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_flash_attention_bwd_never_materializes_scores():
    """No (T, T) intermediate anywhere in the grad program.

    With T=256 and 128-blocks, a dense-recompute backward would carry a
    (..., 256, 256) score matrix; the fused kernels only ever hold
    (128, 128) tiles. Checked on the whole grad jaxpr."""
    ks = jax.random.split(jax.random.key(7), 3)
    t = 256
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    assert f"{t},{t}" not in str(jaxpr)


def test_flash_attention_rejects_cross_attention_shapes():
    """Tq != Tk raises: the kernel's causal mask alignment assumes Tq == Tk."""
    k1, k2 = jax.random.split(jax.random.key(8))
    q = jax.random.normal(k1, (1, 32, 2, 16), jnp.float32)
    k = v = jax.random.normal(k2, (1, 64, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="Tq == Tk"):
        flash_attention(q, k, v)


@pytest.mark.parametrize("t", [49, 127, 200])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_unaligned_lengths(t, causal):
    """Odd/prime T pads to a block multiple with masked tail positions."""
    ks = jax.random.split(jax.random.key(5), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 4, 16), jnp.float32) for kk in ks)
    np.testing.assert_allclose(
        flash_attention(q, k, v, causal=causal),
        full_attention(q, k, v, causal=causal),
        rtol=1e-5, atol=1e-5,
    )


def test_fused_adam_bf16_grads_keep_f32_moments():
    """bf16 gradients must not demote the f32 moment buffers."""
    g = jnp.ones((10,), jnp.bfloat16)
    m = jnp.zeros((10,), jnp.float32)
    v = jnp.zeros((10,), jnp.float32)
    hypers = jnp.asarray(
        [1e-3, 0.9, 0.999, 1e-8, 10.0, 1000.0, 0.1, 0.001, 0.0], jnp.float32
    )
    delta, m1, v1 = fused_adam_leaf(g, m, v, hypers)
    assert delta.dtype == jnp.bfloat16
    assert m1.dtype == jnp.float32 and v1.dtype == jnp.float32


# --------------------------------------------------------- int8 MXU matmul

@pytest.mark.parametrize("shape", [(5, 7, 11), (128, 64, 10), (33, 200, 130)])
def test_matmul_i8_exact_integer_oracle(shape):
    """int8 x int8 -> int32 is EXACT integer arithmetic (the int32
    accumulator never rounds), so the kernel must equal np.matmul
    bit-for-bit — including the unaligned shapes that exercise the
    (32, 128) tile padding, whose zero rows/lanes contribute nothing."""
    m, k, n = shape
    rng = np.random.default_rng(10)
    a = rng.integers(-127, 128, size=(m, k), dtype=np.int8)
    b = rng.integers(-127, 128, size=(k, n), dtype=np.int8)
    out = matmul_i8(jnp.asarray(a), jnp.asarray(b))
    want = np.matmul(a.astype(np.int32), b.astype(np.int32))
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), want)


def test_matmul_i8_rejects_non_int8_operands():
    a = jnp.zeros((4, 8), jnp.float32)
    b = jnp.zeros((8, 4), jnp.int8)
    with pytest.raises(ValueError, match="int8 operands"):
        matmul_i8(a, b)


def test_quantize_dynamic_i8_roundtrip():
    """Symmetric per-tensor quantization: values stay in [-127, 127],
    the dequantized round-trip lands within half a quantization step,
    and the extremum maps onto the grid end exactly."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    q, scale = quantize_dynamic_i8(x)
    assert q.dtype == jnp.int8 and float(scale) > 0
    qn = np.asarray(q, np.int32)
    assert qn.min() >= -127 and qn.max() <= 127
    np.testing.assert_allclose(
        qn.astype(np.float32) * float(scale), np.asarray(x),
        atol=float(scale) / 2 + 1e-7)
    assert np.max(np.abs(qn)) == 127  # the extremum pins the grid end


def test_int8_dot_general_matches_dequant_oracle():
    """The Dense contraction through the kernel == quantize-then-f32-
    matmul, tightly: the int32 accumulation is exact where the f32
    oracle rounds, so any gap beyond f32 epsilon is a kernel bug. The
    loose pin vs the unquantized f32 product bounds total quantization
    error (per-tensor scales over K=64 terms)."""
    rng = np.random.default_rng(12)
    lhs = jnp.asarray(rng.normal(size=(4, 6, 64)), jnp.float32)
    rhs = jnp.asarray(rng.normal(size=(64, 10)), jnp.float32)
    dn = (((2,), (0,)), ((), ()))
    out = int8_dot_general(lhs, rhs, dn)
    assert out.shape == (4, 6, 10) and out.dtype == jnp.float32
    qa, sa = quantize_dynamic_i8(lhs.reshape(-1, 64))
    qb, sb = quantize_dynamic_i8(rhs)
    oracle = (qa.astype(jnp.float32) * sa) @ (qb.astype(jnp.float32) * sb)
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, 10), np.asarray(oracle),
        rtol=1e-5, atol=1e-5)
    ref = jax.lax.dot_general(lhs, rhs, dn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=0.5)


def test_int8_dot_general_falls_back_verbatim_on_batch_dims():
    """Any contraction that is not the plain Dense shape (here: batched
    einsum) must be lax.dot_general UNCHANGED — bitwise, not allclose —
    so wiring the kernel through a model's dot_general field can never
    miscompute a contraction it wasn't built for."""
    rng = np.random.default_rng(13)
    lhs = jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32)
    rhs = jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)
    dn = (((2,), (1,)), ((0,), (0,)))
    out = int8_dot_general(lhs, rhs, dn)
    ref = jax.lax.dot_general(lhs, rhs, dn)
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), np.asarray(ref).view(np.uint32))


def test_int8_dot_general_injects_through_model_field():
    """End-to-end through the serving wiring: get_model(...,
    dot_general=int8_dot_general) — the int8 plane's injection — keeps
    the linear model's logits within quantization error of the plain
    instance on the SAME checkpoint tree, preserving argmax."""
    model = get_model("linear")
    params = model.init(jax.random.key(0), jnp.zeros((1, 28, 28, 1)))["params"]
    rng = np.random.default_rng(14)
    x = jnp.asarray(rng.random(size=(16, 28, 28, 1)), jnp.float32)
    plain = model.apply({"params": params}, x, train=False)
    quant = get_model("linear", dot_general=int8_dot_general).apply(
        {"params": params}, x, train=False)
    np.testing.assert_allclose(
        np.asarray(quant), np.asarray(plain), atol=0.05)
    assert float(jnp.mean(
        jnp.argmax(quant, -1) == jnp.argmax(plain, -1))) >= 0.9
