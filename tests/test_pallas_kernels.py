"""Pallas kernels vs their XLA/optax oracles (interpret mode on CPU).

Every kernel runs in interpreter mode off-TPU (the kernels gate on
``jax.default_backend()``), so these tests exercise the identical kernel
bodies that compile on real chips.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_mnist_tpu.ops.attention import full_attention
from pytorch_distributed_mnist_tpu.ops.pallas.adam import fused_adam_leaf, pallas_adam
from pytorch_distributed_mnist_tpu.ops.pallas.flash import flash_attention
from pytorch_distributed_mnist_tpu.train.state import create_train_state, make_optimizer
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


# ---------------------------------------------------------------- fused adam

@pytest.mark.parametrize("shape", [(7,), (32, 10), (3, 3, 8, 5), ()])
def test_fused_adam_leaf_matches_optax(shape):
    """Kernel == optax.adam update for one leaf, any shape incl. scalar."""
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.normal(size=shape), jnp.float32)
    g = jnp.asarray(rng.normal(size=shape), jnp.float32)
    lr, b1, b2, eps = 1e-3, 0.9, 0.999, 1e-8

    tx = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    state = tx.init(p)
    want_delta, state = tx.update(g, state, p)

    m = jnp.zeros(shape, jnp.float32)
    v = jnp.zeros(shape, jnp.float32)
    hypers = jnp.asarray(
        [lr, b1, b2, eps, 1 / (1 - b1), 1 / (1 - b2), 1 - b1, 1 - b2, 0.0],
        jnp.float32,
    )
    delta, m1, v1 = fused_adam_leaf(g, m, v, hypers)
    adam_state = state[0]  # optax.adam = chain(scale_by_adam, scale)
    np.testing.assert_allclose(delta, want_delta, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(m1, adam_state.mu, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(v1, adam_state.nu, rtol=1e-6, atol=1e-8)


def test_pallas_adam_transform_matches_optax_over_steps():
    """Full transform: 5 steps on a pytree track optax.adam elementwise."""
    rng = np.random.default_rng(1)
    params = {
        "w": jnp.asarray(rng.normal(size=(13, 4)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(4,)), jnp.float32),
    }
    ref_tx = optax.adam(1e-2)
    pal_tx = pallas_adam(1e-2)
    ref_state, pal_state = ref_tx.init(params), pal_tx.init(params)
    ref_p = pal_p = params
    for i in range(5):
        g = jax.tree_util.tree_map(
            lambda p: jnp.asarray(rng.normal(size=p.shape), jnp.float32), params
        )
        ref_u, ref_state = ref_tx.update(g, ref_state, ref_p)
        pal_u, pal_state = pal_tx.update(g, pal_state, pal_p)
        ref_p = optax.apply_updates(ref_p, ref_u)
        pal_p = optax.apply_updates(pal_p, pal_u)
    for a, b in zip(jax.tree_util.tree_leaves(ref_p),
                    jax.tree_util.tree_leaves(pal_p)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_adam_pallas_trains_end_to_end():
    """A jitted train step with the fused optimizer learns on a fixed batch."""
    model = get_model("cnn")
    state = create_train_state(model, jax.random.key(0), optimizer="adam_pallas")
    step = make_train_step()
    rng = np.random.default_rng(2)
    batch = {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }
    losses = []
    for _ in range(8):
        state, m = step(state, batch)
        losses.append(float(m.loss_sum) / float(m.count))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_adam_pallas_checkpoint_state_shape_matches_adam():
    """Same opt_state pytree as stock adam -> checkpoints interchangeable."""
    model = get_model("linear")
    s1 = create_train_state(model, jax.random.key(0), optimizer="adam")
    s2 = create_train_state(model, jax.random.key(0), optimizer="adam_pallas")
    t1 = jax.tree_util.tree_structure(s1.opt_state)
    t2 = jax.tree_util.tree_structure(s2.opt_state)
    assert t1 == t2


# ----------------------------------------------------------- flash attention

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 256])
def test_flash_attention_matches_dense(causal, t):
    ks = jax.random.split(jax.random.key(3), 3)
    q, k, v = (jax.random.normal(kk, (2, t, 4, 32), jnp.float32) for kk in ks)
    out = flash_attention(q, k, v, causal=causal)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 256])
def test_flash_attention_grad_matches_dense(causal, t):
    """Fused Pallas backward (dq/dk/dv kernels) == vjp of the dense oracle."""
    ks = jax.random.split(jax.random.key(4), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("block", [64, 256])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_block_override(block, causal):
    """Numerics are block-size invariant (fwd AND bwd): the ``block``
    override exists so tools/sweep_flash.py can tune the tile edge on
    chip — any size must produce the same attention, including when the
    block exceeds T (256 > 192: single padded tile) and when it divides
    T unevenly (64 into 192)."""
    t = 192
    ks = jax.random.split(jax.random.key(9), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block=block) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    out = flash_attention(q, k, v, causal=causal, block=block)
    ref = full_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_flash_attention_block_bounds_rejected():
    """The block override is bounded on both ends: non-multiple-of-8
    below, and >512 above (the block^2 f32 VMEM scratch would blow the
    ~16 MB/core budget with an opaque Mosaic error instead of this
    message — round-3 advisor finding)."""
    ks = jax.random.split(jax.random.key(9), 3)
    q, k, v = (jax.random.normal(kk, (1, 64, 2, 16), jnp.float32)
               for kk in ks)
    with pytest.raises(ValueError, match="multiple of 8"):
        flash_attention(q, k, v, block=20)
    with pytest.raises(ValueError, match="<= 512"):
        flash_attention(q, k, v, block=1024)


@pytest.mark.parametrize("t", [49, 200])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grad_unaligned_lengths(t, causal):
    """Backward kernels mask padded rows/cols exactly like the forward."""
    ks = jax.random.split(jax.random.key(6), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_flash_attention_bwd_never_materializes_scores():
    """No (T, T) intermediate anywhere in the grad program.

    With T=256 and 128-blocks, a dense-recompute backward would carry a
    (..., 256, 256) score matrix; the fused kernels only ever hold
    (128, 128) tiles. Checked on the whole grad jaxpr."""
    ks = jax.random.split(jax.random.key(7), 3)
    t = 256
    q, k, v = (jax.random.normal(kk, (1, t, 2, 16), jnp.float32) for kk in ks)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    assert f"{t},{t}" not in str(jaxpr)


def test_flash_attention_rejects_cross_attention_shapes():
    """Tq != Tk raises: the kernel's causal mask alignment assumes Tq == Tk."""
    k1, k2 = jax.random.split(jax.random.key(8))
    q = jax.random.normal(k1, (1, 32, 2, 16), jnp.float32)
    k = v = jax.random.normal(k2, (1, 64, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="Tq == Tk"):
        flash_attention(q, k, v)


@pytest.mark.parametrize("t", [49, 127, 200])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_unaligned_lengths(t, causal):
    """Odd/prime T pads to a block multiple with masked tail positions."""
    ks = jax.random.split(jax.random.key(5), 3)
    q, k, v = (jax.random.normal(kk, (1, t, 4, 16), jnp.float32) for kk in ks)
    np.testing.assert_allclose(
        flash_attention(q, k, v, causal=causal),
        full_attention(q, k, v, causal=causal),
        rtol=1e-5, atol=1e-5,
    )


def test_fused_adam_bf16_grads_keep_f32_moments():
    """bf16 gradients must not demote the f32 moment buffers."""
    g = jnp.ones((10,), jnp.bfloat16)
    m = jnp.zeros((10,), jnp.float32)
    v = jnp.zeros((10,), jnp.float32)
    hypers = jnp.asarray(
        [1e-3, 0.9, 0.999, 1e-8, 10.0, 1000.0, 0.1, 0.001, 0.0], jnp.float32
    )
    delta, m1, v1 = fused_adam_leaf(g, m, v, hypers)
    assert delta.dtype == jnp.bfloat16
    assert m1.dtype == jnp.float32 and v1.dtype == jnp.float32
