"""Unit matrix for the serving control plane (``serve/control.py``,
ISSUE 15): token buckets + per-client quotas, the priority shed policy
and the priority-ordered batcher queue, the autoscaler's
hysteresis/cooldown state machine, the weighted-fair multi-model gate,
and the rolling-window /stats plane — all driveable with stubs, no
device, no socket."""

import threading
import time

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher, Overloaded
from pytorch_distributed_mnist_tpu.serve.control import (
    DEFAULT_WATERMARKS,
    PRIORITY_CLASSES,
    AutoScaler,
    ClientQuotas,
    DrainRate,
    ShedPolicy,
    TokenBucket,
    WeightedFairGate,
    parse_quota_spec,
    parse_weight_spec,
    priority_rank,
)
from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog

pytestmark = pytest.mark.serve


# -- vocabulary --------------------------------------------------------------


def test_priority_classes_order_and_ranks():
    assert PRIORITY_CLASSES == ("interactive", "batch", "best_effort")
    assert [priority_rank(k) for k in PRIORITY_CLASSES] == [0, 1, 2]
    with pytest.raises(ValueError, match="unknown priority"):
        priority_rank("urgent")


def test_loadgen_class_vocabulary_pinned_to_control():
    """tools/loadgen.py mirrors the class vocabulary without importing
    jax-adjacent modules; drift would silently mis-tag every mixed
    drive."""
    from tools import loadgen

    assert tuple(loadgen.PRIORITY_CLASSES) == PRIORITY_CLASSES


# -- shed policy -------------------------------------------------------------


def test_shed_policy_default_watermarks_and_depths():
    policy = ShedPolicy()
    assert policy.watermarks == DEFAULT_WATERMARKS
    assert policy.admit_depth("interactive", 64) == 64
    assert policy.admit_depth("batch", 64) == 48
    assert policy.admit_depth("best_effort", 64) == 32
    # depth < limit admits; at/above sheds.
    assert policy.admits("best_effort", 31, 64)
    assert not policy.admits("best_effort", 32, 64)
    assert policy.admits("interactive", 63, 64)
    assert not policy.admits("interactive", 64, 64)


def test_shed_policy_overrides_and_validation():
    policy = ShedPolicy({"best_effort": 0.25})
    assert policy.admit_depth("best_effort", 64) == 16
    assert policy.admit_depth("batch", 64) == 48  # untouched default
    with pytest.raises(ValueError, match="unknown priority"):
        ShedPolicy({"urgent": 0.5})
    with pytest.raises(ValueError, match="in \\(0, 1\\]"):
        ShedPolicy({"batch": 0.0})
    # A watermark never sheds an empty queue, however small the queue.
    assert ShedPolicy({"best_effort": 0.01}).admit_depth(
        "best_effort", 4) == 1


def test_shed_policy_retry_after_from_drain_rate():
    policy = ShedPolicy()
    # 10 requests over the best_effort limit at 20 req/s drain = 0.55s.
    ra = policy.retry_after_s("best_effort", 41, 64, drain_rps=20.0)
    assert ra == pytest.approx((41 - 32 + 1) / 20.0, abs=1e-3)
    # Clamped: dead drain doesn't produce an hours-long hint...
    assert policy.retry_after_s("best_effort", 1000, 64, 0.0) == 30.0
    # ...and a fast drain doesn't produce a sub-100ms re-offer.
    assert policy.retry_after_s("interactive", 64, 64, 1e9) == 0.1


def test_drain_rate_window():
    drain = DrainRate(window_s=10.0)
    drain.note(5, now=100.0)
    drain.note(5, now=105.0)
    assert drain.rate(now=105.0) == pytest.approx(1.0)
    # The first note ages out of the window.
    assert drain.rate(now=112.0) == pytest.approx(0.5)
    assert drain.rate(now=200.0) == 0.0


# -- token bucket + quotas ---------------------------------------------------


def test_token_bucket_burst_then_refill():
    bucket = TokenBucket(rate=2.0, burst=4.0, now=0.0)
    codes = [bucket.admit(now=0.0)[0] for _ in range(5)]
    assert codes == [True] * 4 + [False]
    admitted, retry = bucket.admit(now=0.0)
    assert not admitted and retry == pytest.approx(0.5, abs=1e-3)
    # 1 second refills 2 tokens.
    assert bucket.admit(now=1.0)[0]
    assert bucket.admit(now=1.0)[0]
    assert not bucket.admit(now=1.0)[0]


def test_token_bucket_never_exceeds_burst():
    bucket = TokenBucket(rate=100.0, burst=2.0, now=0.0)
    assert [bucket.admit(now=1000.0)[0] for _ in range(3)] \
        == [True, True, False]


def test_parse_quota_spec_grammar():
    assert parse_quota_spec("100") == {
        "interactive": 100.0, "batch": 100.0, "best_effort": 100.0}
    assert parse_quota_spec("100,interactive=20") == {
        "interactive": 20.0, "batch": 100.0, "best_effort": 100.0}
    assert parse_quota_spec("batch=50") == {"batch": 50.0}
    with pytest.raises(ValueError, match="unknown priority"):
        parse_quota_spec("urgent=5")
    with pytest.raises(ValueError, match="more than one bare"):
        parse_quota_spec("5,10")
    with pytest.raises(ValueError, match=">= 0"):
        parse_quota_spec("interactive=-1")


def test_client_quotas_per_class_override_and_isolation():
    quotas = ClientQuotas({"interactive": 2.0}, burst_s=1.0)
    # interactive bounded at 2/s with a 1s burst (2 tokens)...
    assert quotas.admit("a", "interactive", now=0.0)[0]
    assert quotas.admit("a", "interactive", now=0.0)[0]
    refused, retry = quotas.admit("a", "interactive", now=0.0)
    assert not refused and retry > 0
    # ...while batch (no rate configured) is unlimited...
    assert all(quotas.admit("a", "batch", now=0.0)[0]
               for _ in range(100))
    # ...and OTHER clients' interactive buckets are untouched.
    assert quotas.admit("b", "interactive", now=0.0)[0]
    snap = quotas.snapshot()
    assert snap["rejected"] == 1 and snap["clients_tracked"] == 2


def test_client_quotas_anonymous_shared_bucket():
    """Requests without a client_id share ONE bucket: anonymity is not
    a quota bypass."""
    quotas = ClientQuotas({"interactive": 1.0}, burst_s=1.0)
    assert quotas.admit(None, "interactive", now=0.0)[0]
    assert not quotas.admit(None, "interactive", now=0.0)[0]


def test_client_quotas_lru_bound():
    """An adversary minting client_ids cannot grow server memory: the
    bucket map is an LRU capped at max_clients."""
    quotas = ClientQuotas({"interactive": 1.0}, max_clients=8)
    for i in range(100):
        quotas.admit(f"client-{i}", "interactive", now=0.0)
    assert len(quotas._buckets) <= 8


# -- priority batcher --------------------------------------------------------


def _stalled_batcher(max_queue=8, max_batch=1, policy=True,
                     serve_log=None):
    """A batcher whose engine blocks until ``release`` is set; returns
    (batcher, release_event, executed_klasses)."""
    release = threading.Event()
    executed = []

    def infer(images):
        release.wait(10.0)
        executed.append(int(images.shape[0]))
        return np.zeros((images.shape[0], 2))

    batcher = MicroBatcher(
        infer, max_batch=max_batch, max_wait_s=0.01,
        max_queue=max_queue, serve_log=serve_log,
        shed_policy=ShedPolicy() if policy else None).start()
    return batcher, release, executed


def _wait_depth(batcher, depth, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if batcher.queue_depth() == depth:
            return
        time.sleep(0.005)
    raise AssertionError(
        f"queue depth never reached {depth} (at {batcher.queue_depth()})")


def test_priority_queue_orders_interactive_ahead_of_batch():
    """With the engine stalled, queued best_effort/batch requests are
    overtaken by a later interactive arrival — completion order follows
    class rank, FIFO within a class."""
    order = []
    release = threading.Event()

    def infer(images):
        release.wait(10.0)
        return images[:, :2]  # echo the (tag, tag) rows

    batcher = MicroBatcher(infer, max_batch=1, max_wait_s=0.0,
                           max_queue=16,
                           shed_policy=ShedPolicy()).start()
    try:
        # One request occupies the engine (taken off the queue first).
        blocker = batcher.submit(np.full((1, 4), -1.0))
        _wait_depth(batcher, 0)
        pendings = []
        for i, klass in enumerate(["best_effort", "batch",
                                   "best_effort", "interactive",
                                   "batch", "interactive"]):
            pendings.append(
                (klass, i, batcher.submit(np.full((1, 4), float(i)),
                                          klass=klass)))
        release.set()
        MicroBatcher.result(blocker, 10.0)
        results = [(klass, i, MicroBatcher.result(p, 10.0))
                   for klass, i, p in pendings]
        for klass, i, out in results:
            order.append((float(out[0, 0]), klass))
        by_completion = sorted(
            results, key=lambda r: r[2].tolist())  # placeholder
    finally:
        batcher.close()
    # Reconstruct execution order from the batcher's own take order:
    # interactive (3, 5) first, then batch (1, 4), then best_effort
    # (0, 2) — FIFO within each class.
    taken_order = [int(v) for v, _ in
                   sorted(((float(out[0, 0]), klass)
                           for klass, i, out in results))]
    assert taken_order == [0, 1, 2, 3, 4, 5]  # identity: echo check
    del by_completion, order


def test_priority_queue_take_order_is_rank_then_fifo():
    """Drive the take order directly: stall the engine, queue a mixed
    set, release, and assert the engine saw interactive first, batch
    next, best_effort last (FIFO within class)."""
    seen = []
    release = threading.Event()

    def infer(images):
        release.wait(10.0)
        seen.append(float(images[0, 0]))
        return np.zeros((images.shape[0], 2))

    batcher = MicroBatcher(infer, max_batch=1, max_wait_s=0.0,
                           max_queue=16,
                           shed_policy=ShedPolicy()).start()
    try:
        blocker = batcher.submit(np.full((1, 4), -1.0))
        _wait_depth(batcher, 0)
        submits = [("best_effort", 0.0), ("batch", 1.0),
                   ("best_effort", 2.0), ("interactive", 3.0),
                   ("batch", 4.0), ("interactive", 5.0)]
        pendings = [batcher.submit(np.full((1, 4), v), klass=k)
                    for k, v in submits]
        release.set()
        MicroBatcher.result(blocker, 10.0)
        for p in pendings:
            MicroBatcher.result(p, 10.0)
    finally:
        batcher.close()
    assert seen == [-1.0, 3.0, 5.0, 1.0, 4.0, 0.0, 2.0]


def test_watermarks_shed_best_effort_first():
    """The admission state machine over a stalled engine: with
    max_queue=8, best_effort sheds at depth 4, batch at 6, interactive
    only at the full 8."""
    serve_log = ServeLog()
    batcher, release, _ = _stalled_batcher(max_queue=8,
                                           serve_log=serve_log)
    try:
        blocker = batcher.submit(np.zeros((1, 4)))
        _wait_depth(batcher, 0)
        for _ in range(4):
            batcher.submit(np.zeros((1, 4)), klass="best_effort")
        # depth 4 == best_effort limit: shed, with a Retry-After.
        with pytest.raises(Overloaded) as exc_info:
            batcher.submit(np.zeros((1, 4)), klass="best_effort")
        assert exc_info.value.retry_after_s is not None
        assert exc_info.value.retry_after_s > 0
        # batch still admitted to depth 6...
        batcher.submit(np.zeros((1, 4)), klass="batch")
        batcher.submit(np.zeros((1, 4)), klass="batch")
        with pytest.raises(Overloaded):
            batcher.submit(np.zeros((1, 4)), klass="batch")
        # ...interactive to the full queue...
        batcher.submit(np.zeros((1, 4)), klass="interactive")
        batcher.submit(np.zeros((1, 4)), klass="interactive")
        with pytest.raises(Overloaded, match="interactive"):
            batcher.submit(np.zeros((1, 4)), klass="interactive")
        snap = serve_log.snapshot()
        assert snap["classes"]["best_effort"]["shed"] == 1
        assert snap["classes"]["batch"]["shed"] == 1
        assert snap["classes"]["interactive"]["shed"] == 1
        # Queue sheds are 503-class rejections in the lifetime counter.
        assert snap["rejected"] == 3
        release.set()
        MicroBatcher.result(blocker, 10.0)
    finally:
        release.set()
        batcher.close()


def test_no_policy_keeps_classic_admission_and_message():
    """Without a shed policy the batcher is the classic single-class
    queue: full-queue 503 with the historical message, no retry hint,
    FIFO order."""
    batcher, release, _ = _stalled_batcher(max_queue=2, policy=False)
    try:
        blocker = batcher.submit(np.zeros((1, 4)))
        _wait_depth(batcher, 0)
        batcher.submit(np.zeros((1, 4)))
        batcher.submit(np.zeros((1, 4)))
        with pytest.raises(Overloaded, match="request queue full"):
            batcher.submit(np.zeros((1, 4)))
        try:
            batcher.submit(np.zeros((1, 4)))
        except Overloaded as exc:
            assert exc.retry_after_s is None
        release.set()
        MicroBatcher.result(blocker, 10.0)
    finally:
        release.set()
        batcher.close()


def test_deadline_anchors_to_oldest_not_most_urgent():
    """A queued batch request's flush clock must not reset when
    interactive arrivals keep overtaking it: the coalescing deadline
    anchors to the OLDEST waiting request."""
    walls = []

    def infer(images):
        walls.append(time.perf_counter())
        return np.zeros((images.shape[0], 2))

    batcher = MicroBatcher(infer, max_batch=64, max_wait_s=0.08,
                           max_queue=64,
                           shed_policy=ShedPolicy()).start()
    try:
        t0 = time.perf_counter()
        first = batcher.submit(np.zeros((1, 4)), klass="batch")
        # A trickle of interactive arrivals, each younger than the
        # batch request; the flush must still happen ~max_wait after
        # the FIRST submit, not after the last.
        for _ in range(5):
            time.sleep(0.02)
            batcher.submit(np.zeros((1, 4)), klass="interactive")
        MicroBatcher.result(first, 10.0)
        assert walls[0] - t0 < 0.5  # flushed on the oldest's clock
    finally:
        batcher.close()


# -- autoscaler --------------------------------------------------------------


class _FakePool:
    def __init__(self, n_devices=1, fail=False):
        self.n_devices = n_devices
        self.fail = fail
        self.calls = []

    def resize(self, n_devices=None, mesh_size=None):
        self.calls.append(n_devices)
        if self.fail:
            raise RuntimeError("a resize is already in progress")
        self.n_devices = n_devices
        return {"old": {}, "new": {}}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _EventSink:
    def __init__(self):
        self.events = []

    def record_pool_event(self, kind, **fields):
        self.events.append((kind, fields))


def _scaler(pool, stats, **kw):
    clock = kw.pop("clock", _Clock())
    defaults = dict(slo_p95_ms=100.0, queue_high=48, min_devices=1,
                    max_devices=4, interval_s=60.0, cooldown_s=10.0,
                    down_after=3)
    defaults.update(kw)
    return AutoScaler(pool, lambda: dict(stats), now_fn=clock,
                      **defaults), clock, stats


def test_autoscaler_scales_up_on_p95_breach_and_respects_cooldown():
    pool = _FakePool(1)
    scaler, clock, stats = _scaler(pool, {"p95_ms": 500.0,
                                          "queue_depth": 0})
    decision = scaler.tick()
    assert decision["action"] == "scale_up"
    assert pool.n_devices == 2 and pool.calls == [2]
    # Still breaching, but inside the cooldown: hold.
    clock.t = 5.0
    assert scaler.tick() is None
    # Past the cooldown: the next step fires.
    clock.t = 11.0
    assert scaler.tick()["action"] == "scale_up"
    assert pool.n_devices == 3


def test_autoscaler_scales_up_on_queue_depth_alone():
    pool = _FakePool(1)
    scaler, _, _ = _scaler(pool, {"p95_ms": 1.0, "queue_depth": 48})
    decision = scaler.tick()
    assert decision["action"] == "scale_up"
    assert "watermark" in decision["reason"]


def test_autoscaler_max_devices_caps_scale_up():
    pool = _FakePool(4)
    scaler, _, _ = _scaler(pool, {"p95_ms": 500.0, "queue_depth": 60})
    assert scaler.tick() is None
    assert pool.calls == []


def test_autoscaler_hysteresis_band_never_acts():
    """p95 between the down bar (slo/2) and the SLO is the hysteresis
    band: no action either way, the calm streak resets."""
    pool = _FakePool(2)
    scaler, clock, stats = _scaler(pool, {"p95_ms": 75.0,
                                          "queue_depth": 0})
    for t in (0.0, 100.0, 200.0, 300.0):
        clock.t = t
        assert scaler.tick() is None
    assert pool.calls == []
    # Two calm samples, then one band sample: the streak resets and
    # two MORE calm samples still don't scale down (needs 3 in a row).
    stats["p95_ms"] = 1.0
    clock.t = 400.0
    assert scaler.tick() is None
    clock.t = 500.0
    assert scaler.tick() is None
    stats["p95_ms"] = 75.0
    clock.t = 600.0
    assert scaler.tick() is None
    stats["p95_ms"] = 1.0
    clock.t = 700.0
    assert scaler.tick() is None
    clock.t = 800.0
    assert scaler.tick() is None
    assert pool.calls == []


def test_autoscaler_scales_down_after_sustained_calm_to_floor():
    pool = _FakePool(3)
    scaler, clock, _ = _scaler(pool, {"p95_ms": 1.0, "queue_depth": 0},
                               min_devices=2)
    clock.t = 0.0
    assert scaler.tick() is None
    clock.t = 100.0
    assert scaler.tick() is None
    clock.t = 200.0
    decision = scaler.tick()
    assert decision["action"] == "scale_down"
    assert pool.n_devices == 2
    # At the floor: sustained calm never goes below min_devices.
    for t in (300.0, 400.0, 500.0, 600.0):
        clock.t = t
        scaler.tick()
    assert pool.n_devices == 2


def test_autoscaler_dry_run_records_without_actuating():
    pool = _FakePool(1)
    sink = _EventSink()
    scaler, _, _ = _scaler(pool, {"p95_ms": 500.0, "queue_depth": 0},
                           dry_run=True, serve_log=sink)
    decision = scaler.tick()
    assert decision["action"] == "scale_up" and decision["dry_run"]
    assert pool.calls == []  # never actuated
    assert pool.n_devices == 1
    snap = scaler.snapshot()
    assert snap["dry_run"] and snap["scale_ups"] == 1
    assert snap["last_decision"]["action"] == "scale_up"
    assert [k for k, _ in sink.events] == ["serve_autoscale"]
    assert sink.events[0][1]["dry_run"] is True


def test_autoscaler_resize_failure_is_contained_and_recorded():
    pool = _FakePool(1, fail=True)
    sink = _EventSink()
    scaler, _, _ = _scaler(pool, {"p95_ms": 500.0, "queue_depth": 0},
                           serve_log=sink)
    decision = scaler.tick()  # must not raise
    assert "error" in decision and "resize" in decision["error"]
    snap = scaler.snapshot()
    assert snap["errors"] == 1 and snap["scale_ups"] == 0
    assert "error" in sink.events[0][1]


def test_autoscaler_constructor_validation():
    pool = _FakePool(1)
    with pytest.raises(ValueError, match="slo_p95_ms"):
        AutoScaler(pool, dict, slo_p95_ms=0, queue_high=10)
    with pytest.raises(ValueError, match="queue_high"):
        AutoScaler(pool, dict, slo_p95_ms=10, queue_high=0)
    with pytest.raises(ValueError, match="max_devices"):
        AutoScaler(pool, dict, slo_p95_ms=10, queue_high=10,
                   min_devices=4, max_devices=2)
    with pytest.raises(ValueError, match="down_frac"):
        AutoScaler(pool, dict, slo_p95_ms=10, queue_high=10,
                   down_frac=1.5)


# -- weighted-fair gate ------------------------------------------------------


def test_fair_gate_virtual_time_encodes_the_weight_ratio():
    """The accounting that decides every contention: a grant charges
    rows/weight, so after one grant each from equal clocks the
    3-weighted model's virtual time sits at a third of the 1-weighted
    model's — it wins the next contention — and exactly three a-grants
    equal one b-grant (the 3:1 ratio, as arithmetic)."""
    gate = WeightedFairGate({"a": 3.0, "b": 1.0})
    gate.grant("a", rows=1)
    gate.grant("b", rows=1)
    assert gate._vtime["a"] == pytest.approx(1 / 3)
    assert gate._vtime["b"] == pytest.approx(1.0)
    # Two more a-grants: 3 x (1/3) == 1 x 1 — the clocks meet.
    gate.grant("a", rows=1)
    gate.grant("a", rows=1)
    assert gate._vtime["a"] == pytest.approx(gate._vtime["b"])
    # Rows charge too: an 8-row batch costs 8x a 1-row one.
    gate.grant("b", rows=8)
    assert gate._vtime["b"] == pytest.approx(9.0)


def test_fair_gate_blocks_behind_lower_vtime_waiter_and_wakes():
    """The blocking half of the policy: a model whose virtual time is
    ABOVE another waiting model's parks on the gate's cv, and proceeds
    the moment the lower-vtime waiter is gone."""
    gate = WeightedFairGate({"a": 1.0, "b": 1.0})
    with gate._cv:
        gate._waiting["a"] = 1  # a parked at vtime 0
        gate._vtime["b"] = 0.5
    done = threading.Event()

    def b_dispatch():
        gate.grant("b", rows=1)
        done.set()

    t = threading.Thread(target=b_dispatch, daemon=True)
    t.start()
    # b must be blocked: a is waiting with the lower virtual time.
    assert not done.wait(0.2)
    with gate._cv:
        del gate._waiting["a"]
        gate._cv.notify_all()
    assert done.wait(5.0)
    t.join(5.0)
    assert gate.snapshot()["grants"]["b"] == 1


def test_fair_gate_idle_model_never_blocks_the_busy_one():
    gate = WeightedFairGate({"a": 1.0, "b": 1.0})
    for _ in range(50):
        gate.grant("a", rows=8)  # b never shows up; a never waits
    snap = gate.snapshot()
    assert snap["grants"]["a"] == 50 and snap["grants"]["b"] == 0


def test_fair_gate_reentry_floor_prevents_catchup_burst():
    """A model returning from idle is floored to the grant clock: its
    stale virtual time must not buy a monopoly repaying the idle
    period."""
    gate = WeightedFairGate({"a": 1.0, "b": 1.0})
    for _ in range(100):
        gate.grant("a", rows=1)
    # b re-enters with vtime 0; the floor lifts it to a's clock, so
    # alternation resumes immediately instead of 100 consecutive
    # b-grants.
    gate.grant("b", rows=1)
    assert gate._vtime["b"] >= 100.0


def test_fair_gate_unknown_model_and_weight_parsing():
    gate = WeightedFairGate({"a": 1.0})
    with pytest.raises(ValueError, match="unknown model"):
        gate.grant("zzz")
    with pytest.raises(ValueError, match="at least one"):
        WeightedFairGate({})
    with pytest.raises(ValueError, match="> 0"):
        WeightedFairGate({"a": 0.0})
    assert parse_weight_spec("a=2", ["a", "b"]) == {"a": 2.0, "b": 1.0}
    assert parse_weight_spec("", ["a"]) == {"a": 1.0}
    with pytest.raises(ValueError, match="not in the"):
        parse_weight_spec("zzz=2", ["a"])
    with pytest.raises(ValueError, match="MODEL=WEIGHT"):
        parse_weight_spec("just-a-name", ["a"])


# -- rolling-window ServeLog -------------------------------------------------


def test_serve_log_window_ages_out_old_samples():
    log = ServeLog(window_s=60.0)
    clock = _Clock()
    log._now = clock
    log.reset()
    clock.t = 10.0
    for _ in range(10):
        log.record_request(latency_s=0.005)
    clock.t = 30.0
    for _ in range(5):
        log.record_request(latency_s=0.5)
    win = log.window_stats()
    assert win["count"] == 15
    # 80 seconds on: the fast early samples aged out; only the slow
    # ones remain, and the window quantiles see CURRENT load.
    clock.t = 80.0
    win = log.window_stats()
    assert win["count"] == 5
    assert win["p95_ms"] == pytest.approx(500.0, abs=1.0)
    assert win["rps"] == pytest.approx(5 / 60.0, abs=0.01)
    # Lifetime quantiles still carry everything.
    snap = log.snapshot()
    assert snap["latency_ms"]["count"] == 15
    assert snap["window"]["count"] == 5


def test_serve_log_window_rps_uses_elapsed_before_full_window():
    log = ServeLog(window_s=60.0)
    clock = _Clock()
    log._now = clock
    log.reset()
    clock.t = 10.0
    for _ in range(50):
        log.record_request(latency_s=0.001)
    win = log.window_stats()
    # 50 requests over 10 elapsed seconds (not diluted over the full
    # 60s window the log hasn't lived yet).
    assert win["rps"] == pytest.approx(5.0, abs=0.2)


def test_serve_log_per_class_counters_and_quota_separation():
    log = ServeLog()
    log.record_request(latency_s=0.01, klass="interactive")
    log.record_request(latency_s=0.02, klass="batch")
    log.record_rejection(klass="best_effort")          # shed (503)
    log.record_rejection(klass="interactive", quota=True)  # 429
    snap = log.snapshot()
    classes = snap["classes"]
    assert classes["interactive"]["requests"] == 1
    assert classes["interactive"]["quota_rejected"] == 1
    assert classes["best_effort"]["shed"] == 1
    assert classes["batch"]["latency_ms"]["p50"] == pytest.approx(
        20.0, abs=0.5)
    # Quota refusals are the CLIENT's overload: the lifetime rejected
    # counter (admission control) counts only the shed.
    assert snap["rejected"] == 1


def test_serve_log_classless_schema_has_no_classes_block():
    log = ServeLog()
    log.record_request(latency_s=0.01)
    snap = log.snapshot()
    assert "classes" not in snap
    assert "window" in snap  # the rolling block is always present


# -- loadgen shapes/mix (pure helpers) ---------------------------------------


def test_loadgen_parse_mix_and_pick():
    from tools import loadgen

    mix = loadgen.parse_mix("interactive=0.8,batch=0.2")
    assert [k for k, _ in mix] == ["interactive", "batch"]
    assert mix[-1][1] == pytest.approx(1.0)
    import random

    rng = random.Random(0)
    picks = [loadgen.pick_class(mix, rng) for _ in range(1000)]
    frac = picks.count("interactive") / len(picks)
    assert 0.75 < frac < 0.85
    assert loadgen.pick_class(None, rng) == "interactive"


def test_loadgen_shapes_modulate_rate():
    from tools import loadgen

    # sine: peak ~1.8x at t=T/4, trough ~0.2x at t=3T/4.
    assert loadgen.rate_at("sine", 100.0, 2.5, 10.0, 5.0, 0) \
        == pytest.approx(180.0, abs=1.0)
    assert loadgen.rate_at("sine", 100.0, 7.5, 10.0, 5.0, 0) \
        == pytest.approx(20.0, abs=1.0)
    # spike: mult through the middle fifth, baseline outside it.
    assert loadgen.rate_at("spike", 100.0, 5.0, 10.0, 5.0, 0) == 500.0
    assert loadgen.rate_at("spike", 100.0, 1.0, 10.0, 5.0, 0) == 100.0
    # adversarial: deterministic per (seed, second), values in
    # {0.1x, 3x}.
    vals = {loadgen.rate_at("adversarial", 100.0, float(t), 30.0, 5.0,
                            7) for t in range(30)}
    assert vals <= {10.0, 300.0} and len(vals) == 2
    assert loadgen.rate_at("adversarial", 100.0, 3.3, 30.0, 5.0, 7) \
        == loadgen.rate_at("adversarial", 100.0, 3.9, 30.0, 5.0, 7)


def test_loadgen_schedule_counts_follow_shape():
    from tools import loadgen

    flat = loadgen.schedule("constant", 100.0, 10.0, 0)
    spiky = loadgen.schedule("spike", 100.0, 10.0, 0, spike_mult=5.0)
    assert len(flat) == pytest.approx(1000, rel=0.02)
    # The spike adds ~2s x 400 extra requests over the flat schedule.
    assert len(spiky) == pytest.approx(1800, rel=0.05)
    assert all(b > a for a, b in zip(spiky, spiky[1:]))


# -- review regressions ------------------------------------------------------


def test_autoscaler_steps_by_mesh_group_quantum():
    """A sharded pool resizes by whole mesh groups (resize validates
    serve_mesh | serve_devices): with step=mesh_size the controller
    targets valid topologies only — 2 -> 4 up, 4 -> 2 down, never an
    odd chip count a 2-chip mesh can't host."""
    pool = _FakePool(2)
    scaler, clock, stats = _scaler(pool, {"p95_ms": 500.0,
                                          "queue_depth": 0},
                                   step=2, min_devices=2, max_devices=4)
    assert scaler.tick()["to_devices"] == 4
    assert pool.n_devices == 4
    # At max: hold, not an invalid 6.
    clock.t = 100.0
    assert scaler.tick() is None
    stats["p95_ms"] = 1.0
    for t in (200.0, 300.0, 400.0):
        clock.t = t
        decision = scaler.tick()
    assert decision["to_devices"] == 2 and pool.n_devices == 2
    assert pool.calls == [4, 2]


def test_autoscale_sharded_bounds_must_be_mesh_multiples(tmp_path):
    """Non-mesh-multiple --autoscale-max-devices on a sharded mode is a
    boot-time flag error, not a controller spinning on resize 400s."""
    from pytorch_distributed_mnist_tpu.serve.server import (
        build_parser,
        create_server,
    )

    d = tmp_path / "ckpt"
    d.mkdir()
    args = build_parser().parse_args([
        "--checkpoint-dir", str(d), "--model", "vit", "--dtype", "f32",
        "--serve-mode", "tensor", "--serve-devices", "2",
        "--serve-mesh", "2", "--autoscale",
        "--autoscale-max-devices", "3"])
    with pytest.raises(SystemExit, match="whole 2-chip mesh groups"):
        create_server(args)


def test_classless_submits_keep_classless_schema_through_policy():
    """A policy-attached batcher whose clients never send a priority
    (klass=None end to end) must not grow a `classes` block: None is
    TREATED as the most urgent class for ordering/admission but never
    recorded as one."""
    serve_log = ServeLog()
    batcher = MicroBatcher(
        lambda images: np.zeros((images.shape[0], 2)), max_batch=4,
        max_wait_s=0.0, max_queue=8, serve_log=serve_log,
        shed_policy=ShedPolicy()).start()
    try:
        batcher.predict(np.zeros((1, 4)), timeout=10.0)
    finally:
        batcher.close()
    snap = serve_log.snapshot()
    assert snap["requests"] == 1
    assert "classes" not in snap


def test_chaos_and_loadgen_help_render():
    """argparse expands '%' conversions in help strings: a bare '%'
    crashes --help with a TypeError (caught in review). Pin that both
    tools render usage cleanly."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for tool in ("chaos.py", "loadgen.py"):
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", tool),
             "--help"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "usage" in proc.stdout.lower()
