"""End-to-end smoke of ``bench.py --mode publish`` on the CPU backend:
the acceptance line for delta distribution. The report must carry the
``publish`` block (whole-file baseline vs chunked publish costs) and
the ``fleet`` block (3-fetcher convergence with loopback gossip), with
the headline ratio asserted under the ISSUE's 30% bar — so the delta
BENCH schema can't silently rot while CI exercises only the in-process
pieces. The inject-fail twin pins that a broken assertion exits 1 with
the failure named in the JSON line, never a silent green."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.slow, pytest.mark.distrib]


def _run(extra_env):
    env = os.environ.copy()
    env.update({
        "JAX_PLATFORMS": "cpu",
        # Small chunk budget: the linear model must span several chunks
        # or the adjacency measurement degenerates to one-chunk leaves.
        "BENCH_PUBLISH_CHUNK_MB": "0.25",
        "BENCH_PUBLISH_BACKENDS": "3",
        "BENCH_COMPILE_CACHE": "",
        "TPUMNIST_COMPILE_CACHE": "",
    })
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--mode", "publish"],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO,
    )


def test_bench_publish_reports_delta_and_fleet_blocks():
    proc = _run({})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])

    assert report["metric"] == \
        "mnist_delta_publish_adjacent_fleet_bytes_fraction"
    assert report.get("error") is None
    # The headline: adjacent-epoch fleet bytes as a fraction of shipping
    # the whole file to every backend — the ISSUE's <30% acceptance bar.
    assert 0 < report["value"] < 0.30
    assert report["vs_baseline"] > 1

    pub = report["publish"]
    assert pub["chunk_mb"] == 0.25
    assert pub["whole_file_bytes"] > 0
    assert 0 < pub["cold_chunk_bytes"]
    # An adjacent epoch re-publishes only the dirtied leaf's chunks.
    assert 0 < pub["adjacent_new_chunk_bytes"] < pub["cold_chunk_bytes"]
    assert pub["adjacent_publish_bytes_fraction"] < 0.30
    for key in ("whole_file_publish_s", "cold_publish_s",
                "adjacent_publish_s"):
        assert pub[key] >= 0

    fleet = report["fleet"]
    assert fleet["backends"] == 3
    assert fleet["cold_fetch_bytes"] > 0
    assert 0 < fleet["adjacent_fetch_bytes"] < fleet["cold_fetch_bytes"]
    assert fleet["adjacent_fleet_bytes_fraction"] == report["value"]
    assert fleet["delta_under_30pct_of_whole_file"] is True
    # The gossip ordering proof: non-seed fetchers pulled every missing
    # chunk from the peer endpoint, and the source dir saw ZERO reads
    # from them — peers-before-source, measured not asserted-by-code.
    assert fleet["gossip_peer_bytes"] > 0
    assert fleet["non_seed_source_bytes"] == 0
    assert fleet["dirty_leaves"] > 0 and fleet["clean_leaves"] > 0

    # BENCH_r05 CPU labeling: the caveat says what this line measured.
    assert "caveat" in report and report["measured_at"]


def test_bench_publish_inject_fail_exits_loudly():
    proc = _run({"BENCH_PUBLISH_INJECT_FAIL": "1"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["error"] and "BENCH_PUBLISH_INJECT_FAIL" in report["error"]
