"""Elastic GROW unit tests (fast, tier-1): join-record IO, the pure
grow planner, the worker-side epoch-boundary grow rendezvous (yield
records + the agreed EXIT_GROW exit), the world_grown observability
event, and the reshard event's direction label. The real 2->1->2
shrink-then-grow twin lives in tests/test_elastic_chaos.py."""

import json
import os

import pytest

from pytorch_distributed_mnist_tpu.runtime import elastic, supervision
from pytorch_distributed_mnist_tpu.runtime.elastic import (
    DIR_ENV,
    EXIT_GROW,
    GEN_ENV,
    GROW_ENV,
    MAX_WORLD_ENV,
    MEMBERS_ENV,
    PREV_ENV,
    announce_join,
    join_path,
    maybe_grow_rendezvous,
    pending_joins,
    plan_grow,
    strip_elastic_flags,
    write_yield_record,
)
from pytorch_distributed_mnist_tpu.utils.profiling import failure_events

pytestmark = pytest.mark.elastic


def _elastic_env(monkeypatch, tmp_path, gen=0, members="0,1", grow=True):
    monkeypatch.setenv(DIR_ENV, str(tmp_path))
    monkeypatch.setenv(GEN_ENV, str(gen))
    monkeypatch.setenv(MEMBERS_ENV, members)
    monkeypatch.delenv(PREV_ENV, raising=False)
    if grow:
        monkeypatch.setenv(GROW_ENV, "1")
    else:
        monkeypatch.delenv(GROW_ENV, raising=False)


# -- join-record IO ----------------------------------------------------------


def test_announce_and_list_joins(tmp_path):
    path = announce_join(str(tmp_path), 7)
    assert path == join_path(str(tmp_path), 7)
    announce_join(str(tmp_path), 2)
    assert pending_joins(str(tmp_path)) == [(2, join_path(str(tmp_path), 2)),
                                            (7, path)]
    # No torn reads: the write is tmp+replace, nothing else in the dir.
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


def test_pending_joins_skips_malformed_records(tmp_path, capsys):
    announce_join(str(tmp_path), 3)
    (tmp_path / "join_h00009.json").write_text("{not json")
    (tmp_path / "join_h00011.json").write_text('{"wrong": "shape"}')
    assert [h for h, _ in pending_joins(str(tmp_path))] == [3]
    err = capsys.readouterr().err
    assert "malformed join record" in err


def test_pending_joins_missing_dir_is_empty(tmp_path):
    assert pending_joins(str(tmp_path / "nope")) == []


# -- the pure grow planner ---------------------------------------------------


def test_plan_grow_appends_joiners_after_survivors():
    new, admitted, stale = plan_grow([0, 2], [5, 1])
    # Survivor ranks stay a prefix (rank 0 keeps streaming logs);
    # joiners append in host-id order.
    assert new == [0, 2, 1, 5]
    assert admitted == [1, 5] and stale == []


def test_plan_grow_ignores_stale_member_records():
    new, admitted, stale = plan_grow([0, 1], [1, 3])
    assert new == [0, 1, 3]
    assert admitted == [3] and stale == [1]


def test_plan_grow_caps_at_max_world_and_defers_the_rest():
    new, admitted, stale = plan_grow([0], [1, 2, 3], max_world=2)
    assert new == [0, 1]
    assert admitted == [1]
    # 2 and 3 are neither admitted nor stale: they stay pending.
    assert stale == []


def test_plan_grow_unbounded_by_default_and_dedups():
    new, admitted, _ = plan_grow([0], [4, 4, 3])
    assert new == [0, 3, 4] and admitted == [3, 4]


# -- worker side: the grow rendezvous ----------------------------------------


def test_grow_rendezvous_noop_outside_elastic_grow(monkeypatch, tmp_path):
    # Not an elastic worker at all.
    for env in (DIR_ENV, GEN_ENV, MEMBERS_ENV, GROW_ENV):
        monkeypatch.delenv(env, raising=False)
    assert maybe_grow_rendezvous() is None
    # Elastic worker but no --elastic-grow: the epoch boundary is not a
    # rendezvous point (joiners still ride failure rebuilds).
    _elastic_env(monkeypatch, tmp_path, grow=False)
    announce_join(str(tmp_path), 5)
    assert maybe_grow_rendezvous() is None
    # The join record is untouched for the supervisor to admit later.
    assert [h for h, _ in pending_joins(str(tmp_path))] == [5]


def test_grow_rendezvous_noop_without_pending_joiners(
        monkeypatch, tmp_path):
    _elastic_env(monkeypatch, tmp_path)
    assert maybe_grow_rendezvous() is None  # no records: nothing to do
    assert os.listdir(tmp_path) == []


def test_grow_rendezvous_then_yield_writes_record_and_exit_code(
        monkeypatch, tmp_path):
    """The worker half of the grow protocol, in its two halves: the
    rendezvous AGREES the pending joiner list (returned, not raised —
    the cli epoch loop must first exit its saver scope cleanly so an
    async saver's deferred publish lands), then yield_for_grow writes
    the YIELD record (a survivor vote with yield: true) and raises the
    agreed EXIT_GROW SystemExit."""
    _elastic_env(monkeypatch, tmp_path, gen=1, members="0")
    announce_join(str(tmp_path), 1)
    joiners = maybe_grow_rendezvous()
    assert joiners == [1]
    assert not os.path.exists(elastic.record_path(str(tmp_path), 1, 0))
    with pytest.raises(SystemExit) as exc_info:
        elastic.yield_for_grow(joiners)
    assert exc_info.value.code == EXIT_GROW
    # Agreed symmetric exit: marked so the unwind never poisons peers.
    assert getattr(exc_info.value, "_poison_delivered", False)
    with open(elastic.record_path(str(tmp_path), 1, 0)) as f:
        rec = json.load(f)
    assert rec["yield"] is True
    assert rec["join_hosts"] == [1]
    assert rec["dead_ranks"] == [] and rec["dead_hosts"] == []
    assert rec["phase"] == "grow_check"
    # The join record itself is NOT consumed by the worker — admission
    # (and stale filtering) is the supervisor's job.
    assert [h for h, _ in pending_joins(str(tmp_path))] == [1]


def test_grow_rendezvous_ignores_stale_member_records(
        monkeypatch, tmp_path):
    """A join record for a host that is already a member must not make
    the world yield (nothing to admit)."""
    _elastic_env(monkeypatch, tmp_path, members="0,1")
    announce_join(str(tmp_path), 1)
    assert maybe_grow_rendezvous() is None  # host 1 is already a member


def test_grow_rendezvous_skipped_at_max_world_cap(monkeypatch, tmp_path):
    """A world already AT --max-world must not yield for a joiner the
    supervisor could only defer: the still-pending record would
    otherwise re-trigger a full teardown/re-exec at EVERY epoch
    boundary. The cap is mirrored to workers and the rendezvous is
    skipped outright; below the cap it runs (and a yield then always
    admits at least one joiner)."""
    _elastic_env(monkeypatch, tmp_path, members="0,1")
    announce_join(str(tmp_path), 5)
    monkeypatch.setenv(MAX_WORLD_ENV, "2")
    assert maybe_grow_rendezvous() is None  # at cap: nothing admissible
    # The record stays pending (a later failure rebuild may use it as a
    # replacement).
    assert [h for h, _ in pending_joins(str(tmp_path))] == [5]
    # One slot below the cap: the rendezvous agrees the joiner.
    monkeypatch.setenv(MAX_WORLD_ENV, "3")
    assert maybe_grow_rendezvous() == [5]


def test_yield_record_write_failure_is_swallowed(monkeypatch, tmp_path,
                                                 capsys):
    target = tmp_path / "not_a_dir"
    target.write_text("a file where the rendezvous dir should be")
    monkeypatch.setenv(DIR_ENV, str(target))
    monkeypatch.setenv(GEN_ENV, "0")
    monkeypatch.setenv(MEMBERS_ENV, "0")
    assert write_yield_record([3]) is None
    assert "could not be written" in capsys.readouterr().err


# -- the world_grown event ---------------------------------------------------


def test_note_rebuilt_world_records_grow_direction(monkeypatch, tmp_path):
    _elastic_env(monkeypatch, tmp_path, gen=2, members="0,1,3")
    monkeypatch.setenv(PREV_ENV, "0,1")
    failure_events.reset()
    elastic.note_rebuilt_world()
    events = failure_events.snapshot()
    grown = [e for e in events if e["kind"] == "world_grown"]
    assert len(grown) == 1
    assert grown[0]["old_members"] == [0, 1]
    assert grown[0]["new_members"] == [0, 1, 3]
    assert grown[0]["generation"] == 2
    assert [e for e in events if e["kind"] == "world_shrunk"] == []


def test_note_rebuilt_world_same_size_replacement_is_grown(
        monkeypatch, tmp_path):
    """A loss whose replacement rode the same rebuild: same world size,
    different members — a new host joined, recorded as world_grown
    (the member lists carry the loss)."""
    _elastic_env(monkeypatch, tmp_path, gen=1, members="0,7")
    monkeypatch.setenv(PREV_ENV, "0,1")
    failure_events.reset()
    elastic.note_rebuilt_world()
    grown = [e for e in failure_events.snapshot()
             if e["kind"] == "world_grown"]
    assert len(grown) == 1 and grown[0]["new_members"] == [0, 7]


def test_note_rebuilt_world_unchanged_membership_records_nothing(
        monkeypatch, tmp_path):
    """A same-membership relaunch (a spurious yield) is not a topology
    change; the metrics stream stays quiet."""
    _elastic_env(monkeypatch, tmp_path, gen=1, members="0,1")
    monkeypatch.setenv(PREV_ENV, "0,1")
    failure_events.reset()
    elastic.note_rebuilt_world()
    assert [e for e in failure_events.snapshot()
            if e["kind"] in ("world_grown", "world_shrunk")] == []


# -- the reshard event's direction label -------------------------------------


def test_cross_world_resume_labels_direction(monkeypatch):
    from pytorch_distributed_mnist_tpu import cli
    from pytorch_distributed_mnist_tpu.train import checkpoint

    for saved_procs, direction in ((1, "grow"), (4, "shrink")):
        failure_events.reset()
        monkeypatch.setattr(
            checkpoint, "checkpoint_world",
            lambda path, _n=saved_procs: {"processes": _n, "devices": _n})
        cli._note_cross_world_resume("ckpt_x.npz")
        (event,) = [e for e in failure_events.snapshot()
                    if e["kind"] == "checkpoint_reshard"]
        assert event["direction"] == direction, direction
        assert direction in event["detail"]


def test_cross_world_resume_same_world_records_nothing(monkeypatch):
    import jax

    from pytorch_distributed_mnist_tpu import cli
    from pytorch_distributed_mnist_tpu.train import checkpoint

    failure_events.reset()
    monkeypatch.setattr(
        checkpoint, "checkpoint_world",
        lambda path: {"processes": 1, "devices": jax.device_count()})
    cli._note_cross_world_resume("ckpt_x.npz")
    assert [e for e in failure_events.snapshot()
            if e["kind"] == "checkpoint_reshard"] == []


# -- supervisor-side flag plumbing and validation ----------------------------


def test_strip_elastic_flags_covers_grow_flags():
    argv = ["--spawn", "3", "--elastic", "--elastic-grow",
            "--max-world", "4", "--model", "linear", "--max-world=2"]
    assert strip_elastic_flags(argv) == ["--spawn", "3", "--model",
                                         "linear"]


def test_supervise_validates_max_world():
    with pytest.raises(ValueError, match="max-world"):
        elastic.supervise(3, [], max_world=2)
    with pytest.raises(ValueError, match="max-world"):
        elastic.supervise(2, [], max_world=-1)


def test_cli_rejects_grow_flags_without_elastic():
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit, match="require --elastic"):
        main(["--elastic-grow", "--spawn", "2", "--dataset", "synthetic"])
    with pytest.raises(SystemExit, match="require --elastic"):
        main(["--max-world", "4", "--dataset", "synthetic"])


def test_cli_rejects_max_world_below_spawn():
    from pytorch_distributed_mnist_tpu.cli import main

    with pytest.raises(SystemExit, match="below the initial world"):
        main(["--elastic", "--spawn", "3", "--max-world", "2"])
