"""Publication gates of the capture watcher (tools/tpu_watch_r5.sh).

The watcher is the machinery that turns a rare chip-recovery window into
round evidence; its ``run_capture`` gating (producer exit code, required
backend marker, forbidden re-emission marker, skip-once-captured,
liveness re-probe) has to be right the one time it runs for real. These
tests extract the function from the script and exercise each gate with
stub producers — no TPU, no jax.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPT = os.path.join(REPO, "tools", "tpu_watch_r5.sh")


def _extract_run_capture() -> str:
    src = open(_SCRIPT).read()
    start = src.index("run_capture() {")
    end = src.index("\n}", start) + 2
    return src[start:end]


def _harness(tmp_path, probe_ok: bool, calls: str) -> subprocess.CompletedProcess:
    """Run run_capture scenarios in a bash sandbox with stubbed deps."""
    script = f"""
set -u
OUT={tmp_path}/out
STATE={tmp_path}/state
mkdir -p "$OUT" "$STATE"
log() {{ echo "$(date -u +%FT%TZ) $*" >> "$OUT/watch.log"; }}
probe_tpu() {{ {"true" if probe_ok else "false"}; }}
{_extract_run_capture()}
{calls}
"""
    return subprocess.run(["bash", "-c", script], capture_output=True,
                          text=True, timeout=60, cwd=str(tmp_path))


TPU = '"backend": "tpu"'


def test_good_capture_published_and_skipped_next_cycle(tmp_path):
    out = tmp_path / "out"
    p = _harness(tmp_path, True, f"""
run_capture item 30 "$OUT/item.json" '{TPU}' "" \
  printf '%s' '{{"backend": "tpu", "value": 1.0}}'
echo "first=$?"
# Second cycle: producer would now FAIL, but the item is already
# captured, so it must be skipped (rc 0) and the file untouched.
run_capture item 30 "$OUT/item.json" '{TPU}' "" false
echo "second=$?"
""")
    assert "first=0" in p.stdout and "second=0" in p.stdout
    assert (out / "item.json").read_text() == '{"backend": "tpu", "value": 1.0}'
    assert os.path.exists(tmp_path / "state" / "item")


@pytest.mark.parametrize("producer,why", [
    ("printf '%s' '{\"backend\": \"cpu\", \"value\": 1.0}'",
     "missing required tpu marker (honest CPU fallback line)"),
    ("false", "producer exit code nonzero"),
    ("sh -c 'printf bad; exit 3'", "nonzero rc with output"),
])
def test_rejected_captures_never_published(tmp_path, producer, why):
    p = _harness(tmp_path, True, f"""
run_capture item 30 "$OUT/item.json" '{TPU}' "" {producer}
echo "rc=$?"
""")
    # Rejection is ANY nonzero rc (the producer's own code passes through).
    rc_line = [l for l in p.stdout.splitlines() if l.startswith("rc=")][0]
    assert rc_line != "rc=0", why
    assert not os.path.exists(tmp_path / "out" / "item.json")
    assert not os.path.exists(tmp_path / "state" / "item")
    # The rejected output is preserved in the log for postmortems, and
    # no .new temp file leaks.
    assert not os.path.exists(tmp_path / "out" / "item.json.new")


def test_forbidden_marker_rejects_reemission(tmp_path):
    """bench.json's forbid gate: a line that is itself a watcher-capture
    re-emission must never be captured again."""
    p = _harness(tmp_path, True, f"""
run_capture bench 30 "$OUT/bench.json" '{TPU}' '"source": "watcher_capture"' \
  printf '%s' '{{"backend": "tpu", "value": 2.0, "source": "watcher_capture"}}'
echo "rc=$?"
""")
    assert "rc=1" in p.stdout
    assert not os.path.exists(tmp_path / "out" / "bench.json")


def test_dead_link_skips_without_running_producer(tmp_path):
    p = _harness(tmp_path, False, """
run_capture item 30 "$OUT/item.json" "" "" sh -c 'touch ran; true'
echo "rc=$?"
""")
    assert "rc=1" in p.stdout
    assert not os.path.exists(tmp_path / "ran")
    log = (tmp_path / "out" / "watch.log").read_text()
    assert "skipped: link re-probe failed" in log
