"""One rank of a real multi-process DP run, for tests/test_multiprocess.py.

Run as: python multiproc_worker.py RANK NPROCS PORT CKPT_DIR [extra CLI args]

Each process is one SPMD host: ``jax.distributed.initialize`` with a
localhost coordinator (the analog of the reference's
``mp.spawn``-per-GPU workers rendezvousing over
``tcp://127.0.0.1:23456``, ``/root/reference/multi_proc_single_gpu.py:167-168,
284-285``), then the FULL job driver (``cli.run``) — so the multi-host code
paths that a single-process suite can never reach actually execute:
``jax.make_array_from_process_local_data`` (data/loader.py), per-host
disjoint sampler shards, cross-process metric reduction, and process-0-only
checkpoint writes.

Prints one ``SUMMARY{json}`` line for the parent test to parse.
"""

import json
import os
import sys


def main() -> None:
    rank, nprocs = int(sys.argv[1]), int(sys.argv[2])
    port, ckpt_dir = sys.argv[3], sys.argv[4]
    extra = sys.argv[5:]

    # Hermetic CPU backend, one local device per process (the parent strips
    # any xla_force_host_platform_device_count flag from XLA_FLAGS).
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    if os.environ.get("TPUMNIST_TEST_CKPT_FAULT_RANK") == str(rank):
        # Fault injection for test_two_process_ckpt_write_fault_fails_all:
        # this rank's sharded shard-file write raises, exercising the
        # write-ok allgather that keeps the OTHER rank out of the
        # timeout-less publish barrier (round-4 advisor).
        from pytorch_distributed_mnist_tpu.train import checkpoint as _ckpt

        def _failing_write(*a, **kw):
            raise OSError("injected checkpoint write fault (test)")

        _ckpt._sharded_write_files = _failing_write

    if os.environ.get("TPUMNIST_TEST_RESUME_HIDE_RANK") == str(rank):
        # Fault injection for test_two_process_resume_divergence: this
        # rank's view of the checkpoint dir is "stale" (NFS attribute
        # cache) — try_resume silently reports no checkpoint, the exact
        # silent-fresh-train divergence the resume-outcome agreement
        # must turn into a loud symmetric exit. cli binds try_resume at
        # import, so patch the cli-module binding.
        from pytorch_distributed_mnist_tpu import cli as _cli

        def _blind_try_resume(path, state):
            return state, 0, 0.0

        _cli.try_resume = _blind_try_resume

    if os.environ.get("TPUMNIST_TEST_CKPT_FAULT_PUBLISH") and rank == 0:
        # Fault injection for test_two_process_ckpt_publish_fault: process
        # 0's publish body raises (the shared-fs RuntimeError path),
        # exercising the publish-phase agreement that keeps rank 1 out of
        # the trailing collective (round-5 audit).
        from pytorch_distributed_mnist_tpu.train import checkpoint as _ckpt

        def _failing_publish(*a, **kw):
            raise OSError("injected checkpoint publish fault (test)")

        _ckpt._publish_dir = _failing_publish

    args = build_parser().parse_args(
        [
            "--dataset", "synthetic",
            "--model", "linear",
            "--epochs", "1",
            "--batch-size", "64",
            "--synthetic-train-size", "256",
            "--synthetic-test-size", "128",
            "--trainer-mode", "stepwise",
            "--seed", "0",
            "--coordinator", f"127.0.0.1:{port}",
            "--num-processes", str(nprocs),
            "--process-id", str(rank),
            "--checkpoint-dir", ckpt_dir,
        ]
        + extra
    )
    summary = run(args)

    wrote = sorted(os.listdir(ckpt_dir)) if os.path.isdir(ckpt_dir) else []
    print(
        "SUMMARY"
        + json.dumps(
            {
                "rank": rank,
                "process_count": jax.process_count(),
                "device_count": jax.device_count(),
                "best_acc": summary["best_acc"],
                "train_loss": summary["history"][0]["train_loss"],
                "test_acc": summary["history"][0]["test_acc"],
                "start_epoch": summary.get("start_epoch"),
                "epochs_run": summary.get("epochs_run"),
                "checkpoint_files": wrote,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
