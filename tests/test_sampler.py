"""DistributedShardSampler semantics — parity with torch DistributedSampler
as used at ``/root/reference/multi_proc_single_gpu.py:143-144,159-161``."""

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.sampler import DistributedShardSampler


def shards(n, k, epoch=0, shuffle=True, drop_last=False):
    out = []
    for r in range(k):
        s = DistributedShardSampler(n, k, r, shuffle=shuffle, drop_last=drop_last)
        s.set_epoch(epoch)
        out.append(s.indices())
    return out


def test_disjoint_exact_cover_when_divisible():
    parts = shards(100, 4)
    allidx = np.concatenate(parts)
    assert allidx.size == 100
    assert sorted(allidx.tolist()) == list(range(100))  # disjoint exact cover


def test_padding_wraps_when_not_divisible():
    parts = shards(10, 4)  # ceil(10/4)=3 each, total 12, 2 padded
    assert all(p.size == 3 for p in parts)
    allidx = np.concatenate(parts)
    assert allidx.size == 12
    assert set(allidx.tolist()) == set(range(10))  # every sample covered


def test_drop_last_truncates():
    parts = shards(10, 4, drop_last=True)
    assert all(p.size == 2 for p in parts)
    assert len(set(np.concatenate(parts).tolist())) == 8


def test_epoch_reshuffle_changes_order_deterministically():
    s = DistributedShardSampler(64, 1, 0, shuffle=True, seed=0)
    s.set_epoch(0)
    e0a = s.indices()
    s.set_epoch(1)
    e1 = s.indices()
    s.set_epoch(0)
    e0b = s.indices()
    assert not np.array_equal(e0a, e1)  # different shuffle per epoch (:159-161)
    assert np.array_equal(e0a, e0b)  # deterministic for a given epoch


def test_no_shuffle_is_sequential():
    (idx,) = shards(10, 1, shuffle=False)
    assert np.array_equal(idx, np.arange(10))


def test_rank_validation():
    with pytest.raises(ValueError):
        DistributedShardSampler(10, 4, 4)


def test_ranks_agree_on_permutation():
    # All ranks must derive the same epoch permutation or shards overlap.
    parts = shards(1000, 8, epoch=7)
    assert len(set(np.concatenate(parts).tolist())) == 1000
