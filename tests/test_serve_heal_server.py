"""Serve-pool self-healing + /resize over real loopback HTTP — the
ISSUE acceptance twins for the serving plane:

- (b) a mesh group 'dies' under live loadgen traffic (the
  TPUMNIST_SERVE_FAULT injection — the single-process stand-in for a
  group SIGKILL): the pool quarantines it, in-flight and subsequent
  requests fail over with ZERO drops, the background regroup rebuilds
  the group from its chips, and ``loadgen --smoke --expect-groups``
  passes against the healed topology;
- (c) ``POST /resize`` re-shapes the pool under live traffic — up and
  back down — with zero dropped requests and /stats reporting the new
  topology (generation counter, group counts) after every step.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.pool import SERVE_FAULT_ENV
from pytorch_distributed_mnist_tpu.serve.server import (
    build_parser,
    create_server,
)
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _publish(ckpt_dir, epoch, seed):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return model, state


def _serve_args(ckpt_dir, **overrides):
    argv = [
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8,32",
        "--max-wait-ms", "2", "--max-queue", "128",
        "--poll-interval", "0.1",
        # Split-plane boots: this suite pins no fused behavior, and the
        # fused AOT warm would re-pay its compile wall per boot (x replicas)
        # across the whole file -- tier-1 compile budget. The fused default
        # is pinned in test_serve_server.py / test_serve_fused.py.
        "--no-fuse",
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


class _Server:
    def __init__(self, args):
        self.httpd = create_server(args)
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post(self, path, payload, timeout=120):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())


def _loadgen(url, requests, *extra):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", url, "--requests", str(requests),
         "--concurrency", "8", *extra],
        capture_output=True, text=True, timeout=300)


def test_serve_fault_env_names_agree():
    """tools/chaos.py spells the injection env var out (to stay
    jax-import-free at CLI time); it must match the pool's."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "chaos", os.path.join(REPO, "tools", "chaos.py"))
    chaos = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos)
    assert chaos.SERVE_FAULT_ENV == SERVE_FAULT_ENV


def test_group_death_under_live_loadgen_regroups_zero_drops(
        tmp_path, monkeypatch):
    """THE serve acceptance twin (b): group 0 of a 4-replica server
    'dies' after 5 batches under loadgen traffic. Every request must
    answer 200 with correct predictions (failover), the pool must
    quarantine + regroup, and the post-heal ``--expect-groups 4`` smoke
    must pass."""
    ckpt = tmp_path / "ckpt"
    model, state = _publish(ckpt, epoch=0, seed=10)
    monkeypatch.setenv(SERVE_FAULT_ENV, "0:5")
    srv = _Server(_serve_args(ckpt, serve_devices=4, quarantine_after=3))
    try:
        # Live traffic through the death + quarantine + regroup window.
        proc = _loadgen(srv.url, 600)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["smoke_ok"] and report["ok"] == 600
        assert report["status_counts"] == {"200": 600}  # zero drops
        assert report["transport_errors"] == 0

        # The pool actually walked the lifecycle (it quarantined and
        # healed — give the background rebuild a bounded moment).
        deadline = time.time() + 30
        while time.time() < deadline:
            stats = srv.get("/stats")
            if stats["regroups"] >= 1 and not stats["quarantined_groups"]:
                break
            time.sleep(0.1)
        assert stats["regroups"] >= 1, stats
        assert stats["failovers"] >= 3, stats
        assert stats["topology_generation"] >= 2, stats
        assert stats["active_groups"] == 4, stats
        assert stats["replicas"]["r0"]["generation"] == 1

        # The post-regroup topology gate, exactly as the ISSUE names it.
        proc = _loadgen(srv.url, 100, "--expect-groups", "4")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["smoke_ok"]
        assert report["active_groups"] == 4
        assert "topology_generation" in report

        # Correctness end to end on the healed pool: predictions pinned
        # to the direct forward pass, no corrupted requests.
        images, _ = synthetic_dataset(6, seed=2)
        reply = srv.post("/predict", {"images": images.tolist()})
        want = np.argmax(np.asarray(model.apply(
            state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        assert reply["predictions"] == [int(v) for v in want]
        assert reply["model_epoch"] == 0
    finally:
        srv.close()


def test_resize_under_live_traffic_zero_drops(tmp_path):
    """THE serve acceptance twin (c): /resize rolls the pool 2 -> 4 ->
    2 replicas while clients hammer /predict. Zero dropped or corrupted
    requests, and /stats reports the new topology after every step."""
    ckpt = tmp_path / "ckpt"
    model, state = _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_devices=2))
    images, _ = synthetic_dataset(4, seed=3)
    payload = {"images": images.tolist()}
    want = [int(v) for v in np.argmax(np.asarray(model.apply(
        state.params, jnp.asarray(normalize_images(images)),
        train=False)), axis=-1)]
    failures = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                reply = srv.post("/predict", payload, timeout=30)
                if reply["predictions"] != want:
                    failures.append(("corrupted", reply))
            except Exception as exc:  # noqa: BLE001
                failures.append(("error", repr(exc)))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)  # traffic established before the first resize
        reply = srv.post("/resize", {"serve_devices": 4})
        assert reply["ok"] and reply["new"]["groups"] == 4
        assert reply["old"]["groups"] == 2
        stats = srv.get("/stats")
        assert stats["serve_devices"] == 4 and stats["groups"] == 4
        assert stats["topology_generation"] == 1
        time.sleep(0.3)  # serve on the grown pool under traffic
        reply = srv.post("/resize", {"serve_devices": 2})
        assert reply["ok"] and reply["new"]["groups"] == 2
        time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
        srv.close()
    assert not failures, failures[:5]
    # (srv closed; but the last /stats was asserted above mid-flight.)


def test_resize_reports_final_topology_and_expect_groups(tmp_path):
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_devices=2))
    try:
        srv.post("/resize", {"serve_devices": 3})
        stats = srv.get("/stats")
        assert stats["groups"] == 3 == stats["active_groups"]
        assert stats["topology_generation"] == 1
        proc = _loadgen(srv.url, 60, "--expect-groups", "3")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        # And the wrong expectation FAILS the gate (the assertion has
        # teeth).
        proc = _loadgen(srv.url, 10, "--expect-groups", "2")
        assert proc.returncode == 1
    finally:
        srv.close()


def test_resize_rejections(tmp_path):
    """/resize speaks flag language and never wedges the server: bad
    targets 400 with nothing changed; the single-engine (non-pooled)
    server has no pool to re-shape."""
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, serve_devices=2))
    try:
        for payload, match in [
            ({}, "serve_devices and/or serve_mesh"),
            ([4], "JSON object"),  # valid JSON, wrong shape: still a 400
            ({"serve_devices": 99}, "local device"),
            ({"serve_devices": "x"}, "invalid literal"),
            ({"serve_mesh": 2}, "no mesh to resize"),
        ]:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                srv.post("/resize", payload)
            assert exc_info.value.code == 400
            body = json.loads(exc_info.value.read())
            assert match in body["error"]
        assert srv.get("/stats")["groups"] == 2  # nothing changed
    finally:
        srv.close()
    # The default single-engine plane: no pool, /resize is a 400 that
    # names the flags that would create one.
    single = _Server(_serve_args(ckpt))
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            single.post("/resize", {"serve_devices": 2})
        assert exc_info.value.code == 400
        assert "pooled data plane" in json.loads(exc_info.value.read())["error"]
    finally:
        single.close()


def test_sharded_pool_resize_mesh_regroups(tmp_path):
    """The sharded plane resizes too: a 4-chip expert pool at mesh 2
    (2 groups) re-shapes to one all-chip mesh group (mesh 4) under the
    same zero-drop contract, and /stats carries the new mesh shape."""
    from pytorch_distributed_mnist_tpu.train.state import (
        create_train_state as _cts,
    )

    ckpt = tmp_path / "ckpt"
    model = get_model("moe_mlp", compute_dtype=jnp.float32)
    state = _cts(model, jax.random.key(4))
    save_checkpoint(state, epoch=0, best_acc=0.5, is_best=False,
                    directory=str(ckpt), process_index=0)
    srv = _Server(_serve_args(ckpt, model="moe_mlp", buckets="1,8",
                              serve_devices=4, serve_mode="expert",
                              serve_mesh=2))
    try:
        images, _ = synthetic_dataset(5, seed=1)
        want = [int(v) for v in np.argmax(np.asarray(model.apply(
            state.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)]
        assert srv.post("/predict",
                        {"images": images.tolist()})["predictions"] == want
        reply = srv.post("/resize", {"serve_mesh": 4})
        assert reply["ok"]
        assert reply["new"]["mesh_devices"] == 4
        assert reply["new"]["groups"] == 1
        stats = srv.get("/stats")
        assert stats["mesh_devices"] == 4 and stats["mesh_groups"] == 1
        assert stats["topology_generation"] == 1
        assert srv.post("/predict",
                        {"images": images.tolist()})["predictions"] == want
        # An indivisible mesh target is refused with nothing changed.
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            srv.post("/resize", {"serve_mesh": 3})
        assert exc_info.value.code == 400
        assert srv.get("/stats")["mesh_groups"] == 1
    finally:
        srv.close()
