"""Cross-entropy parity with torch.nn.functional.cross_entropy semantics
(mean-reduced, integer targets — reference ``:88``)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy


def _reference_xent(logits, labels):
    # Straight log-softmax NLL in numpy, mean reduction.
    logits = np.asarray(logits, np.float64)
    z = logits - logits.max(axis=-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
    return -logp[np.arange(len(labels)), labels].mean()


def test_matches_numpy_reference():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    np.testing.assert_allclose(got, _reference_xent(logits, labels), rtol=1e-5)


def test_matches_torch_cross_entropy():
    torch = __import__("torch")
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(32, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 32)
    want = float(
        torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels, dtype=torch.long)
        )
    )
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_uniform_logits_give_log_nclasses():
    logits = jnp.zeros((8, 10))
    labels = jnp.arange(8) % 10
    np.testing.assert_allclose(float(cross_entropy(logits, labels)), np.log(10), rtol=1e-4)


def test_large_logits_stable():
    logits = jnp.array([[1000.0, 0.0], [0.0, 1000.0]])
    labels = jnp.array([0, 1])
    assert float(cross_entropy(logits, labels)) < 1e-3  # no nan/inf


# ---------------------------------------------------------------------------
# Fused Pallas kernel (ops/pallas/xent.py), interpret mode on CPU
# ---------------------------------------------------------------------------


def _oracle_per_example_and_grad(logits, labels, g):
    import jax

    from pytorch_distributed_mnist_tpu.ops.loss import (
        cross_entropy_per_example,
    )

    loss, vjp = jax.vjp(
        lambda l: cross_entropy_per_example(l, jnp.asarray(labels)),
        jnp.asarray(logits),
    )
    return np.asarray(loss), np.asarray(vjp(jnp.asarray(g))[0])


def test_fused_xent_matches_oracle_value_and_grad():
    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy_per_example,
    )
    import jax

    rng = np.random.default_rng(2)
    for b in (8, 20, 300):  # one block, ragged rows, multiple blocks
        logits = rng.normal(size=(b, 10)).astype(np.float32) * 5
        labels = rng.integers(0, 10, b)
        g = rng.normal(size=(b,)).astype(np.float32)
        want, want_dl = _oracle_per_example_and_grad(logits, labels, g)
        got, vjp = jax.vjp(
            lambda l: fused_cross_entropy_per_example(l, jnp.asarray(labels)),
            jnp.asarray(logits),
        )
        got_dl = np.asarray(vjp(jnp.asarray(g))[0])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got_dl, want_dl, rtol=1e-5, atol=1e-6)


def test_fused_xent_saturated_grad_matches_clamped_oracle():
    """Float-saturated logits engage the forward's max(lse - picked, 0)
    clamp; the backward kernel's gate must reproduce XLA's d/dx max(x, 0)
    exactly — including the 0.5 split at the tie — so the fused and XLA
    gradients agree even at the clamp boundary (round-2 ADVICE)."""
    import jax

    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy_per_example,
    )

    # Row 0: hard saturation — lse == picked exactly (every other lane
    # underflows), the tie case. Rows 1-2: ordinary logits. Row 3: strong
    # but unsaturated.
    logits = np.array([
        [900.0, -900.0, -900.0, -900.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0],
        [1.0, 2.0, 3.0, -1.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.5],
        [-5.0, 5.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0],
        [30.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0],
    ], np.float32)
    labels = np.array([0, 2, 1, 0])
    g = np.ones((4,), np.float32)
    want, want_dl = _oracle_per_example_and_grad(logits, labels, g)
    got, vjp = jax.vjp(
        lambda l: fused_cross_entropy_per_example(l, jnp.asarray(labels)),
        jnp.asarray(logits),
    )
    got_dl = np.asarray(vjp(jnp.asarray(g))[0])
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(got_dl, want_dl, rtol=1e-6, atol=1e-7)


def test_fused_xent_bf16_logits():
    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy,
    )

    rng = np.random.default_rng(3)
    logits = (rng.normal(size=(32, 10)) * 3).astype(jnp.bfloat16)
    labels = rng.integers(0, 10, 32)
    got = float(fused_cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    want = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    np.testing.assert_allclose(got, want, rtol=1e-3)


def test_fused_xent_masked_mean_matches():
    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy,
    )

    rng = np.random.default_rng(4)
    logits = rng.normal(size=(24, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 24)
    mask = (rng.random(24) > 0.3).astype(np.float32)
    got = float(fused_cross_entropy(
        jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(mask)))
    want = float(cross_entropy(
        jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(mask)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fused_xent_too_many_classes_raises():
    from pytorch_distributed_mnist_tpu.ops.pallas.xent import (
        fused_cross_entropy,
    )

    with pytest.raises(ValueError, match="128 classes"):
        fused_cross_entropy(jnp.zeros((4, 200)), jnp.zeros((4,), jnp.int32))


def test_loss_impl_switch_in_train_step(tmp_path):
    """--loss fused end-to-end: same training trajectory as the XLA impl
    (f32 model, single device via stepwise mode on the 8-dev suite is
    still GSPMD — use explicit mode, which shard_maps and hands the
    kernel local shards)."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    common = [
        "--dataset", "synthetic", "--model", "linear", "--dtype", "f32",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--epochs", "1",
        "--trainer-mode", "explicit",
    ]
    s_xla = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "a"), "--loss", "xla"]))
    s_fused = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "b"), "--loss",
                  "fused"]))
    np.testing.assert_allclose(
        s_fused["history"][0]["train_loss"],
        s_xla["history"][0]["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(
        s_fused["history"][0]["test_acc"],
        s_xla["history"][0]["test_acc"], rtol=1e-6)


def test_fused_loss_gspmd_multidevice_matches_xla(tmp_path):
    """--loss fused under GSPMD (scan/stepwise) on the 8-device mesh: the
    nested shard_map hands the kernel per-device batch shards; the
    training trajectory must match the XLA impl."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    for mode in ("stepwise", "scan"):
        common = [
            "--dataset", "synthetic", "--model", "linear", "--dtype", "f32",
            "--batch-size", "64", "--synthetic-train-size", "256",
            "--synthetic-test-size", "128", "--seed", "0", "--epochs", "1",
            "--trainer-mode", mode,
        ]
        s_xla = run(build_parser().parse_args(
            common + ["--checkpoint-dir", str(tmp_path / f"x{mode}")]))
        s_fused = run(build_parser().parse_args(
            common + ["--checkpoint-dir", str(tmp_path / f"f{mode}"),
                      "--loss", "fused"]))
        np.testing.assert_allclose(
            s_fused["history"][0]["train_loss"],
            s_xla["history"][0]["train_loss"], rtol=1e-5)
        np.testing.assert_allclose(
            s_fused["history"][0]["test_acc"],
            s_xla["history"][0]["test_acc"], rtol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("axis_flag", [
    ("--tensor-parallel", "2"),
    ("--sequence-parallel", "2"),
])
def test_fused_loss_on_tp_sp_mesh_matches_xla(tmp_path, axis_flag):
    """--loss fused on TP and SP meshes: the nested shard_map's P('data')
    specs force a batch-sharded, axis-replicated layout — trajectory
    equal to the XLA impl."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    common = [
        "--dataset", "synthetic", "--model", "vit", "--dtype", "f32",
        "--patch-size", "7", *axis_flag,
        "--batch-size", "32", "--synthetic-train-size", "64",
        "--synthetic-test-size", "32", "--seed", "0", "--epochs", "1",
        "--trainer-mode", "stepwise",
    ]
    s_xla = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "a")]))
    s_fused = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "b"),
                  "--loss", "fused"]))
    np.testing.assert_allclose(
        s_fused["history"][0]["train_loss"],
        s_xla["history"][0]["train_loss"], rtol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize("extra", [
    (),                              # DP x PP
    ("--tensor-parallel", "2"),      # DP x PP x TP
])
def test_fused_loss_on_pp_mesh_matches_xla(tmp_path, extra):
    """--loss fused on the pipeline mesh (round-2 VERDICT composition
    hole, now closed): the logits leaving the GPipe shard_map are
    data-sharded / stage-replicated, exactly the layout the loss kernel's
    nested shard_map in_specs request — trajectory equal to the XLA
    impl."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    common = [
        "--dataset", "synthetic", "--model", "vit", "--dtype", "f32",
        "--pipeline-stages", "2", *extra,
        "--batch-size", "32", "--synthetic-train-size", "64",
        "--synthetic-test-size", "32", "--seed", "0", "--epochs", "1",
        "--trainer-mode", "stepwise",
    ]
    s_xla = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "a")]))
    s_fused = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "b"),
                  "--loss", "fused"]))
    np.testing.assert_allclose(
        s_fused["history"][0]["train_loss"],
        s_xla["history"][0]["train_loss"], rtol=1e-5)
    np.testing.assert_allclose(
        s_fused["history"][0]["test_acc"],
        s_xla["history"][0]["test_acc"], rtol=1e-6)


def test_fused_loss_ragged_batch_falls_back_statically():
    """A batch not divisible by the data axis cannot enter the nested
    shard_map; the per-example fn must statically fall back to XLA and
    still produce correct values."""
    import jax

    from pytorch_distributed_mnist_tpu.ops.loss import (
        cross_entropy,
        set_loss_impl,
    )
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(5)
    logits = rng.normal(size=(30, 10)).astype(np.float32)  # 30 % 8 != 0
    labels = rng.integers(0, 10, 30)
    want = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    set_loss_impl("fused", mesh=make_mesh(("data",)))
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_fused_loss_grad_accum_scan_matches_xla(tmp_path):
    """fused loss inside the grad-accum micro-batch scan inside the epoch
    scan — the deepest nesting the trainer produces — equals the XLA impl
    exactly (f32)."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    common = [
        "--dataset", "synthetic", "--model", "linear", "--dtype", "f32",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--epochs", "1",
        "--trainer-mode", "scan", "--grad-accum", "2",
    ]
    a = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "a")]))
    b = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "b"), "--loss",
                  "fused"]))
    np.testing.assert_allclose(
        a["history"][0]["train_loss"], b["history"][0]["train_loss"],
        rtol=1e-6)
