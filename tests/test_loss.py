"""Cross-entropy parity with torch.nn.functional.cross_entropy semantics
(mean-reduced, integer targets — reference ``:88``)."""

import jax.numpy as jnp
import numpy as np

from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy


def _reference_xent(logits, labels):
    # Straight log-softmax NLL in numpy, mean reduction.
    logits = np.asarray(logits, np.float64)
    z = logits - logits.max(axis=-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(axis=-1, keepdims=True))
    return -logp[np.arange(len(labels)), labels].mean()


def test_matches_numpy_reference():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 16)
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    np.testing.assert_allclose(got, _reference_xent(logits, labels), rtol=1e-5)


def test_matches_torch_cross_entropy():
    torch = __import__("torch")
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(32, 10)).astype(np.float32)
    labels = rng.integers(0, 10, 32)
    want = float(
        torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels, dtype=torch.long)
        )
    )
    got = float(cross_entropy(jnp.asarray(logits), jnp.asarray(labels)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_uniform_logits_give_log_nclasses():
    logits = jnp.zeros((8, 10))
    labels = jnp.arange(8) % 10
    np.testing.assert_allclose(float(cross_entropy(logits, labels)), np.log(10), rtol=1e-4)


def test_large_logits_stable():
    logits = jnp.array([[1000.0, 0.0], [0.0, 1000.0]])
    labels = jnp.array([0, 1])
    assert float(cross_entropy(logits, labels)) < 1e-3  # no nan/inf
