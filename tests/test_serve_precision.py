"""The serving precision plane (ISSUE 14): quantized per-bucket programs.

Pins the registry contract (f32/bf16/int8w/int8, extensible), the
quantized-vs-f32 exactness bounds per precision x servable mode (argmax
agreement + logit bounds, padded AND exact-bucket), install-time
quantization semantics (scales ride the tree as arguments — zero
steady-state recompiles per bucket x mode x precision), the int8
staging dtype/lifecycle, and hot reload under hammering traffic with no
mixed-precision batch.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.pool import EnginePool
from pytorch_distributed_mnist_tpu.serve.programs import (
    ACT_SCALE,
    QuantLeaf,
    ServePrecision,
    dequantize_params,
    get_precision,
    precision_engine_name,
    quantize_leaf_i8,
    register_precision,
    serve_precisions,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

pytestmark = pytest.mark.serve

QUANTIZED = ("bf16", "int8w", "int8")


# -- trained params per model (sharpened logits: fresh-init logits are
# near-ties, where quantization noise flips argmax for free) -----------------

_TRAINED: dict = {}


def _trained_params(model_name: str):
    if model_name in _TRAINED:
        return _TRAINED[model_name]
    model = get_model(model_name, compute_dtype=jnp.float32)
    images, labels = synthetic_dataset(256, seed=3)
    x = jnp.asarray(normalize_images(images))
    y = jnp.asarray(labels)
    params = create_train_state(model, jax.random.key(0)).params
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    def loss_fn(p):
        logits = model.apply(p, x, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    @jax.jit
    def step(p, o):
        updates, o = tx.update(jax.grad(loss_fn)(p), o, p)
        return optax.apply_updates(p, updates), o

    for _ in range(30):
        params, opt = step(params, opt)
    _TRAINED[model_name] = (model, params)
    return _TRAINED[model_name]


# -- registry ----------------------------------------------------------------


def test_precision_registry_vocabulary():
    precisions = serve_precisions()
    assert precisions[0] == "f32"
    assert set(precisions) == {"f32", "bf16", "int8w", "int8"}
    with pytest.raises(ValueError, match="unknown serve precision"):
        get_precision("fp4")
    with pytest.raises(ValueError, match="already registered"):
        register_precision(ServePrecision("bf16"))
    # None resolves to the f32 identity (the engines' default path).
    assert get_precision(None).identity
    assert not get_precision("int8").identity


def test_precision_engine_name_composition():
    """serve_forward_b{b}@{mode}.{prec} per the registry contract; f32
    keeps every historical (suffix-free) name."""
    assert precision_engine_name("r0", "f32") == "r0"
    assert precision_engine_name(None, "f32") is None
    assert precision_engine_name("r0", "bf16") == "r0.bf16"
    assert precision_engine_name("tensor.g1", "int8w") == "tensor.g1.int8w"
    assert precision_engine_name(None, "int8") == "int8"


def test_quantize_leaf_scales_and_roundtrip():
    rng = np.random.default_rng(0)
    leaf = rng.normal(size=(64, 32)).astype(np.float32)
    q = quantize_leaf_i8(leaf)
    assert isinstance(q, QuantLeaf)
    assert q.q.dtype == np.int8 and q.q.shape == leaf.shape
    assert q.s == np.float32(np.abs(leaf).max() / np.float32(127.0))
    # Symmetric quantization round-trip error is bounded by scale/2.
    back = q.q.astype(np.float32) * q.s
    assert float(np.abs(back - leaf).max()) <= float(q.s) / 2 + 1e-7
    # All-zero leaves take scale 1.0 (no divide-by-zero, zeros stay).
    z = quantize_leaf_i8(np.zeros((4,), np.float32))
    assert z.s == np.float32(1.0) and not z.q.any()


def test_dequantize_params_walks_mixed_trees():
    tree = {"a": quantize_leaf_i8(np.full((3,), 2.0, np.float32)),
            "b": np.arange(3)}  # int leaf passes through unquantized
    out = dequantize_params(tree)
    np.testing.assert_allclose(np.asarray(out["a"]), 2.0, rtol=1e-2)
    np.testing.assert_array_equal(np.asarray(out["b"]), np.arange(3))


def test_int8_quantize_skips_integer_leaves():
    spec = get_precision("int8w")
    tree = {"w": np.ones((2, 2), np.float32), "step": np.int32(7)}
    q = spec.quantize(tree)
    assert isinstance(q["w"], QuantLeaf)
    assert q["step"] == np.int32(7)  # not a QuantLeaf


@pytest.mark.parametrize("precision", QUANTIZED)
def test_quantize_is_idempotent(precision):
    """The pool quantizes ONCE per publish and fans the quantized tree
    to its engines, whose install-time quantize runs again — the second
    pass must be the identity (a QuantLeaf's f32 scale leaf must never
    be re-quantized)."""
    spec = get_precision(precision)
    rng = np.random.default_rng(1)
    tree = {"w": rng.normal(size=(8, 4)).astype(np.float32),
            "step": np.int32(3)}
    once = spec.quantize(tree)
    twice = spec.quantize(once)
    assert jax.tree_util.tree_structure(once) \
        == jax.tree_util.tree_structure(twice)
    for a, b in zip(jax.tree_util.tree_leaves(once),
                    jax.tree_util.tree_leaves(twice)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(a).dtype == np.asarray(b).dtype


# -- f32 stays byte-identical ------------------------------------------------


def test_f32_precision_is_byte_identical_to_default():
    model, params = _trained_params("cnn")
    images, _ = synthetic_dataset(16, seed=1)
    default = InferenceEngine(model.apply, params)
    explicit = InferenceEngine(model.apply, params, precision="f32")
    default.warmup()
    explicit.warmup()
    np.testing.assert_array_equal(
        default.logits(images).view(np.uint32),
        explicit.logits(images).view(np.uint32))
    # f32 keeps the historical program names (no suffix) and f32 staging.
    assert explicit.program_name(8) == "serve_forward_b8"
    assert explicit._staging.dtype == np.float32


# -- exactness bounds per precision x servable mode --------------------------

# (mode, model, mesh) — every servable plane: the single-device
# replicated engine, the SPMD tensor/expert mesh groups, and the MPMD
# pipeline chain. 2-chip meshes on the 8-virtual-device CPU world.
MODES = [
    ("replicated", "cnn", 1),
    ("tensor", "vit", 2),
    ("expert", "moe_mlp", 2),
    ("pipeline", "vit", 2),
]


def _build_plane(mode, model_name, mesh, precision):
    model, params = _trained_params(model_name)
    if mode == "replicated":
        engine = InferenceEngine(
            model.apply, params, buckets=(1, 8), precision=precision,
            name=precision_engine_name(None, precision))
        engine.warmup()
        return engine
    if mode == "pipeline":
        from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
            split_vit_params,
        )

        params = split_vit_params(params)
    pool = EnginePool(
        model.apply, params, devices=jax.local_devices()[:mesh],
        buckets=(1, 8), serve_mode=mode, mesh_size=mesh,
        model_name=model_name, model=model, precision=precision)
    pool.warmup()
    return pool


def _plane_logits(plane, images):
    if isinstance(plane, EnginePool):
        return plane.complete(plane.dispatch(plane.preprocess(images)))[0]
    return plane.logits(images)


@pytest.mark.parametrize("mode,model_name,mesh", MODES,
                         ids=[m[0] for m in MODES])
def test_quantized_vs_f32_exactness_bounds(mode, model_name, mesh):
    """ISSUE 14 acceptance: for every servable mode, the bf16 and int8w
    (and int8) engines answer with >= 0.99 argmax agreement vs the f32
    engine, with bounded logit deltas — on padded (5-row) AND
    exact-bucket (8-row) batches — and ZERO steady-state recompiles per
    bucket x mode x precision."""
    images, _ = synthetic_dataset(128, seed=7)
    f32_plane = _build_plane(mode, model_name, mesh, "f32")
    ref = np.concatenate([_plane_logits(f32_plane, images[i:i + 8])
                          for i in range(0, 128, 8)])
    ref_pred = np.argmax(ref, axis=-1)
    scale = max(1.0, float(np.abs(ref).max()))
    bounds = {"bf16": 0.02, "int8w": 0.15, "int8": 0.15}
    # The acceptance bar (>= 0.99) is for bf16 and int8w; int8 adds
    # activation quantization on top and gets a slightly wider bar —
    # which is exactly why the canary gates it in production.
    agreement_floor = {"bf16": 0.99, "int8w": 0.99, "int8": 0.96}
    for precision in QUANTIZED:
        plane = _build_plane(mode, model_name, mesh, precision)

        def compiles():
            return {n: rec["backend_compiles"] for n, rec in
                    compile_log.stats()["programs"].items()
                    if n.startswith("serve_forward_")}

        before = compiles()
        # Exact-bucket batches (8 rows == bucket 8) and padded batches
        # (5 rows padded up to bucket 8) must both satisfy the bounds.
        exact = np.concatenate([_plane_logits(plane, images[i:i + 8])
                                for i in range(0, 128, 8)])
        padded = _plane_logits(plane, images[:5])
        assert compiles() == before, \
            f"{mode}.{precision} recompiled in steady state"
        agreement = float((np.argmax(exact, -1) == ref_pred).mean())
        assert agreement >= agreement_floor[precision], \
            (f"{mode}.{precision}: argmax agreement {agreement} < "
             f"{agreement_floor[precision]}")
        assert float(np.abs(exact - ref).max()) <= bounds[precision] * scale
        np.testing.assert_allclose(
            padded, exact[:5], atol=1e-5,
            err_msg=f"{mode}.{precision}: padded != exact-bucket rows")
        assert exact.dtype == np.float32  # logits come back f32 always


def test_program_names_carry_the_precision_suffix():
    """CompileLog names per the ISSUE: serve_forward_b{b}@{mode}.{prec}
    (with the group/stage qualifiers in their established spots)."""
    _build_plane("tensor", "vit", 2, "int8w")
    _build_plane("pipeline", "vit", 2, "bf16")
    names = set(compile_log.stats()["programs"])
    assert "serve_forward_b8@tensor.int8w" in names
    assert "serve_forward_b8@pipeline.bf16.s0" in names
    assert "serve_forward_b8@pipeline.bf16.s1" in names


# -- int8 staging ------------------------------------------------------------


def test_int8_staging_dtype_and_steady_state():
    """The int8 plane stages int8 buffers (a quarter of the H2D bytes)
    through the same free-list lifecycle: steady state allocates
    nothing new, and the padded tail is zeros."""
    model, params = _trained_params("cnn")
    engine = InferenceEngine(model.apply, params, buckets=(8,),
                             precision="int8", name="int8")
    engine.warmup()
    assert engine._staging.dtype == np.int8
    images, _ = synthetic_dataset(5, seed=2)
    engine.logits(images)
    allocated = engine.staging_allocated()
    for _ in range(5):
        engine.logits(images)
    assert engine.staging_allocated() == allocated  # free-list reuse


def test_int8_host_quantize_matches_program_scale():
    """The host quantizer and the on-chip dequant share ONE fixed
    activation scale (the normalize-range constant): round-tripping the
    staged batch recovers the normalized pixels within scale/2."""
    spec = get_precision("int8")
    images, _ = synthetic_dataset(4, seed=0)
    x = normalize_images(images)
    q = spec.stage_host(x)
    assert q.dtype == np.int8
    back = q.astype(np.float32) * ACT_SCALE
    assert float(np.abs(back - x).max()) <= float(ACT_SCALE) / 2 + 1e-7


def test_int8_native_and_numpy_staging_bitwise(monkeypatch):
    """TPUMNIST_NATIVE=0 switches the activation quantizer to the NumPy
    fallback; the staged bytes must be BITWISE identical — including on
    non-finite pixels (NaN pins to 0, ±inf clips)."""
    from pytorch_distributed_mnist_tpu.data import native

    spec = get_precision("int8")
    images, _ = synthetic_dataset(32, seed=9)
    x = normalize_images(images)
    x[0, 0, 0, 0] = np.nan
    x[0, 1, 0, 0] = np.inf
    x[0, 2, 0, 0] = -np.inf
    native_q = spec.stage_host(x)
    monkeypatch.setenv("TPUMNIST_NATIVE", "0")
    monkeypatch.setattr(native, "_lib", None)
    try:
        fallback_q = spec.stage_host(x)
    finally:
        monkeypatch.delenv("TPUMNIST_NATIVE")
        monkeypatch.setattr(native, "_lib", None)
    np.testing.assert_array_equal(native_q, fallback_q)


# -- hot reload --------------------------------------------------------------


@pytest.mark.parametrize("precision", ["bf16", "int8"])
def test_no_mixed_precision_batch_under_reload_hammering(precision):
    """Hot reload on a quantized engine: quantization happens at
    install time and the swap stays atomic, so under a hammering
    swap thread every batch's logits are BITWISE one checkpoint's
    quantized output or the other's — never a mix of one publish's
    values with another's scales."""
    model, params_a = _trained_params("cnn")
    params_b = jax.tree_util.tree_map(lambda x: x * 1.5, params_a)
    engine = InferenceEngine(model.apply, params_a, buckets=(8,),
                             precision=precision, name=precision,
                             params_epoch=1)
    engine.warmup()
    images, _ = synthetic_dataset(8, seed=4)
    want_a = engine.logits(images)
    engine.swap_params(params_b, epoch=2)
    want_b = engine.logits(images)
    assert not np.array_equal(want_a, want_b)

    stop = threading.Event()

    def hammer():
        flip = False
        while not stop.is_set():
            # Epoch-less swaps install unconditionally (the ordering
            # rule is about provenance) — maximal churn.
            engine.swap_params(params_b if flip else params_a)
            flip = not flip

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for _ in range(60):
            got = engine.logits(images)
            is_a = np.array_equal(got, want_a)
            is_b = np.array_equal(got, want_b)
            assert is_a or is_b, "batch mixed two publishes' quantization"
    finally:
        stop.set()
        t.join(5.0)


def test_pool_reload_fans_out_quantized(tmp_path):
    """The pool's ONE host-side f32 load fans out to per-replica
    install-time quantization; epochs stay the swap-ordering key."""
    model, params_a = _trained_params("cnn")
    params_b = jax.tree_util.tree_map(lambda x: x + 0.25, params_a)
    pool = EnginePool(model.apply, params_a,
                      devices=jax.local_devices()[:2], buckets=(1, 8),
                      params_epoch=1, precision="int8w")
    pool.warmup()
    images, _ = synthetic_dataset(8, seed=5)
    before = _plane_logits(pool, images)
    assert pool.swap_params(params_b, epoch=2) == 2  # both replicas
    after = _plane_logits(pool, images)
    assert not np.array_equal(before, after)
    # A stale fan-out never downgrades a quantized replica either.
    assert pool.swap_params(params_a, epoch=1) == 0
    np.testing.assert_array_equal(_plane_logits(pool, images), after)
