"""Tensor parallelism on the virtual 8-device mesh.

The strategy checklist (SURVEY.md section 2c) requires only DP for parity,
but the mesh is N-dimensional by design; these tests pin the property that
makes TP free to adopt: a DP x TP step is NUMERICALLY EQUIVALENT to the
single-device step — layout changes, math doesn't.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.tensor import (
    make_tp_eval_step,
    make_tp_train_step,
    shard_state,
    state_shardings,
    vit_tp_rules,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(7)
    return {
        "image": jnp.asarray(rng.normal(size=(16, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32),
    }


def _f32_vit():
    return get_model("vit", compute_dtype=jnp.float32)


def test_state_shardings_match_rules():
    mesh = make_mesh(("data", "model"), shape=(4, 2))
    state = create_train_state(_f32_vit(), jax.random.key(0))
    sh = state_shardings(state, mesh, vit_tp_rules())
    qkv = sh.params["params"]["block0"]["attn"]["qkv"]["kernel"]
    assert qkv.spec == P(None, "model")
    # Adam moments carry the SAME layout as their params.
    mu_qkv = sh.opt_state.inner_state[0].mu["params"]["block0"]["attn"]["qkv"]["kernel"]
    assert mu_qkv.spec == P(None, "model")
    # Unmatched leaves replicate.
    assert sh.step.spec == P()
    assert sh.params["params"]["embed"]["kernel"].spec == P()


def test_tp_step_equals_single_device_step(batch):
    """DP(4) x TP(2) train step == single-device train step (same math).

    SGD optimizer: its update is linear in the gradient, so cross-layout
    reduction-order noise stays O(1e-7) in the params. (Adam is
    scale-invariant — a sign flip on a ~0 gradient coordinate moves a param
    by a full +-lr — so elementwise param equality under Adam is not a
    meaningful layout test.)
    """
    model = _f32_vit()
    state_1d = create_train_state(model, jax.random.key(0), optimizer="sgd")
    state_tp = create_train_state(model, jax.random.key(0), optimizer="sgd")

    mesh = make_mesh(("data", "model"), shape=(4, 2))
    rules = vit_tp_rules()
    state_tp, tp_sharding = shard_state(state_tp, mesh, rules)
    step_1d = make_train_step()
    step_tp = make_tp_train_step(mesh, tp_sharding)

    for _ in range(3):
        state_1d, m1 = step_1d(state_1d, batch)
        state_tp, mt = step_tp(state_tp, batch)

    np.testing.assert_allclose(float(mt.loss_sum), float(m1.loss_sum), rtol=1e-4)
    assert int(mt.correct) == int(m1.correct)
    p1 = jax.tree_util.tree_leaves(state_1d.params)
    pt = jax.tree_util.tree_leaves(jax.device_get(state_tp.params))
    for a, b in zip(p1, pt):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_tp_eval_step_equals_single_device(batch):
    model = _f32_vit()
    state = create_train_state(model, jax.random.key(1))
    mesh = make_mesh(("data", "model"), shape=(2, 4))
    rules = vit_tp_rules()
    sstate, s_sharding = shard_state(state, mesh, rules)
    ev_tp = make_tp_eval_step(mesh, s_sharding)

    from pytorch_distributed_mnist_tpu.train.steps import make_eval_step

    m1 = make_eval_step()(state, batch)
    mt = ev_tp(sstate, batch)
    np.testing.assert_allclose(float(mt.loss_sum), float(m1.loss_sum), rtol=1e-4)
    assert int(mt.correct) == int(m1.correct)


@pytest.mark.slow
def test_cli_tensor_parallel_end_to_end(tmp_path):
    """--tensor-parallel 2 trains the ViT through the full driver on a
    data x model mesh, matching the plain-DP run's metrics (TP is a layout
    change, not a math change)."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    base = [
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--root", str(tmp_path / "data"),
    ]
    tp_summary = run(build_parser().parse_args(
        base + ["--tensor-parallel", "2",
                "--checkpoint-dir", str(tmp_path / "ckpt_tp")]))
    dp_summary = run(build_parser().parse_args(
        base + ["--checkpoint-dir", str(tmp_path / "ckpt_dp")]))
    assert tp_summary["history"][0]["train_loss"] == pytest.approx(
        dp_summary["history"][0]["train_loss"], rel=1e-4)
    assert tp_summary["history"][0]["test_acc"] == pytest.approx(
        dp_summary["history"][0]["test_acc"], abs=1e-6)


@pytest.mark.slow
def test_cli_tensor_parallel_composes_with_zero1(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    summary = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--tensor-parallel", "2", "--optimizer-sharding", "zero1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ]))
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


def test_cli_tensor_parallel_rejects_non_vit(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "cnn", "--epochs", "1",
        "--tensor-parallel", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="require --model vit"):
        run(args)


@pytest.mark.slow
def test_cli_sequence_parallel_matches_dense(tmp_path):
    """--sequence-parallel 2 (ring attention) matches the dense-attention
    run's metrics: the ring is the same softmax, blockwise."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    base = [
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--patch-size", "7",
        "--root", str(tmp_path / "data"),
    ]
    sp = run(build_parser().parse_args(
        base + ["--sequence-parallel", "2",
                "--checkpoint-dir", str(tmp_path / "ckpt_sp")]))
    dense = run(build_parser().parse_args(
        base + ["--checkpoint-dir", str(tmp_path / "ckpt_d")]))
    assert sp["history"][0]["train_loss"] == pytest.approx(
        dense["history"][0]["train_loss"], rel=1e-4)
    assert sp["history"][0]["test_acc"] == pytest.approx(
        dense["history"][0]["test_acc"], abs=1e-6)


@pytest.mark.slow
def test_cli_dp_tp_sp_composed(tmp_path):
    """The full 3-axis mesh (data x model x seq) trains from the CLI."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    summary = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--patch-size", "7",
        "--sequence-parallel", "2", "--tensor-parallel", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ]))
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


def test_cli_sequence_parallel_rejects_indivisible_tokens(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--sequence-parallel", "2",  # default patch 4 -> 49 tokens
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="patch-size 7"):
        run(args)


@pytest.mark.slow
def test_cli_ulysses_matches_dense(tmp_path):
    """--sequence-parallel-impl ulysses (all_to_all head sharding) matches
    the dense run's metrics, same contract as the ring."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    base = [
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--patch-size", "7",
        "--root", str(tmp_path / "data"),
    ]
    uly = run(build_parser().parse_args(
        base + ["--sequence-parallel", "2",
                "--sequence-parallel-impl", "ulysses",
                "--checkpoint-dir", str(tmp_path / "ckpt_u")]))
    dense = run(build_parser().parse_args(
        base + ["--checkpoint-dir", str(tmp_path / "ckpt_d")]))
    assert uly["history"][0]["train_loss"] == pytest.approx(
        dense["history"][0]["train_loss"], rel=1e-4)
    assert uly["history"][0]["test_acc"] == pytest.approx(
        dense["history"][0]["test_acc"], abs=1e-6)


def test_cli_ulysses_rejects_tp(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit", "--epochs", "1",
        "--patch-size", "7", "--sequence-parallel", "2",
        "--sequence-parallel-impl", "ulysses", "--tensor-parallel", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="re-shards the"):
        run(args)
