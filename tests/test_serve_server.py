"""Serving integration on a real loopback HTTP server (in-process
ThreadingHTTPServer — no subprocess jax boot): the acceptance run
(loadgen >= 1000 requests, zero steady-state recompiles, correct
predictions, /stats quantiles + histogram) and hot reload under live
traffic."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import synthetic_dataset
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.server import build_parser, create_server
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

pytestmark = pytest.mark.serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _publish(ckpt_dir, epoch, seed):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


def _serve_args(ckpt_dir, **overrides):
    argv = [
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8,32",
        "--max-wait-ms", "2", "--max-queue", "128",
        "--poll-interval", "0.1",
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


class _Server:
    def __init__(self, args):
        self.httpd = create_server(args)
        host, port = self.httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())


@pytest.fixture()
def server(tmp_path):
    ckpt = tmp_path / "ckpt"
    state = _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt))
    try:
        yield srv, state, ckpt
    finally:
        srv.close()


def test_predict_healthz_stats(server):
    srv, state, _ = server
    images, _ = synthetic_dataset(5, seed=7)

    health = srv.get("/healthz")
    assert health["ok"] and health["model_epoch"] == 0

    reply = srv.post("/predict", {"images": images.tolist()})
    assert len(reply["predictions"]) == 5
    assert reply["model_epoch"] == 0
    # Correctness vs the direct forward pass on the SAME preprocessing.
    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images

    model = get_model("linear", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state.params, jnp.asarray(normalize_images(images)), train=False)),
        axis=-1)
    assert reply["predictions"] == [int(v) for v in want]

    # Single image without the leading axis works too.
    single = srv.post("/predict", {"images": images[0].tolist()})
    assert single["predictions"] == [int(want[0])]

    stats = srv.get("/stats")
    assert stats["requests"] >= 2
    assert {"p50", "p95", "p99"} <= set(stats["latency_ms"])
    # Superset, not equality: CompileLog is a process singleton, so a
    # full-suite run sees bucket programs other serve tests compiled too.
    assert {"serve_forward_b1", "serve_forward_b8",
            "serve_forward_b32"} <= set(stats["compile"]["programs"])

    assert srv.post("/predict", {"images": images.tolist()}) is not None
    bad = urllib.request.Request(
        srv.url + "/predict", data=b'{"images": "nonsense"}',
        headers={"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(bad, timeout=30)
        raised = False
    except urllib.error.HTTPError as exc:
        raised = exc.code == 400
        exc.read()
    assert raised


def test_loadgen_acceptance_zero_recompiles(server):
    """The PR's acceptance run: >= 1000 loadgen requests against a warm
    server complete with ZERO steady-state recompiles (CompileLog), and
    /stats carries the latency quantiles and batch-size histogram."""
    srv, _, _ = server
    # settle: one request through every bucket path before the snapshot
    images, _ = synthetic_dataset(3, seed=0)
    srv.post("/predict", {"images": images.tolist()})
    baseline_compiles = compile_log.stats()["totals"]["backend_compiles"]

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", srv.url, "--requests", "1000",
         "--concurrency", "8"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["smoke_ok"] and report["ok"] == 1000
    assert report["transport_errors"] == 0 and report["rejected"] == 0
    assert report["latency_ms"]["p99"] >= report["latency_ms"]["p50"] > 0

    # Zero steady-state recompiles: 1000 requests did not add a single
    # XLA backend compile beyond the AOT warmup.
    assert compile_log.stats()["totals"]["backend_compiles"] \
        == baseline_compiles

    stats = srv.get("/stats")
    assert stats["requests"] >= 1001
    assert stats["latency_ms"]["p99"] >= stats["latency_ms"]["p50"] > 0
    hist = stats["batch_histogram"]
    assert hist and all(k in ("1", "8", "32") for k in hist)
    assert sum(hist.values()) == stats["batches"]
    for rec in stats["compile"]["programs"].values():
        assert rec["backend_compiles"] >= 0  # present per bucket


def test_stats_fused_flag_and_loadgen_expectation(server):
    """The whole-program plane is the server default: /stats carries
    fused=true and ``loadgen --smoke --expect-fused`` passes; a
    ``--no-fuse`` server reports fused=false and FAILS the same
    expectation (the flag has teeth)."""
    srv, _, ckpt = server
    assert srv.get("/stats")["fused"] is True
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
         "--smoke", "--url", srv.url, "--requests", "40",
         "--concurrency", "4", "--expect-fused"],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["fused"] is True  # the advisory shape field rode along
    # The donation lifecycle's observable (§7k): every fused dispatch
    # donated-and-retired its staging buffer, so the per-bucket counter
    # must have kept pace with the traffic just driven.
    stats = srv.get("/stats")
    assert sum(stats["donated_staging_retired"].values()) > 0

    nofuse = _Server(_serve_args(ckpt, no_fuse=True))
    try:
        nf_stats = nofuse.get("/stats")
        assert nf_stats["fused"] is False
        # Nothing donates on the split plane — the key stays absent.
        assert "donated_staging_retired" not in nf_stats
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--smoke", "--url", nofuse.url, "--requests", "8",
             "--concurrency", "2", "--expect-fused"],
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
    finally:
        nofuse.close()


def test_hot_reload_under_live_traffic(server):
    """Publish a new checkpoint while clients hammer /predict: no request
    fails or returns malformed output, and predictions/epoch flip to the
    new params within a few poll intervals."""
    srv, state_a, ckpt = server
    images, _ = synthetic_dataset(4, seed=3)
    payload = {"images": images.tolist()}
    failures = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                reply = srv.post("/predict", payload)
                preds = reply["predictions"]
                if (len(preds) != 4
                        or not all(0 <= p <= 9 for p in preds)
                        or reply["model_epoch"] not in (0, 9)):
                    failures.append(("malformed", reply))
            except Exception as exc:  # noqa: BLE001
                failures.append(("error", repr(exc)))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # in-flight traffic established
    state_b = _publish(ckpt, epoch=9, seed=77)
    deadline = time.time() + 15.0
    while time.time() < deadline:
        if srv.get("/healthz")["model_epoch"] == 9:
            break
        time.sleep(0.05)
    time.sleep(0.3)  # keep hammering across the swap boundary
    stop.set()
    for t in threads:
        t.join(10.0)

    assert not failures, failures[:5]
    assert srv.get("/healthz")["model_epoch"] == 9
    # Steady state now answers with the NEW params.
    from pytorch_distributed_mnist_tpu.data.mnist import normalize_images

    model = get_model("linear", compute_dtype=jnp.float32)
    want = np.argmax(np.asarray(model.apply(
        state_b.params, jnp.asarray(normalize_images(images)),
        train=False)), axis=-1)
    assert srv.post("/predict", payload)["predictions"] \
        == [int(v) for v in want]
    assert srv.get("/stats")["reloads"] == 1


def test_overload_returns_503(tmp_path):
    """Admission control surfaces as HTTP 503, not latency: wedge the
    engine via a gated executable, fill the queue, and watch overflow
    requests bounce."""
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, max_queue=2, max_wait_ms=1))
    try:
        engine = srv.httpd.ctx.engine
        release = threading.Event()
        entered = threading.Event()

        def gate(fn):
            def gated(params, x):
                entered.set()
                release.wait(30.0)
                return fn(params, x)
            return gated

        # Wedge BOTH dispatch planes: raw uint8 requests ride the fused
        # bucket programs (the server default), float input the split
        # ones — the overload behavior under test is plane-independent.
        for table in (engine._compiled, engine._fused_compiled):
            for b, fn in list(table.items()):
                table[b] = gate(fn)
        images, _ = synthetic_dataset(1, seed=0)
        payload = {"images": images.tolist()}
        results = []

        def fire():
            try:
                srv.post("/predict", payload)
                results.append(200)
            except urllib.error.HTTPError as exc:
                exc.read()
                results.append(exc.code)

        threads = [threading.Thread(target=fire, daemon=True)
                   for _ in range(6)]
        threads[0].start()
        assert entered.wait(10.0)  # worker wedged inside the forward
        time.sleep(0.2)  # its batch has drained from the queue
        for t in threads[1:]:
            t.start()
        time.sleep(0.5)  # queue (2) full, the rest must be bouncing
        release.set()
        for t in threads:
            t.join(15.0)
        assert results.count(503) >= 1, results
        assert results.count(200) >= 3, results
        assert srv.get("/stats")["rejected"] >= 1
    finally:
        release.set()
        srv.close()


def test_no_checkpoint_serves_fresh_until_publish(tmp_path):
    """Boot with an empty dir: fresh-init params serve immediately, the
    first published checkpoint is hot-loaded."""
    ckpt = tmp_path / "empty"
    srv = _Server(_serve_args(ckpt))
    try:
        assert srv.get("/healthz")["model_epoch"] is None
        images, _ = synthetic_dataset(2, seed=1)
        assert len(srv.post("/predict",
                            {"images": images.tolist()})["predictions"]) == 2
        _publish(ckpt, epoch=3, seed=50)
        deadline = time.time() + 15.0
        while time.time() < deadline:
            if srv.get("/healthz")["model_epoch"] == 3:
                break
            time.sleep(0.05)
        assert srv.get("/healthz")["model_epoch"] == 3
    finally:
        srv.close()


def test_require_checkpoint_refuses_empty_dir(tmp_path):
    with pytest.raises(SystemExit, match="require-checkpoint"):
        create_server(_serve_args(tmp_path / "none",
                                  require_checkpoint=True))


def test_request_size_caps(tmp_path):
    """One giant request must not sneak past admission control: row
    count over --max-request-images is a 400, and an oversized body is
    refused (413) BEFORE being read/parsed."""
    import http.client

    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Server(_serve_args(ckpt, max_request_images=4))
    try:
        images, _ = synthetic_dataset(5, seed=0)
        try:
            srv.post("/predict", {"images": images.tolist()})
            code = 200
        except urllib.error.HTTPError as exc:
            code = exc.code
            body = json.loads(exc.read())
        assert code == 400 and "batch client-side" in body["error"]
        # 4 images (the cap) still serve fine.
        assert len(srv.post("/predict",
                            {"images": images[:4].tolist()})
                   ["predictions"]) == 4

        host, port = srv.httpd.server_address[:2]
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.putrequest("POST", "/predict")
        conn.putheader("Content-Type", "application/json")
        conn.putheader("Content-Length", str(64 << 20))  # claimed 64 MB
        conn.endheaders()
        resp = conn.getresponse()  # refused before the body arrives
        assert resp.status == 413
        conn.close()
    finally:
        srv.close()


def test_predict_reports_epoch_of_computing_params(server):
    """The model_epoch in a /predict reply is captured WITH the params
    that computed the batch (engine tag), not read from the engine after
    the fact — a hot reload between compute and reply can't mislabel."""
    srv, _, ckpt = server
    images, _ = synthetic_dataset(2, seed=5)
    assert srv.post("/predict",
                    {"images": images.tolist()})["model_epoch"] == 0
    _publish(ckpt, epoch=4, seed=99)
    deadline = time.time() + 15.0
    while time.time() < deadline:
        if srv.get("/healthz")["model_epoch"] == 4:
            break
        time.sleep(0.05)
    assert srv.post("/predict",
                    {"images": images.tolist()})["model_epoch"] == 4


def test_drain_rejects_with_retry_after(server):
    """POST /drain closes admission: new /predict bounces 503 with a
    Retry-After header and a body naming the draining state, /healthz
    and /stats both expose draining=true, and /stats active_requests
    reaches zero (the rolling-reload wait-for-quiescent contract)."""
    srv, _, _ = server
    images, _ = synthetic_dataset(2, seed=1)
    payload = {"images": images.tolist()}
    assert len(srv.post("/predict", payload)["predictions"]) == 2

    reply = srv.post("/drain", {"drain": True})
    assert reply["ok"] and reply["draining"] and not reply["was_draining"]
    assert srv.get("/healthz")["draining"] is True
    stats = srv.get("/stats")
    assert stats["draining"] is True
    assert stats["active_requests"] == 0  # nothing in flight = quiescent

    try:
        srv.post("/predict", payload)
        code, headers, body = 200, {}, {}
    except urllib.error.HTTPError as exc:
        code = exc.code
        headers = exc.headers
        body = json.loads(exc.read())
    assert code == 503
    assert body["draining"] is True and body["error"] == "draining"
    assert int(headers["Retry-After"]) >= 1  # the back-off contract

    # Idempotent: draining an already-draining server reports it was.
    assert srv.post("/drain", {"drain": True})["was_draining"] is True


def test_drain_then_rejoin_serves_again(server):
    """Undrain reopens admission with no restart: the same server that
    just bounced traffic answers again — the rolling reload's rejoin
    step is a state flip, not a process bounce."""
    srv, _, _ = server
    images, _ = synthetic_dataset(2, seed=4)
    payload = {"images": images.tolist()}
    srv.post("/drain", {"drain": True})
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        srv.post("/predict", payload)
    exc_info.value.read()
    assert exc_info.value.code == 503

    reply = srv.post("/drain", {"drain": False})
    assert reply["ok"] and not reply["draining"] and reply["was_draining"]
    assert srv.get("/healthz")["draining"] is False
    assert len(srv.post("/predict", payload)["predictions"]) == 2
    assert srv.get("/stats")["draining"] is False

    # Malformed drain bodies are a client error, not a state change.
    bad = urllib.request.Request(
        srv.url + "/drain", data=b'{"drain": "yes"}',
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(bad, timeout=30)
    exc_info.value.read()
    assert exc_info.value.code == 400
    assert srv.get("/healthz")["draining"] is False


def test_boot_falls_back_past_corrupt_latest(tmp_path):
    """A corrupt latest checkpoint must not turn a server restart into
    an outage: boot walks to the next-older epoch (the serving analog of
    --resume auto's fallback; quarantining stays the trainer's job)."""
    ckpt = tmp_path / "ckpt"
    state_good = _publish(ckpt, epoch=1, seed=10)
    with open(ckpt / "checkpoint_2.npz", "wb") as f:
        f.write(b"definitely not an npz")
    srv = _Server(_serve_args(ckpt))
    try:
        health = srv.get("/healthz")
        assert health["model_epoch"] == 1
        assert health["checkpoint"].endswith("checkpoint_1.npz")
        images, _ = synthetic_dataset(3, seed=2)
        from pytorch_distributed_mnist_tpu.data.mnist import (
            normalize_images,
        )

        model = get_model("linear", compute_dtype=jnp.float32)
        want = np.argmax(np.asarray(model.apply(
            state_good.params, jnp.asarray(normalize_images(images)),
            train=False)), axis=-1)
        got = srv.post("/predict", {"images": images.tolist()})
        assert got["predictions"] == [int(v) for v in want]
    finally:
        srv.close()
