"""Fault-injection (chaos) twins: real 2-process worlds with one rank
sabotaged at a named fault point (``TPUMNIST_FAULT``), proving the
run-supervision subsystem end to end — the proofs a monkeypatched unit
test cannot give:

- killing one host during the publish agreement ends the SURVIVOR with a
  ``PeerFailure`` naming the dead host and the phase, within seconds —
  not a hang until the test harness timeout — and a subsequent
  ``--resume auto`` world recovers from the last published checkpoint;
- killing a host mid-sharded-write leaves the epoch UNPUBLISHED (tmp dir
  only), and the next run cleans up and republishes;
- killing every host mid-epoch (the preemption case) loses at most the
  unpublished epoch: the same command line resumes and finishes;
- a host-local EXCEPTION (not a kill) delivers the poison pill: the
  healthy peer unwinds from its next agreement with the failure
  attributed to the right host and phase.

The acceptance twin (publish-agreement kill + recovery) runs in tier-1
with tight timeouts; the longer scenarios are ``slow``. All are marked
``chaos`` (`pytest -m chaos` runs just this harness).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from pytorch_distributed_mnist_tpu.parallel.launcher import (
    _child_env,
    free_port,
)

_WORKER = os.path.join(os.path.dirname(__file__), "multiproc_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tight deadline for chaos runs: the strand being tested must convert to
# a loud exit in seconds. Generous enough that a healthy-but-loaded rank
# (one CPU core timeshared by everything) cannot trip it spuriously.
_DEADLINE = "8"

pytestmark = pytest.mark.chaos


def _spawn(ckpt, flags, fault=None, nprocs=2, timeout=180):
    """Launch ``nprocs`` worker ranks (optionally fault-injected); wait
    for all (killing stragglers at ``timeout``); return [(rc, out)]."""
    port = free_port()
    env = _child_env()
    env["TPUMNIST_AGREEMENT_TIMEOUT"] = _DEADLINE
    if fault:
        env["TPUMNIST_FAULT"] = fault
    else:
        env.pop("TPUMNIST_FAULT", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), str(nprocs), str(port),
             str(ckpt)] + list(flags),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=_REPO,
        )
        for rank in range(nprocs)
    ]
    results = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                out = (out or "") + "\n<<killed by test harness timeout>>"
            results.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return results


def _summary(out):
    lines = [l for l in out.splitlines() if l.startswith("SUMMARY")]
    assert lines, f"no SUMMARY line in:\n{out[-3000:]}"
    return json.loads(lines[-1][len("SUMMARY"):])


_Z1 = ["--optimizer-sharding", "zero1"]


def test_kill_during_publish_agreement_peer_failure_then_resume(tmp_path):
    """THE acceptance twin. Epoch 1's publish agreement: rank 1 is
    SIGKILLed at the ``ckpt_publish`` fault point (after the write
    agreement, at the publish collective). Before the supervision layer,
    rank 0 blocked forever in the timeout-less publish barrier; now it
    must exit with ``PeerFailure`` attributing host 1 and the
    ``ckpt_publish`` phase — within seconds, not a hang — and a fresh
    2-process ``--resume auto`` world must recover from the last
    published checkpoint."""
    ckpt = tmp_path / "ckpts"
    t0 = time.monotonic()
    results = _spawn(ckpt, _Z1 + ["--epochs", "2"],
                     fault="ckpt_publish:1:kill:1")
    elapsed = time.monotonic() - t0
    (rc0, out0), (rc1, out1) = results
    assert rc1 == -9, f"rank 1 should have been SIGKILLed:\n{out1[-2000:]}"
    assert "<<killed by test harness timeout>>" not in out0, (
        f"rank 0 hung instead of exiting:\n{out0[-2000:]}")
    assert rc0 not in (0, None), f"rank 0 should have failed:\n{out0[-2000:]}"
    # Correct attribution: the phase and the host, in a PeerFailure.
    assert "PeerFailure" in out0
    assert "ckpt_publish" in out0
    assert "[1]" in out0
    # "within the configured deadline, not a hang": the whole twin —
    # startup, epoch 0, epoch 1, kill, supervised exit — stays well
    # under the old failure mode (blocked until the 180s harness kill).
    assert elapsed < 150, f"supervised exit took {elapsed:.0f}s"
    # Epoch 1 HAD published before the agreement (process 0 renames
    # before agreeing); epoch 0 is there from the previous save.
    names = set(os.listdir(ckpt))
    assert "checkpoint_0.ckpt" in names and "checkpoint_1.ckpt" in names

    # Recovery: the same world, no fault, picks up the last PUBLISHED
    # checkpoint (epoch 1 -> start at 2) and finishes the job.
    results = _spawn(ckpt, _Z1 + ["--epochs", "3", "--resume", "auto"])
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"recovery rank {rank} failed:\n{out[-3000:]}"
    for rc, out in results:
        s = _summary(out)
        assert s["start_epoch"] == 2 and s["epochs_run"] == 1


@pytest.mark.slow
def test_stall_during_publish_agreement_trips_watchdog(tmp_path):
    """The silent-peer flavor (process alive, never arrives): rank 1
    STALLS at the publish fault point, so no transport error ever fires —
    only the agreement watchdog can save rank 0. It must dump the
    per-host phase report and exit with the deadline PeerFailure."""
    ckpt = tmp_path / "ckpts"
    port = free_port()
    env = _child_env()
    env["TPUMNIST_AGREEMENT_TIMEOUT"] = _DEADLINE
    env["TPUMNIST_FAULT"] = "ckpt_publish:1:stall:600"
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), "2", str(port),
             str(ckpt)] + _Z1,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=_REPO,
        )
        for rank in range(2)
    ]
    try:
        out0, _ = procs[0].communicate(timeout=180)
    finally:
        for p in procs:  # rank 1 is stalled by design: shoot it
            if p.poll() is None:
                p.kill()
    procs[1].communicate()
    assert procs[0].returncode not in (0, None), out0[-3000:]
    assert "supervision watchdog report" in out0
    assert "blocked in: agreement 'ckpt_publish'" in out0
    assert "PeerFailure" in out0 and "timed out" in out0
    assert "[1]" in out0


@pytest.mark.slow
def test_kill_during_sharded_write_drops_unpublished_tmp(tmp_path):
    """Rank 1 dies inside the shard-file write: the epoch must end
    UNPUBLISHED on every host (tmp dir only — a half-written directory
    must never become ``latest_checkpoint``), and the next run must
    clean the stale tmp and publish normally."""
    ckpt = tmp_path / "ckpts"
    results = _spawn(ckpt, _Z1, fault="ckpt_write:1:kill")
    (rc0, out0), (rc1, out1) = results
    assert rc1 == -9, out1[-2000:]
    assert rc0 not in (0, None), out0[-2000:]
    assert "PeerFailure" in out0
    names = set(os.listdir(ckpt))
    assert "checkpoint_0.ckpt" not in names
    assert "checkpoint_0.ckpt.tmp" in names  # evidence, not a checkpoint

    # Same command line, healthy world: --resume auto finds NO published
    # checkpoint (the tmp is invisible to resolution), trains fresh,
    # cleans the stale tmp, and publishes.
    results = _spawn(ckpt, _Z1 + ["--resume", "auto"])
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"recovery rank {rank} failed:\n{out[-3000:]}"
    assert _summary(results[0][1])["start_epoch"] == 0
    names = set(os.listdir(ckpt))
    assert "checkpoint_0.ckpt" in names
    assert "checkpoint_0.ckpt.tmp" not in names


@pytest.mark.slow
def test_midepoch_kill_every_host_then_resume_auto(tmp_path):
    """The preemption case at 2-process scale: every host is SIGKILLed
    mid-epoch-1 (after epoch 0's checkpoint landed). The relaunch with
    the SAME command line resumes at epoch 1 and finishes — at most the
    unpublished epoch is lost."""
    ckpt = tmp_path / "ckpts"
    flags = ["--epochs", "3", "--resume", "auto"]
    results = _spawn(ckpt, flags, fault="train_epoch:*:kill:1")
    for rank, (rc, out) in enumerate(results):
        assert rc == -9, f"rank {rank} should have been killed:\n{out[-2000:]}"
    assert "checkpoint_0.npz" in os.listdir(ckpt)

    results = _spawn(ckpt, flags)
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"resumed rank {rank} failed:\n{out[-3000:]}"
    for rc, out in results:
        s = _summary(out)
        assert s["start_epoch"] == 1 and s["epochs_run"] == 2
    assert {"checkpoint_0.npz", "checkpoint_1.npz",
            "checkpoint_2.npz"} <= set(os.listdir(ckpt))


@pytest.mark.slow
def test_hostlocal_raise_delivers_poison_pill(tmp_path):
    """The agreed-exit protocol proper (no kill involved): rank 1 raises
    a host-local exception at the ``resume`` fault point. Its poison
    pill pairs with rank 0's resume-resolution collective, so rank 0
    unwinds with the failure attributed to host 1 and its phase —
    before this protocol, rank 0 blocked in that collective forever."""
    ckpt = tmp_path / "ckpts"
    results = _spawn(ckpt, ["--resume", "auto"], fault="resume:1:raise")
    (rc0, out0), (rc1, out1) = results
    assert rc1 not in (0, None), out1[-2000:]
    assert "InjectedFault" in out1
    assert "delivering poison pill" in out1
    assert rc0 not in (0, None), out0[-2000:]
    assert "PeerFailure" in out0
    assert "died on a host-local error" in out0
    assert "[1]" in out0 and "'resume'" in out0
