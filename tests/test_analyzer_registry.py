"""Fixture suite: registry-drift (fault points) and marker-registry."""


import pytest


from tools.analyzer import analyze_snippet  # noqa: E402
from tools.analyzer.checkers import marker_registry  # noqa: E402
from tools.analyzer.core import Module  # noqa: E402

pytestmark = pytest.mark.lint


def _drift(src):
    return analyze_snippet(src, checkers=["registry-drift"])


# -- fault-point drift: firing ----------------------------------------------


def test_fires_on_unregistered_hook():
    src = """
FAULT_POINTS = {"ckpt_write": "shard file IO"}

def save():
    maybe_fault("ckpt_write")

def publish():
    maybe_fault("ckpt_publish")
"""
    (f,) = _drift(src)
    assert "ckpt_publish" in f.message and "not in FAULT_POINTS" in f.message


def test_fires_on_unreachable_registry_entry():
    src = """
FAULT_POINTS = {
    "ckpt_write": "shard file IO",
    "resume": "cli resume entry",
}

def save():
    maybe_fault("ckpt_write")
"""
    (f,) = _drift(src)
    assert "'resume'" in f.message and "no" in f.message
    assert f.line == 4  # points at the registry key itself


def test_fires_on_computed_point_name():
    src = """
FAULT_POINTS = {"a": "x"}

def f(which):
    maybe_fault("a")
    maybe_fault(f"ckpt_{which}")
"""
    (f,) = _drift(src)
    assert "string literal" in f.message


# -- fault-point drift: non-firing -------------------------------------------


def test_silent_when_registry_and_hooks_agree():
    src = """
FAULT_POINTS = {"a": "x", "b": "y"}

def f():
    maybe_fault("a")

def g():
    maybe_fault("b")
"""
    assert _drift(src) == []


def test_silent_on_hooks_without_a_registry_in_view():
    """Analyzing a lone hook-bearing file must not invent drift — the
    registry module simply isn't in the analyzed set."""
    src = """
def save():
    maybe_fault("ckpt_write")
"""
    assert _drift(src) == []


# -- marker registry ---------------------------------------------------------


def _marker_findings(src, registered):
    import ast

    module = Module(path="test_x.py", tree=ast.parse(src), source=src)
    return marker_registry.check_usage(module, registered)


def test_marker_fires_on_unregistered_and_misspelled():
    src = """
import pytest

@pytest.mark.serv
def test_a():
    pass

pytestmark = pytest.mark.choas
"""
    findings = _marker_findings(src, {"serve", "chaos", "slow"})
    assert {f.symbol for f in findings} == {"serv", "choas"}


def test_marker_silent_on_registered_and_builtin():
    src = """
import pytest

@pytest.mark.slow
@pytest.mark.parametrize("x", [1, 2])
def test_a(x):
    pass

pytestmark = pytest.mark.serve
"""
    assert _marker_findings(src, {"serve", "chaos", "slow"}) == []


def test_registered_markers_parser_matches_known_pyproject():
    text = (
        'markers = [\n'
        '    "slow: spawns subprocesses",\n'
        '    "serve: serving subsystem",\n'
        '    "zero3(tol): sharded-optimizer tolerance",\n'
        '    "flaky",\n'  # pytest accepts a description-less marker
        ']\n'
    )
    assert marker_registry.registered_markers(text) == {
        "slow", "serve", "zero3", "flaky"}
    assert marker_registry.registered_markers("nothing here") == set()
