"""tools/bench_kernels.py rot guard: the MXU-bound kernel benchmark must
always produce its JSON (the watcher runs it unattended the moment the
chip answers — a bitrotted tool would silently burn that rare window).
Perf numbers are meaningless on CPU; only the harness contract is pinned.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_kernels_quick_emits_json():
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_kernels.py"),
         "--quick", "--reps", "1", "--iters", "1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"] == "pallas_kernel_vs_xla"
    assert "attention_error" not in out, out
    assert "adam_error" not in out, out
    rows = out["attention_fwd_bwd"]
    assert len(rows) == 2 and all(r["flash_ms"] > 0 for r in rows)
    assert out["adam_update"]["n_params"] > 0


@pytest.mark.slow
def test_bench_kernels_impossible_mfu_fails_loudly():
    """Round-4 guard: a measurement faster than the chip's peak FLOPs
    (sync failure — how round 3's kernels.json went bad) must exit
    nonzero, stamp "invalid", and NOT carry the "sync": "host_read"
    validity marker. Peak is faked to 1 FLOP/s so any real timing
    violates it."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="",
               BENCH_FAKE_PEAK_FLOPS="1.0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_kernels.py"),
         "--quick", "--reps", "1", "--iters", "1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 1, (proc.stdout, proc.stderr[-2000:])
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    out = json.loads(line)
    assert "impossible" in out["invalid"]
    assert "sync" not in out


@pytest.mark.slow
def test_bench_kernels_adam_hbm_guard_fails_loudly():
    """Same contract for the HBM-bandwidth bound on the (attention-MFU-
    blind) Adam rows: faked 1 byte/s bandwidth makes any timing
    impossible."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="",
               BENCH_FAKE_HBM_BW="1.0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_kernels.py"),
         "--quick", "--reps", "1", "--iters", "1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 1, (proc.stdout, proc.stderr[-2000:])
    out = json.loads([l for l in proc.stdout.splitlines()
                      if l.strip().startswith("{")][-1])
    assert "impossible adam" in out["invalid"]
    assert "sync" not in out


@pytest.mark.slow
def test_sweep_flash_impossible_mfu_fails_loudly():
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="",
               BENCH_FAKE_PEAK_FLOPS="1.0")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sweep_flash.py"),
         "--quick", "--reps", "1", "--iters", "1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 1, (proc.stdout, proc.stderr[-2000:])
    out = json.loads([l for l in proc.stdout.splitlines()
                      if l.strip().startswith("{")][-1])
    assert "impossible" in out["invalid"]
    assert "sync" not in out


@pytest.mark.slow
def test_sweep_flash_quick_emits_json():
    """Same rot guard for the flash block-size sweep: the follow-up
    watcher runs it unattended in a rare chip-recovery window, and it
    imports across modules by path hack (bench.configure_jax,
    bench_kernels._timeit) — drift there must fail here, not there."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "sweep_flash.py"),
         "--quick", "--reps", "1", "--iters", "1"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"] == "flash_block_sweep_fwd_bwd"
    (row,) = out["rows"]
    assert row["dense_ms"] > 0 and row["flash_b32_ms"] > 0
    assert "flash_b32_speedup" in row
