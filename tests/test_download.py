"""Dataset downloader, tested offline through a file:// mirror.

The reference's acquisition path is ``datasets.MNIST(root, download=True)``
(``/root/reference/multi_proc_single_gpu.py:137-138``); this suite proves the
first-party equivalent end to end without egress: a local directory of
gzipped IDX files served via ``file://`` plays the role of the HTTP mirror.
"""

import gzip
import hashlib
import os

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.download import (
    dataset_present,
    download_dataset,
)
from pytorch_distributed_mnist_tpu.data.mnist import (
    load_dataset,
    synthetic_dataset,
    write_idx,
)

_GZ = (
    "train-images-idx3-ubyte.gz",
    "train-labels-idx1-ubyte.gz",
    "t10k-images-idx3-ubyte.gz",
    "t10k-labels-idx1-ubyte.gz",
)


@pytest.fixture()
def mirror(tmp_path):
    """A file:// mirror of tiny-but-real gzipped IDX files + their md5s."""
    mdir = tmp_path / "mirror"
    mdir.mkdir()
    imgs, labels = synthetic_dataset(32, seed=7)
    t_imgs, t_labels = synthetic_dataset(16, seed=8)
    payload = {
        "train-images-idx3-ubyte.gz": imgs,
        "train-labels-idx1-ubyte.gz": labels,
        "t10k-images-idx3-ubyte.gz": t_imgs,
        "t10k-labels-idx1-ubyte.gz": t_labels,
    }
    checksums = {}
    for name, arr in payload.items():
        raw = str(mdir / name[: -len(".gz")])
        write_idx(raw, arr)
        with open(raw, "rb") as f:
            data = f.read()
        gz = gzip.compress(data)
        (mdir / name).write_bytes(gz)
        os.remove(raw)
        checksums[name] = hashlib.md5(gz).hexdigest()
    return {"url": mdir.as_uri(), "checksums": checksums,
            "expected": {"train_n": 32, "test_n": 16}}


def test_download_fetches_and_verifies(tmp_path, mirror):
    root = str(tmp_path / "data")
    d = download_dataset(root, "mnist", mirrors=[mirror["url"]],
                         checksums=mirror["checksums"])
    assert dataset_present(d)
    # The full loader path reads what was downloaded (gzip IDX).
    images, labels = load_dataset(root, "mnist", train=True,
                                  synthesize_if_missing=False)
    assert images.shape == (32, 28, 28)
    assert labels.shape == (32,)
    images, _ = load_dataset(root, "mnist", train=False,
                             synthesize_if_missing=False)
    assert images.shape == (16, 28, 28)


def test_download_idempotent(tmp_path, mirror):
    root = str(tmp_path / "data")
    d = download_dataset(root, "mnist", mirrors=[mirror["url"]],
                         checksums=mirror["checksums"])
    mtimes = {f: os.path.getmtime(os.path.join(d, f)) for f in _GZ}
    download_dataset(root, "mnist", mirrors=[mirror["url"]],
                     checksums=mirror["checksums"])
    assert mtimes == {f: os.path.getmtime(os.path.join(d, f)) for f in _GZ}


def test_download_checksum_mismatch_raises(tmp_path, mirror):
    root = str(tmp_path / "data")
    bad = dict(mirror["checksums"])
    bad["train-images-idx3-ubyte.gz"] = "0" * 32
    with pytest.raises(OSError, match="checksum mismatch"):
        download_dataset(root, "mnist", mirrors=[mirror["url"]], checksums=bad)
    # The corrupt file must not have been left behind.
    assert not os.path.isfile(
        os.path.join(root, "mnist", "train-images-idx3-ubyte.gz")
    )


def test_download_repairs_corrupt_file(tmp_path, mirror):
    root = str(tmp_path / "data")
    d = os.path.join(root, "mnist")
    os.makedirs(d)
    target = os.path.join(d, "train-images-idx3-ubyte.gz")
    with open(target, "wb") as f:
        f.write(b"truncated garbage")
    download_dataset(root, "mnist", mirrors=[mirror["url"]],
                     checksums=mirror["checksums"])
    assert hashlib.md5(open(target, "rb").read()).hexdigest() == (
        mirror["checksums"]["train-images-idx3-ubyte.gz"]
    )


def test_download_no_checksum_sanity_gate(tmp_path, mirror):
    """Without pinned checksums the gunzip-IDX-magic gate still rejects junk."""
    mdir = tmp_path / "junk_mirror"
    mdir.mkdir()
    for name in _GZ:
        (mdir / name).write_bytes(gzip.compress(b"<html>not found</html>"))
    with pytest.raises(OSError, match="not a gzipped IDX"):
        download_dataset(str(tmp_path / "data2"), "mnist",
                         mirrors=[mdir.as_uri()], checksums={})


def test_download_nonzero_process_is_noop(tmp_path, mirror):
    root = str(tmp_path / "data")
    download_dataset(root, "mnist", mirrors=[mirror["url"]],
                     checksums=mirror["checksums"], process_index=1)
    assert not dataset_present(os.path.join(root, "mnist"))


def test_load_dataset_download_flag(tmp_path, mirror, monkeypatch):
    """load_dataset(download=True) pulls from the mirror list when absent."""
    import pytorch_distributed_mnist_tpu.data.download as dl

    monkeypatch.setitem(dl.MIRRORS, "mnist", (mirror["url"],))
    monkeypatch.setitem(dl.CHECKSUMS, "mnist", mirror["checksums"])
    root = str(tmp_path / "data")
    images, labels = load_dataset(root, "mnist", train=True,
                                  synthesize_if_missing=False, download=True)
    assert images.shape == (32, 28, 28)
    # Second call takes the already-present fast path.
    images2, _ = load_dataset(root, "mnist", train=True,
                              synthesize_if_missing=False, download=True)
    np.testing.assert_array_equal(images, images2)


def test_download_unreachable_mirror_raises(tmp_path):
    with pytest.raises(OSError):
        download_dataset(str(tmp_path / "data"), "mnist",
                         mirrors=[(tmp_path / "missing").as_uri()],
                         checksums={})


def test_fetch_retries_flaky_server(tmp_path, mirror, monkeypatch):
    """Run-supervision satellite: one mirror used to get exactly one shot
    per file. A flaky server — connection reset on the first attempt, a
    TRUNCATED body on the second (which publishes a file that only the
    per-attempt re-verification can reject), good bytes on the third —
    must be survived by the bounded backoff retry inside _fetch_verified,
    without ever falling through to the next mirror or the caller."""
    import io
    import time as _time
    import urllib.parse
    import urllib.request

    from pytorch_distributed_mnist_tpu.utils.profiling import failure_events

    mdir = urllib.parse.urlparse(mirror["url"]).path
    good = {name: open(os.path.join(mdir, name), "rb").read()
            for name in _GZ}

    class _Resp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    per_url = {}

    def flaky_urlopen(url, timeout=None):
        name = url.rsplit("/", 1)[1]
        n = per_url[name] = per_url.get(name, 0) + 1
        if n == 1:
            raise urllib.error.URLError("connection reset (fake)")
        if n == 2:
            return _Resp(good[name][: len(good[name]) // 2])  # truncated
        return _Resp(good[name])

    delays = []
    monkeypatch.setattr(urllib.request, "urlopen", flaky_urlopen)
    monkeypatch.setattr(_time, "sleep", delays.append)
    failure_events.reset()
    root = str(tmp_path / "data")
    d = download_dataset(root, "mnist", mirrors=["http://fake.test/m"],
                         checksums=mirror["checksums"])
    assert dataset_present(d)
    # Every file needed exactly 3 attempts, each retry backed off, and
    # the near-misses are visible in the failure-event log.
    assert all(n == 3 for n in per_url.values())
    assert len(delays) == 2 * len(_GZ)
    assert all(dl >= 0.5 for dl in delays)
    kinds = [e["kind"] for e in failure_events.snapshot()]
    assert kinds.count("download_retry") == 2 * len(_GZ)
    # The verified files actually load.
    images, _ = load_dataset(root, "mnist", train=True,
                             synthesize_if_missing=False)
    assert images.shape == (32, 28, 28)


def test_fetch_retries_exhausted_tries_next_mirror(tmp_path, mirror,
                                                   monkeypatch):
    """A mirror that stays bad for all attempts is given up on, and the
    next mirror serves the file — retries nest INSIDE the mirror loop."""
    import io
    import time as _time
    import urllib.parse
    import urllib.request

    mdir = urllib.parse.urlparse(mirror["url"]).path
    good = {name: open(os.path.join(mdir, name), "rb").read()
            for name in _GZ}

    class _Resp(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    calls = {"bad": 0, "good": 0}

    def urlopen(url, timeout=None):
        if url.startswith("http://bad.test"):
            calls["bad"] += 1
            raise urllib.error.URLError("down (fake)")
        calls["good"] += 1
        return _Resp(good[url.rsplit("/", 1)[1]])

    monkeypatch.setattr(urllib.request, "urlopen", urlopen)
    monkeypatch.setattr(_time, "sleep", lambda _d: None)
    d = download_dataset(str(tmp_path / "data"), "mnist",
                         mirrors=["http://bad.test/m", "http://good.test/m"],
                         checksums=mirror["checksums"], attempts=2)
    assert dataset_present(d)
    assert calls["bad"] == 2 * len(_GZ)  # attempts per file, then moved on
    assert calls["good"] == len(_GZ)
