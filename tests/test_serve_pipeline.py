"""Pipelined MicroBatcher: the form/dispatch + completion stage split,
the bounded in-flight window, error delivery from both stages, and
drain-on-close with batches in flight. All with stub dispatch/complete
callables — no device required."""

import threading
import time

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher

pytestmark = pytest.mark.serve


def _rows(n, base=0.0):
    return (np.arange(n, dtype=np.float32) + base).reshape(n, 1)


class GatedPipe:
    """dispatch records and passes through; complete blocks until
    released — the stub device whose executions never finish until the
    test says so."""

    def __init__(self):
        self.lock = threading.Lock()
        self.dispatched = []
        self.completed = []
        self.release = threading.Event()

    def dispatch(self, images):
        with self.lock:
            self.dispatched.append(images.shape[0])
        return images

    def complete(self, handle):
        assert self.release.wait(30.0), "test deadlock"
        with self.lock:
            self.completed.append(handle.shape[0])
        return handle

    def dispatch_count(self):
        with self.lock:
            return len(self.dispatched)


def test_window_bounds_inflight_dispatch():
    """With completion wedged, dispatch runs exactly ``max_inflight``
    batches ahead and then stalls; releasing completion lets the rest
    through and every request gets its own rows back."""
    pipe = GatedPipe()
    with MicroBatcher(None, max_batch=1, max_wait_s=0.001,
                      dispatch_fn=pipe.dispatch, complete_fn=pipe.complete,
                      max_inflight=3) as b:
        pendings = [b.submit(_rows(1, base=i)) for i in range(6)]
        deadline = time.time() + 10.0
        while pipe.dispatch_count() < 3 and time.time() < deadline:
            time.sleep(0.01)
        assert pipe.dispatch_count() == 3  # the window, no further
        time.sleep(0.15)  # would overrun here if the window leaked
        assert pipe.dispatch_count() == 3
        pipe.release.set()
        for i, p in enumerate(pendings):
            np.testing.assert_array_equal(b.result(p, timeout=10.0),
                                          _rows(1, base=i))
    assert pipe.dispatched == [1] * 6 and pipe.completed == [1] * 6


def test_window_one_is_strict_alternation():
    """max_inflight=1 (the default, and the single-device server): batch
    N+1 is NOT dispatched until batch N completed — the pre-pipelining
    serialization, pinned."""
    pipe = GatedPipe()
    with MicroBatcher(None, max_batch=1, max_wait_s=0.001,
                      dispatch_fn=pipe.dispatch, complete_fn=pipe.complete,
                      max_inflight=1) as b:
        pendings = [b.submit(_rows(1, base=i)) for i in range(3)]
        deadline = time.time() + 10.0
        while pipe.dispatch_count() < 1 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.15)
        assert pipe.dispatch_count() == 1  # strictly one in flight
        pipe.release.set()
        for i, p in enumerate(pendings):
            np.testing.assert_array_equal(b.result(p, timeout=10.0),
                                          _rows(1, base=i))


def test_results_map_back_across_inflight_batches():
    """Multiple batches in flight at once: each request's slice still
    comes back exact (the completion stage owns the slice bookkeeping)."""
    with MicroBatcher(None, max_batch=4, max_wait_s=0.001,
                      dispatch_fn=lambda x: x * 10.0,
                      complete_fn=lambda h: h,
                      max_inflight=4) as b:
        pendings = [b.submit(_rows(3, base=100 * i)) for i in range(8)]
        for i, p in enumerate(pendings):
            np.testing.assert_array_equal(b.result(p, timeout=10.0),
                                          _rows(3, base=100 * i) * 10.0)


def test_dispatch_error_delivered_to_riders():
    def boom(images):
        raise RuntimeError("staging on fire")

    with MicroBatcher(None, max_batch=8, max_wait_s=0.01,
                      dispatch_fn=boom, complete_fn=lambda h: h,
                      max_inflight=2) as b:
        pa, pb = b.submit(_rows(1)), b.submit(_rows(1))
        for p in (pa, pb):
            with pytest.raises(RuntimeError, match="staging on fire"):
                b.result(p, timeout=10.0)


def test_complete_error_delivered_to_riders():
    def boom(handle):
        raise RuntimeError("fetch on fire")

    with MicroBatcher(None, max_batch=8, max_wait_s=0.01,
                      dispatch_fn=lambda x: x, complete_fn=boom,
                      max_inflight=2) as b:
        pa, pb = b.submit(_rows(1)), b.submit(_rows(1))
        for p in (pa, pb):
            with pytest.raises(RuntimeError, match="fetch on fire"):
                b.result(p, timeout=10.0)


def test_error_batch_does_not_wedge_the_window():
    """A window=1 batcher keeps serving after a failed batch (the window
    slot is released on the error path too)."""
    calls = {"n": 0}

    def flaky(images):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("first batch dies")
        return images

    with MicroBatcher(None, max_batch=1, max_wait_s=0.001,
                      dispatch_fn=flaky, complete_fn=lambda h: h,
                      max_inflight=1) as b:
        bad = b.submit(_rows(1, base=9))
        with pytest.raises(RuntimeError, match="first batch dies"):
            b.result(bad, timeout=10.0)
        good = b.submit(_rows(1, base=5))
        np.testing.assert_array_equal(b.result(good, timeout=10.0),
                                      _rows(1, base=5))


def test_mismatched_cobatch_is_an_error_not_a_dead_worker():
    """Two co-batched requests whose trailing shapes disagree (submit
    validates only the leading dim) must fail as per-request errors —
    and close() must still return (the dispatch worker survives, and
    even a dying worker hands completion its shutdown sentinel)."""
    with MicroBatcher(None, max_batch=8, max_wait_s=0.05,
                      dispatch_fn=lambda x: x, complete_fn=lambda h: h,
                      max_inflight=2) as b:
        pa = b.submit(np.zeros((1, 4), np.float32))
        pb = b.submit(np.zeros((1, 5), np.float32))
        for p in (pa, pb):
            with pytest.raises(ValueError):
                b.result(p, timeout=10.0)
        # The worker is alive: a well-formed request still serves.
        np.testing.assert_array_equal(b.result(b.submit(_rows(2)),
                                               timeout=10.0), _rows(2))
    # reaching here means close() returned (the with-exit join finished)


def test_malformed_completion_is_an_error_not_a_dead_worker():
    """A complete_fn returning garbage (scalar, wrong row count) becomes
    a per-request error; the completion worker survives and close()
    returns."""
    returns = iter([np.float32(7.0),            # 0-d: no shape[0] at all
                    np.zeros((9, 1), np.float32)])  # wrong row count

    with MicroBatcher(None, max_batch=1, max_wait_s=0.001,
                      dispatch_fn=lambda x: x,
                      complete_fn=lambda h: next(returns, h),
                      max_inflight=2) as b:
        with pytest.raises(RuntimeError, match="scalar"):
            b.result(b.submit(_rows(1)), timeout=10.0)
        with pytest.raises(RuntimeError, match="9 row"):
            b.result(b.submit(_rows(1)), timeout=10.0)
        # Worker alive: a well-formed request still serves.
        np.testing.assert_array_equal(
            b.result(b.submit(_rows(1, base=3)), timeout=10.0),
            _rows(1, base=3))


def test_close_drains_queued_and_inflight():
    """close() completes everything: batches already past dispatch AND
    requests still queued behind them."""
    pipe = GatedPipe()
    b = MicroBatcher(None, max_batch=1, max_wait_s=5.0,
                     dispatch_fn=pipe.dispatch, complete_fn=pipe.complete,
                     max_inflight=2).start()
    pendings = [b.submit(_rows(1, base=i)) for i in range(5)]
    deadline = time.time() + 10.0
    while pipe.dispatch_count() < 2 and time.time() < deadline:
        time.sleep(0.01)
    closer = threading.Thread(target=b.close, daemon=True)
    closer.start()
    pipe.release.set()
    closer.join(30.0)
    assert not closer.is_alive()
    for i, p in enumerate(pendings):
        np.testing.assert_array_equal(b.result(p, timeout=1.0),
                                      _rows(1, base=i))
    with pytest.raises(RuntimeError, match="shut down"):
        b.submit(_rows(1))


def test_completion_keeps_request_accounting():
    """Latency/queue-wait accounting rides the completion stage: counts
    and quantiles behave exactly as in the synchronous batcher."""
    from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog

    log = ServeLog()
    with MicroBatcher(None, max_batch=4, max_wait_s=0.001,
                      dispatch_fn=lambda x: x, complete_fn=lambda h: h,
                      max_inflight=3, serve_log=log) as b:
        for i in range(6):
            b.predict(_rows(2, base=i), timeout=10.0)
    snap = log.snapshot()
    assert snap["requests"] == 6 and snap["images"] == 12
    assert snap["latency_ms"]["count"] == 6
    assert snap["queue_wait_ms"]["p50"] <= snap["latency_ms"]["p50"] + 1e-6


@pytest.mark.parametrize("kwargs", [
    dict(max_inflight=0),
    dict(dispatch_fn=lambda x: x),                  # missing complete_fn
    dict(),                                         # no inference at all
])
def test_constructor_validation(kwargs):
    base = dict(infer_fn=None, max_batch=4)
    if "dispatch_fn" not in kwargs and "max_inflight" not in kwargs:
        pass  # neither form given
    elif "max_inflight" in kwargs:
        base.update(dispatch_fn=lambda x: x, complete_fn=lambda h: h)
    base.update(kwargs)
    with pytest.raises(ValueError):
        MicroBatcher(**base)


def test_infer_fn_and_two_phase_are_exclusive():
    with pytest.raises(ValueError, match="exactly one"):
        MicroBatcher(lambda x: x, max_batch=4,
                     dispatch_fn=lambda x: x, complete_fn=lambda h: h)
