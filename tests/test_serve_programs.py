"""Sharded serving (serve/programs.py): the forward-program registry,
mesh-group placement, exactness pins against the single-device forward
(including under live hot-reload and exact-bucket padding), per
bucket x mode zero-recompile invariants, the checkpoint parallel-layout
gate at boot and reload, and the analyzer cleanliness of the new
module."""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.models.registry import model_field_default
from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.pool import EnginePool
from pytorch_distributed_mnist_tpu.serve.programs import (
    SERVE_MODES,
    build_group_placements,
    build_placement,
    check_checkpoint_layout,
    register_serve_mode,
    servable_modes,
    validate_serve_mode,
)
from pytorch_distributed_mnist_tpu.serve.reload import CheckpointWatcher
from pytorch_distributed_mnist_tpu.train.checkpoint import (
    checkpoint_parallel_layout,
    save_checkpoint,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import ServeLog, compile_log

pytestmark = pytest.mark.serve

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def vit_setup():
    model = get_model("vit", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    images, _ = synthetic_dataset(32, seed=3)
    return model, state, images


@pytest.fixture(scope="module")
def moe_setup():
    model = get_model("moe_mlp", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(1))
    images, _ = synthetic_dataset(32, seed=4)
    return model, state, images


def _direct_labels(model, state, raw_images):
    logits = model.apply(state.params, jnp.asarray(
        normalize_images(raw_images)), train=False)
    return np.argmax(np.asarray(logits), axis=-1)


# -- the registry ------------------------------------------------------------


def test_servable_modes_per_model():
    assert servable_modes("vit") == ["replicated", "pipeline", "tensor"]
    assert servable_modes("moe_mlp") == ["replicated", "expert"]
    assert servable_modes("cnn") == ["replicated"]
    assert SERVE_MODES == ["replicated", "expert", "pipeline", "tensor"]


def test_unservable_model_rejected_with_modes_named(vit_setup):
    with pytest.raises(ValueError, match=r"no sharding rule table.*cnn"):
        validate_serve_mode("tensor", "cnn", 2)
    with pytest.raises(ValueError,
                       match=r"\['replicated', 'pipeline', 'tensor'\]"):
        validate_serve_mode("expert", "vit", 2)
    with pytest.raises(ValueError, match="unknown serve mode"):
        validate_serve_mode("ring", "vit", 2)


def test_non_dividing_weight_dim_rejected(vit_setup):
    _, state, _ = vit_setup
    # The ViT's sharded dims are 64/192/256-sized: 7 divides none; the
    # rejection names the leaf, the dim, and the fix.
    with pytest.raises(ValueError, match=r"param .* dim .* does not"):
        validate_serve_mode("tensor", "vit", 7, state.params)
    # A dividing mesh passes.
    validate_serve_mode("tensor", "vit", 2, state.params)


def test_replicated_needs_no_mesh():
    validate_serve_mode("replicated", "cnn", 1)
    with pytest.raises(ValueError, match="sharded mode"):
        validate_serve_mode("replicated", "cnn", 2)


def test_register_serve_mode_rejects_duplicates():
    with pytest.raises(ValueError, match="already registered"):
        register_serve_mode("tensor", "model", {})
    with pytest.raises(ValueError, match="already registered"):
        register_serve_mode("replicated", "x", {})


def test_model_field_default_registry_helper():
    assert model_field_default("vit", "num_heads") == 4
    assert model_field_default("moe_mlp", "num_experts") == 8
    with pytest.raises(ValueError, match="no field"):
        model_field_default("vit", "not_a_field")


def test_group_partition_names_and_spans(moe_setup):
    _, state, _ = moe_setup
    devices = jax.local_devices()
    groups = build_group_placements("expert", "moe_mlp", devices[:8], 4,
                                    state.params)
    assert [g.name for g in groups] == ["expert.g0", "expert.g1"]
    spans = [set(map(str, g.devices)) for g in groups]
    assert len(spans[0]) == 4 and len(spans[1]) == 4
    assert spans[0].isdisjoint(spans[1])
    # One group spanning everything gets the bare @{mode} name.
    (single,) = build_group_placements("expert", "moe_mlp", devices[:8],
                                       8, state.params)
    assert single.name == "expert" and len(single.devices) == 8
    with pytest.raises(ValueError, match="partition"):
        build_group_placements("expert", "moe_mlp", devices[:3], 2,
                               state.params)


# -- exactness: sharded logits == single-device forward ----------------------


@pytest.mark.parametrize("model_name,mode,mesh", [
    ("vit", "tensor", 2),
    ("moe_mlp", "expert", 4),
])
def test_sharded_logits_match_single_device(model_name, mode, mesh,
                                            vit_setup, moe_setup):
    model, state, images = vit_setup if model_name == "vit" else moe_setup
    base = InferenceEngine(model.apply, state.params, buckets=(8,))
    base.warmup()
    placement = build_placement(mode, model_name,
                                jax.local_devices()[:mesh], state.params)
    eng = InferenceEngine(model.apply, state.params, buckets=(8,),
                          placement=placement, name=placement.name)
    eng.warmup()
    ref, _ = base.logits_with_epoch(images[:8])
    got, _ = eng.logits_with_epoch(images[:8])
    # The mesh program reassociates the partial-sum reductions, so the
    # cross-plane pin is allclose (tight), with argmax identical.
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.argmax(got, -1), np.argmax(ref, -1))
    # Padded (5 -> bucket 8) rows match the single-device forward too.
    ref5, _ = base.logits_with_epoch(images[:5])
    got5, _ = eng.logits_with_epoch(images[:5])
    np.testing.assert_allclose(got5, ref5, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("model_name,mode", [("vit", "tensor"),
                                             ("moe_mlp", "expert")])
def test_exact_bucket_vs_staged_path_bitwise_on_mesh(model_name, mode,
                                                     vit_setup, moe_setup):
    """On the SHARDED plane, the exact-fit no-copy fast path and the
    padded staging path feed the device identical bytes: an 8-row f32
    C-contiguous batch (no copy) and a non-contiguous view of the same
    rows (forced through the staging buffer) produce BITWISE-equal
    logits."""
    model, state, images = vit_setup if model_name == "vit" else moe_setup
    placement = build_placement(mode, model_name, jax.local_devices()[:2],
                                state.params)
    eng = InferenceEngine(model.apply, state.params, buckets=(8,),
                          placement=placement, name=placement.name)
    eng.warmup()
    exact = normalize_images(images[:8])
    assert exact.dtype == np.float32 and exact.flags["C_CONTIGUOUS"]
    staged_src = np.asfortranarray(exact)  # same values, staging path
    assert not staged_src.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(eng.logits(exact),
                                  eng.logits(staged_src))


@pytest.mark.parametrize("model_name,mode", [("vit", "tensor"),
                                             ("moe_mlp", "expert")])
def test_zero_steady_state_recompiles_per_bucket_and_mode(
        model_name, mode, vit_setup, moe_setup):
    model, state, images = vit_setup if model_name == "vit" else moe_setup
    placement = build_placement(mode, model_name, jax.local_devices()[:2],
                                state.params)
    eng = InferenceEngine(model.apply, state.params, buckets=(1, 8),
                          placement=placement, name=placement.name)
    eng.warmup()
    programs = compile_log.stats()["programs"]
    expected = {f"serve_forward_b{b}@{mode}" for b in (1, 8)}
    assert expected <= set(programs)
    before = {n: programs[n]["backend_compiles"] for n in expected}
    eng.logits(images[:1])
    eng.logits(images[:8])
    eng.logits(images[:5])  # padded
    eng.logits(images[:20])  # chunked through the top bucket
    after = compile_log.stats()["programs"]
    assert {n: after[n]["backend_compiles"] for n in expected} == before


# -- the pool's mesh groups --------------------------------------------------


def _drive_pool(pool, request_stacks, max_inflight):
    def complete(handle):
        labels, epoch = pool.predict_complete(handle)
        tag = np.full_like(labels, -1 if epoch is None else epoch)
        return np.stack([labels, tag], axis=1)

    results = []
    with MicroBatcher(None, max_batch=pool.max_batch, max_wait_s=0.002,
                      dispatch_fn=pool.dispatch, complete_fn=complete,
                      max_inflight=max_inflight) as batcher:
        pendings = [batcher.submit(pool.preprocess(stack))
                    for stack in request_stacks]
        for p in pendings:
            out = batcher.result(p, timeout=60.0)
            results.append((out[:, 0].tolist(), sorted(set(out[:, 1]))))
    return results


def test_sharded_pool_matches_replicated_pool(moe_setup):
    """The mesh-group plane is invisible to clients: the same requests
    through a replicated 4-replica pool and a 2-group expert-sharded
    pool (same 4 chips) produce identical predictions and epochs, both
    matching the direct forward."""
    model, state, images = moe_setup
    stacks = [images[i:i + 1 + (i % 3)] for i in range(16)]
    repl = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:4], buckets=(1, 4, 8),
                      params_epoch=2)
    repl.warmup()
    shard = EnginePool(model.apply, state.params,
                       devices=jax.local_devices()[:4], buckets=(1, 4, 8),
                       params_epoch=2, serve_mode="expert", mesh_size=2,
                       model_name="moe_mlp")
    assert shard.n_replicas == 2 and shard.n_devices == 4
    shard.warmup()
    got = _drive_pool(shard, stacks, max_inflight=3)
    assert got == _drive_pool(repl, stacks, max_inflight=5)
    for stack, (labels, epochs) in zip(stacks, got):
        assert labels == _direct_labels(model, state, stack).tolist()
        assert epochs == [2]


def test_sharded_pool_snapshot_and_least_loaded_groups(moe_setup):
    model, state, images = moe_setup
    log = ServeLog()
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:4], buckets=(4,),
                      serve_log=log, serve_mode="expert", mesh_size=2,
                      model_name="moe_mlp")
    pool.warmup()
    handles = [pool.dispatch(pool.preprocess(images[i:i + 2]))
               for i in range(2)]
    assert sorted(h.replica.name for h in handles) \
        == ["expert.g0", "expert.g1"]
    snap = pool.snapshot()
    assert sorted(snap) == ["expert.g0", "expert.g1"]
    for row in snap.values():
        assert row["mode"] == "expert" and len(row["devices"]) == 2
        assert row["pending"] == 1
    for h in handles:
        pool.complete(h)
    assert all(r["pending"] == 0 for r in pool.snapshot().values())


def test_pool_sharded_requires_model_name_and_mesh_fit(moe_setup):
    model, state, _ = moe_setup
    with pytest.raises(ValueError, match="model_name"):
        EnginePool(model.apply, state.params,
                   devices=jax.local_devices()[:4], serve_mode="expert",
                   mesh_size=2)
    with pytest.raises(ValueError, match="sharded serve_mode"):
        EnginePool(model.apply, state.params,
                   devices=jax.local_devices()[:4], mesh_size=2)


def test_hot_reload_no_mixed_epochs_on_sharded_pool(moe_setup):
    """The no-mixed-epoch-within-a-batch guarantee survives the sharded
    plane: hammer requests through a 2-group expert pool while params
    hot-swap; every reply carries exactly one installed epoch, and the
    final swap serves everywhere with logits pinned to the direct
    forward."""
    model, state, images = moe_setup
    states = {e: create_train_state(model, jax.random.key(e))
              for e in (10, 11, 12)}
    pool = EnginePool(model.apply, states[10].params,
                      devices=jax.local_devices()[:4], buckets=(1, 8),
                      params_epoch=10, serve_mode="expert", mesh_size=2,
                      model_name="moe_mlp")
    pool.warmup()

    def complete(handle):
        labels, epoch = pool.predict_complete(handle)
        tag = np.full_like(labels, -1 if epoch is None else epoch)
        return np.stack([labels, tag], axis=1)

    failures = []
    stop = threading.Event()

    def hammer(wid):
        i = 0
        while not stop.is_set():
            stack = pool.preprocess(images[(wid + i) % 24:
                                           (wid + i) % 24 + 4])
            out = batcher.predict(stack, timeout=30.0)
            epochs = set(out[:, 1].tolist())
            if len(epochs) != 1 or not epochs <= {10, 11, 12}:
                failures.append(out[:, 1].tolist())
            i += 1

    with MicroBatcher(None, max_batch=8, max_wait_s=0.002,
                      dispatch_fn=pool.dispatch, complete_fn=complete,
                      max_inflight=3) as batcher:
        threads = [threading.Thread(target=hammer, args=(w,), daemon=True)
                   for w in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        for epoch in (11, 12):
            assert pool.swap_params(states[epoch].params, epoch=epoch) == 2
            time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(30.0)
    assert not failures, failures[:5]
    labels, epoch = pool.predict_complete(
        pool.dispatch(pool.preprocess(images[:8])))
    assert epoch == 12
    np.testing.assert_array_equal(
        labels, _direct_labels(model, states[12], images[:8]))


# -- the checkpoint parallel-layout gate -------------------------------------


def test_check_checkpoint_layout_rules():
    check_checkpoint_layout(None, "replicated", "cnn")  # no provenance
    check_checkpoint_layout({"tensor": 1, "expert": 1}, "replicated", "cnn")
    check_checkpoint_layout({"expert": 4}, "expert", "moe_mlp")
    check_checkpoint_layout({"sequence": 4}, "replicated", "vit")  # SP ok
    with pytest.raises(ValueError, match="--serve-mode expert"):
        check_checkpoint_layout({"expert": 4}, "replicated", "moe_mlp")
    with pytest.raises(ValueError, match="--serve-mode tensor"):
        check_checkpoint_layout({"tensor": 2}, "replicated", "vit")
    with pytest.raises(ValueError, match="--serve-mode tensor"):
        check_checkpoint_layout({"tensor": 2}, "expert", "vit")
    # The FLIPPED pipeline arm (ISSUE 12): a pipeline-trained checkpoint
    # names --serve-mode pipeline as the valid choice instead of being
    # rejected by name, and serves under it.
    check_checkpoint_layout({"pipeline": 2}, "pipeline", "vit")
    with pytest.raises(ValueError, match="--serve-mode pipeline"):
        check_checkpoint_layout({"pipeline": 2}, "replicated", "vit")


def test_parallel_layout_round_trips_through_meta(tmp_path, moe_setup):
    model, state, _ = moe_setup
    layout = {"tensor": 1, "sequence": 1, "expert": 4, "pipeline": 1}
    path = save_checkpoint(state, epoch=3, best_acc=0.1, is_best=False,
                           directory=str(tmp_path), process_index=0,
                           parallel_layout=layout)
    assert checkpoint_parallel_layout(path) == layout
    # A stamp-less save reads back None (legacy files, library callers).
    bare = save_checkpoint(state, epoch=4, best_acc=0.1, is_best=False,
                           directory=str(tmp_path), process_index=0)
    assert checkpoint_parallel_layout(bare) is None


def test_watcher_skips_layout_mismatched_reload(tmp_path, moe_setup):
    """A published checkpoint whose recorded layout contradicts the
    serving mode is SKIPPED (recorded as a reload failure, permanent for
    that file); the server keeps serving, and the next layout-clean
    publish loads normally."""
    model, state, images = moe_setup
    template = create_train_state(model, jax.random.key(1))
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:2], buckets=(8,),
                      params_epoch=0)
    pool.warmup()
    log = ServeLog()

    def validate(path):
        check_checkpoint_layout(checkpoint_parallel_layout(path),
                                "replicated", "moe_mlp")

    watcher = CheckpointWatcher(str(tmp_path), template, pool.swap_params,
                                serve_log=log, validate_fn=validate)
    bad = create_train_state(model, jax.random.key(7))
    save_checkpoint(bad, epoch=5, best_acc=0.5, is_best=False,
                    directory=str(tmp_path), process_index=0,
                    parallel_layout={"expert": 4})
    assert not watcher.poll_once()
    assert log.snapshot()["reload_failures"] == 1
    assert [r.engine.params_epoch for r in pool.replicas] == [0, 0]
    # Permanent for the file: the next poll does not retry it.
    assert not watcher.poll_once()
    assert log.snapshot()["reload_failures"] == 1
    good = create_train_state(model, jax.random.key(8))
    save_checkpoint(good, epoch=6, best_acc=0.5, is_best=False,
                    directory=str(tmp_path), process_index=0,
                    parallel_layout={"expert": 1})
    assert watcher.poll_once()
    assert [r.engine.params_epoch for r in pool.replicas] == [6, 6]
    np.testing.assert_array_equal(
        pool.predict_complete(pool.dispatch(
            pool.preprocess(images[:8])))[0],
        _direct_labels(model, good, images[:8]))


# -- analyzer cleanliness ----------------------------------------------------


@pytest.mark.lint
def test_programs_module_clean_under_analyzer():
    """The new sharded-serve module is pinned clean under the four
    checkers its code could plausibly trip: collective symmetry (mesh
    building), trace purity (the pjit-lowered forward), recompile
    hazard (bucket lowering), lock discipline (it owns no locks and
    must not acquire any engine lock around device work)."""
    from tools.analyzer import run_analysis

    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                      "programs.py")],
        checkers=["collective-symmetry", "trace-purity",
                  "recompile-hazard", "lock-discipline"],
        baseline=None)
    assert result.findings == []


# -- serve-mesh slice alignment (PR 13) --------------------------------------


def test_partition_groups_orders_slice_major(monkeypatch):
    """With a slice topology, chips are ordered slice-major before
    chunking: a shuffled device list still yields one-slice groups
    whenever the mesh size fits in a slice."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import DCN_SLICES_ENV
    from pytorch_distributed_mnist_tpu.serve.programs import (
        partition_groups,
    )

    devs = jax.devices()  # ids 0..7
    monkeypatch.setenv(DCN_SLICES_ENV, "2")  # slices: {0..3}, {4..7}
    shuffled = [devs[i] for i in (5, 0, 7, 2, 4, 1, 6, 3)]
    groups = partition_groups(shuffled, 2)
    for group in groups:
        slices = {d.id // 4 for d in group}
        assert len(slices) == 1, [d.id for d in group]
    # Without a topology, the given order is preserved untouched.
    monkeypatch.delenv(DCN_SLICES_ENV)
    groups = partition_groups(shuffled, 2)
    assert [d.id for d in groups[0]] == [5, 0]


def test_pool_topology_flags_slice_straddling_groups(moe_setup,
                                                     monkeypatch):
    """The stats-field warning: a mesh size that cannot fit in a slice
    produces groups spanning slices, and the pool names exactly those
    in ``slice_straddling_groups``; aligned layouts report an empty
    list, and the field vanishes with the topology."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import DCN_SLICES_ENV

    model, state, _ = moe_setup

    def build():
        return EnginePool(model.apply, state.params,
                          devices=jax.local_devices()[:4], buckets=(4,),
                          serve_mode="expert", mesh_size=2,
                          model_name="moe_mlp")

    # 8 emulated slices of 1 chip: every 2-chip group must straddle.
    monkeypatch.setenv(DCN_SLICES_ENV, "8")
    topo = build().topology()
    assert sorted(topo["slice_straddling_groups"]) \
        == ["expert.g0", "expert.g1"]
    # 2 slices of 4: chips 0-3 share slice 0 — aligned, empty list.
    monkeypatch.setenv(DCN_SLICES_ENV, "2")
    topo = build().topology()
    assert topo["slice_straddling_groups"] == []
    # No topology: the field is absent (schema untouched for the
    # single-slice worlds every existing test runs in).
    monkeypatch.delenv(DCN_SLICES_ENV)
    topo = build().topology()
    assert "slice_straddling_groups" not in topo


def test_loadgen_shape_fields_carry_slice_straddling(tmp_path):
    """The loadgen report's shape-field list includes the slice
    warning, so a --smoke report carries it whenever /stats does (the
    field rides the same best-effort copy as the other topology
    fields)."""
    import ast
    import inspect

    import tools.loadgen as loadgen

    src = inspect.getsource(loadgen)
    tree = ast.parse(src)
    consts = {n.value for n in ast.walk(tree)
              if isinstance(n, ast.Constant) and isinstance(n.value, str)}
    assert "slice_straddling_groups" in consts
