"""Tier-1 gate: ruff (general-purpose lint) is clean, when available.

tpumnist-lint (tools/analyzer) owns the codebase-SPECIFIC invariants;
ruff owns the generic ones (pyflakes/pycodestyle/bugbear, configured in
pyproject.toml ``[tool.ruff]``). The container may not ship ruff — the
gate then skips cleanly rather than failing on a missing dev tool;
``tools/lint.sh`` prints the same skip.
"""

import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ruff():
    exe = shutil.which("ruff")
    if exe:
        return [exe]
    probe = subprocess.run([sys.executable, "-m", "ruff", "--version"],
                           capture_output=True)
    if probe.returncode == 0:
        return [sys.executable, "-m", "ruff"]
    return None


def test_ruff_check_is_clean():
    runner = _ruff()
    if runner is None:
        pytest.skip("ruff is not installed in this environment")
    proc = subprocess.run(
        runner + ["check", "--no-cache",
                  "pytorch_distributed_mnist_tpu", "tools", "tests",
                  "bench.py"],
        capture_output=True, text=True, cwd=_REPO, timeout=300)
    assert proc.returncode == 0, \
        f"ruff check failed:\n{proc.stdout}\n{proc.stderr}"
