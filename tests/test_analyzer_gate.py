"""Tier-1 gate: tpumnist-lint is clean over the codebase it guards.

The contract (ISSUE 5): ``python -m tools.analyzer`` over
``pytorch_distributed_mnist_tpu/``, ``tools/`` and ``bench.py`` exits 0
with ZERO non-baselined findings; every baseline entry carries a
justification; a stale baseline entry fails the gate; and deliberately
re-introducing the zlib-strand bug (narrowing ``_try_load``'s except
back to a tuple) makes the analyzer fail with a file:line finding.
"""

import json
import os
import pathlib
import re
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import (  # noqa: E402
    analyze_snippet,
    default_baseline_path,
    load_baseline,
    run_analysis,
)

pytestmark = pytest.mark.lint

GATE_PATHS = [os.path.join(_REPO, p)
              for p in ("pytorch_distributed_mnist_tpu", "tools")] \
             + [os.path.join(_REPO, "bench.py")]

# One full-tree analysis shared by every read-only assertion below (a
# cold run costs ~7s of tier-1 wall on one core; four tests reading the
# same immutable result need not repeat it).
_GATE_RESULT = None


def _gate_result():
    global _GATE_RESULT
    if _GATE_RESULT is None:
        _GATE_RESULT = run_analysis(GATE_PATHS)
    return _GATE_RESULT


def test_codebase_has_zero_nonbaselined_findings():
    result = _gate_result()
    rendered = "\n".join(f.render() for f in result.findings)
    assert result.ok, (
        f"tpumnist-lint found unbaselined violations (fix them — only "
        f"genuinely intentional findings may be baselined, with a "
        f"justification):\n{rendered}\n"
        f"stale: {result.stale_baseline}\n"
        f"baseline problems: {result.baseline_problems}")
    # The gate is only meaningful if it actually scanned the codebase.
    assert result.n_files > 50, result.n_files


def test_every_baseline_entry_has_a_justification():
    path = default_baseline_path()
    entries, problems = load_baseline(path)
    assert not problems, problems
    raw = json.loads(pathlib.Path(path).read_text())
    assert len(raw) == len(entries)  # nothing skipped by validation
    for entry in entries:
        assert str(entry["justification"]).strip(), entry


def test_baseline_suppressions_each_match_exactly_one_known_finding():
    """The baseline documents ACCEPTED findings — each entry must still
    be suppressing something (stale entries fail), and what it
    suppresses is visible in the result for audit."""
    result = _gate_result()
    assert not result.stale_baseline, result.stale_baseline
    suppressed_checkers = {f.checker for f, _e in result.suppressed}
    entries, _ = load_baseline(default_baseline_path())
    assert len(result.suppressed) >= len(entries)
    for entry in entries:
        assert entry["checker"] in suppressed_checkers


_CLI = pathlib.Path(_REPO) / "pytorch_distributed_mnist_tpu" / "cli.py"


def _try_load_region(source: str) -> str:
    start = source.index("def _try_load")
    return source[start:source.index("loaded = (_try_load")]


def test_reintroducing_the_zlib_strand_fails_the_gate():
    """Narrow ``_try_load``'s funnel back to an enumerated tuple — the
    exact PR-1-era bug — and the agreement-except-breadth checker must
    produce a file:line finding in the dataset-agreement scope."""
    source = _CLI.read_text()
    region = _try_load_region(source)
    assert re.search(r"except Exception\b", region), (
        "cli.py _try_load no longer catches Exception — if that is "
        "intentional, this acceptance test and the checker must evolve "
        "together")
    narrowed = source.replace(
        region,
        region.replace(
            "except Exception as exc:",
            "except (FileNotFoundError, ValueError, OSError, "
            "EOFError) as exc:", 1),
        1)
    assert narrowed != source
    findings = analyze_snippet(narrowed,
                               checkers=["agreement-except-breadth"],
                               filename="cli.py")
    assert findings, "narrowed _try_load funnel was not flagged"
    f = findings[0]
    assert f.symbol == "_build_loaders"
    assert f.line > 0 and f.path == "cli.py"  # file:line attribution
    assert "zlib" in f.message  # names the incident class


def test_pristine_cli_is_clean_for_the_breadth_checker():
    findings = analyze_snippet(_CLI.read_text(),
                               checkers=["agreement-except-breadth"],
                               filename="cli.py")
    assert findings == []


def test_stale_baseline_entry_fails_the_gate(tmp_path):
    target = tmp_path / "clean.py"
    target.write_text("def f():\n    return 1\n")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps([{
        "checker": "lock-discipline",
        "path": "clean.py",
        "contains": "no longer exists",
        "justification": "was accepted once; the code is gone",
    }]))
    result = run_analysis([str(target)], baseline=str(baseline))
    assert not result.ok
    assert len(result.stale_baseline) == 1
    assert result.findings == []  # clean code; ONLY the staleness fails


def test_cli_entry_point_exits_zero_and_emits_schema_json():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyzer", "--format", "json"]
        + GATE_PATHS,
        capture_output=True, text=True, cwd=_REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["summary"]["ok"] is True
    assert payload["summary"]["findings"] == 0
    # The lock-discipline report must include the engine/pool lock graph
    # (ISSUE 5 acceptance).
    graph = payload["reports"]["lock-discipline"]["lock_graph"]
    engine = graph["pytorch_distributed_mnist_tpu/serve/engine.py"]
    # The staging free-list lock lives on the shared StagingPool since
    # ISSUE 12 (the MPMD plane reuses the same lifecycle).
    assert set(engine["locks"]) == {"InferenceEngine._lock",
                                    "StagingPool._lock"}
    pool = graph["pytorch_distributed_mnist_tpu/serve/pool.py"]
    assert pool["locks"] == ["EnginePool._lock"]


def test_gate_runs_all_twelve_checkers():
    """Analyzer v2 contract: the default registry carries the five
    serve/distrib-era checkers alongside the original seven — the gate
    above is only as strong as this list."""
    from tools.analyzer import checker_registry

    assert list(checker_registry()) == [
        "collective-symmetry", "agreement-except-breadth",
        "trace-purity", "recompile-hazard", "lock-discipline",
        "registry-drift", "marker-registry",
        "thread-lifecycle", "handler-discipline",
        "generation-ordering", "short-read", "donated-reuse",
    ]
    result = _gate_result()
    assert set(result.checkers) == set(checker_registry())


def test_sarif_output_is_schema_shaped():
    """Pin the SARIF 2.1.0 surface CI uploaders rely on: version, tool
    driver with one rule per checker, results with physical locations,
    and baselined findings carried as external suppressions."""
    from tools.analyzer import checker_registry, render_sarif

    result = _gate_result()
    payload = json.loads(render_sarif(result))
    assert payload["version"] == "2.1.0"
    assert payload["$schema"].endswith("sarif-2.1.0.json")
    (run,) = payload["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "tpumnist-lint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert rule_ids == set(checker_registry())
    for rule in driver["rules"]:
        assert rule["shortDescription"]["text"]
    # The gate is clean, so every emitted result is a suppressed
    # baseline entry — and each must carry its justification.
    for res in run["results"]:
        assert res["ruleId"] in rule_ids
        loc = res["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
        if "suppressions" in res:
            (sup,) = res["suppressions"]
            assert sup["kind"] == "external"
            assert sup["justification"].strip()
    suppressed = [r for r in run["results"] if "suppressions" in r]
    assert len(suppressed) == len(result.suppressed)


def test_warm_cache_rerun_is_deterministic(tmp_path):
    """Two runs over the same tree with the same cache file: identical
    findings byte-for-byte, and the second run reports a cache hit."""
    cache = str(tmp_path / "cache.json")
    cold = run_analysis(GATE_PATHS, cache=cache)
    assert cold.cache_info is not None and cold.cache_info["hit"] is False
    warm = run_analysis(GATE_PATHS, cache=cache)
    assert warm.cache_info is not None and warm.cache_info["hit"] is True
    cold_payload = [f.render() for f in cold.findings] + \
        [f.render() for f, _ in cold.suppressed]
    warm_payload = [f.render() for f in warm.findings] + \
        [f.render() for f, _ in warm.suppressed]
    assert cold_payload == warm_payload
    assert warm.ok == cold.ok


def test_cache_invalidates_on_file_change(tmp_path):
    """Touching one byte of one analyzed file must flip the next run
    back to a cold (correct) analysis, not replay stale findings."""
    target = tmp_path / "mod.py"
    target.write_text("import subprocess\n\n"
                      "def go(cmd):\n"
                      "    p = subprocess.Popen(cmd)\n"
                      "    return p.pid\n")
    cache = str(tmp_path / "cache.json")
    first = run_analysis([str(target)], baseline=None, cache=cache)
    assert len(first.findings) == 1  # unreaped Popen
    target.write_text("import subprocess\n\n"
                      "def go(cmd):\n"
                      "    with subprocess.Popen(cmd) as p:\n"
                      "        return p.wait()\n")
    second = run_analysis([str(target)], baseline=None, cache=cache)
    assert second.cache_info is not None
    assert second.cache_info["hit"] is False
    assert second.findings == []


def test_cli_nonexistent_path_is_a_usage_error_exit_2():
    """Exit-code contract: 2 for a misconfigured invocation (typoed
    path), distinct from 1 (real lint findings) for CI wrappers."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyzer", "/nonexistent_path_xyz"],
        capture_output=True, text=True, cwd=_REPO, timeout=120)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "does not exist" in proc.stdout
