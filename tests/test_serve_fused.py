"""Whole-program fused serving (ISSUE 16): raw bytes -> logits, one program.

Pins the fused-plane contract per servable mode x precision: the fused
bucket programs (in-XLA normalize + activation quantize + forward,
staging buffer DONATED) answer BITWISE-identically to the split plane at
exact-fit buckets, allclose + argmax-equal on padded batches, with zero
steady-state recompiles on either plane's ``CompileLog`` names (the
``.fused`` tag rides the bucket segment so ``serve_forward_`` filters
cover both). Plus the donation lifecycle — a donated staging buffer is
retired, never re-pinned — and the ``--no-fuse`` reference: an unfused
engine is byte-identical to the fused engine's split path.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.pool import EnginePool
from pytorch_distributed_mnist_tpu.serve.programs import (
    precision_engine_name,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.utils.profiling import compile_log

pytestmark = pytest.mark.serve

PRECISIONS = ("f32", "bf16", "int8w", "int8")

# Every servable plane (the test_serve_precision.py matrix): the
# single-device replicated engine, the SPMD tensor/expert mesh groups,
# and the MPMD pipeline chain (which fuses at stage 0 only).
MODES = [
    # linear for the replicated plane: the fused wrapper is
    # model-independent and XLA-CPU conv gradients would dominate the
    # tier-1 wall (the /verify recipe's ~4.6 s/step cnn caveat).
    ("replicated", "linear", 1),
    ("tensor", "vit", 2),
    ("expert", "moe_mlp", 2),
    ("pipeline", "vit", 2),
]

_TRAINED: dict = {}


def _trained_params(model_name: str):
    """Sharpened logits (fresh-init logits are near-ties, where float
    noise flips argmax for free) — same recipe as the precision suite."""
    if model_name in _TRAINED:
        return _TRAINED[model_name]
    model = get_model(model_name, compute_dtype=jnp.float32)
    images, labels = synthetic_dataset(256, seed=3)
    x = jnp.asarray(normalize_images(images))
    y = jnp.asarray(labels)
    params = create_train_state(model, jax.random.key(0)).params
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    def loss_fn(p):
        logits = model.apply(p, x, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    @jax.jit
    def step(p, o):
        updates, o = tx.update(jax.grad(loss_fn)(p), o, p)
        return optax.apply_updates(p, updates), o

    for _ in range(12):
        params, opt = step(params, opt)
    _TRAINED[model_name] = (model, params)
    return _TRAINED[model_name]


def _build_fused_plane(mode, model_name, mesh, precision):
    """A fuse=True plane carries BOTH dispatch planes: raw uint8 rides
    the fused bucket programs, float rides the split (reference) ones."""
    model, params = _trained_params(model_name)
    # One bucket: the equivalence drives only ever touch b8 (exact-fit
    # 8-row batches + a padded 5-row one); a second bucket would only
    # add AOT compile wall per plane x precision.
    if mode == "replicated":
        engine = InferenceEngine(
            model.apply, params, buckets=(8,), precision=precision,
            name=precision_engine_name(None, precision), fuse=True)
        engine.warmup()
        return engine
    if mode == "pipeline":
        from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
            split_vit_params,
        )

        params = split_vit_params(params)
    pool = EnginePool(
        model.apply, params, devices=jax.local_devices()[:mesh],
        buckets=(8,), serve_mode=mode, mesh_size=mesh,
        model_name=model_name, model=model, precision=precision, fuse=True)
    pool.warmup()
    return pool


def _plane_logits(plane, images):
    if isinstance(plane, EnginePool):
        return plane.complete(plane.dispatch(plane.preprocess(images)))[0]
    return plane.logits(images)


def _raw_images(n, seed=7):
    images, _ = synthetic_dataset(n, seed=seed)
    assert images.dtype == np.uint8
    return images


@pytest.mark.parametrize("mode,model_name,mesh", MODES,
                         ids=[m[0] for m in MODES])
def test_fused_bitwise_equals_split_every_precision(mode, model_name, mesh):
    """ISSUE 16 acceptance: for every servable mode x precision, the
    fused plane (raw uint8 in) is BITWISE equal to the split plane
    (host-normalized float in) at exact-fit buckets — the in-XLA
    normalize/quantize twins are pinned to the host ones — allclose +
    argmax-equal on padded batches, with ZERO steady-state recompiles
    across BOTH planes' programs."""
    raw = _raw_images(16)
    norm = normalize_images(raw)
    for precision in PRECISIONS:
        plane = _build_fused_plane(mode, model_name, mesh, precision)

        def compiles():
            return {n: rec["backend_compiles"] for n, rec in
                    compile_log.stats()["programs"].items()
                    if n.startswith("serve_forward_")}

        # Warm both routes once, then pin steady state over a second
        # round: no serve_forward_ program (split OR .fused) recompiles.
        split = np.concatenate([_plane_logits(plane, norm[i:i + 8])
                                for i in range(0, 16, 8)])
        fused = np.concatenate([_plane_logits(plane, raw[i:i + 8])
                                for i in range(0, 16, 8)])
        before = compiles()
        assert any(".fused" in n for n in before), \
            f"{mode}.{precision}: no fused program in CompileLog"
        split2 = np.concatenate([_plane_logits(plane, norm[i:i + 8])
                                 for i in range(0, 16, 8)])
        fused2 = np.concatenate([_plane_logits(plane, raw[i:i + 8])
                                 for i in range(0, 16, 8)])
        fused_pad = _plane_logits(plane, raw[:5])
        split_pad = _plane_logits(plane, norm[:5])
        assert compiles() == before, \
            f"{mode}.{precision} recompiled in steady state"

        # Exact-fit buckets: bitwise — the whole-program plane changes
        # WHERE the preprocessing runs, not what it computes.
        np.testing.assert_array_equal(
            fused.view(np.uint32), split.view(np.uint32),
            err_msg=f"{mode}.{precision}: fused != split at exact fit")
        np.testing.assert_array_equal(fused.view(np.uint32),
                                      fused2.view(np.uint32))
        np.testing.assert_array_equal(split.view(np.uint32),
                                      split2.view(np.uint32))
        # Padded: the fused plane pads RAW zeros (normalized in-program)
        # where the split plane pads 0.0 — real rows are row-independent.
        np.testing.assert_allclose(
            fused_pad, split_pad, atol=1e-5,
            err_msg=f"{mode}.{precision}: padded fused != split")
        assert np.array_equal(fused_pad.argmax(-1), split_pad.argmax(-1))


def test_fused_program_names_carry_the_tag():
    """``.fused`` rides the bucket segment (serve_forward_b8.fused@...)
    so every serve_forward_ prefix filter covers both planes; pipeline
    fuses at stage 0 ONLY (later stages see the identical activation
    contract, so the split chain past stage 0 IS the fused chain)."""
    _build_fused_plane("tensor", "vit", 2, "int8w")
    _build_fused_plane("pipeline", "vit", 2, "bf16")
    names = set(compile_log.stats()["programs"])
    assert "serve_forward_b8.fused@tensor.int8w" in names
    assert "serve_forward_b8.fused@pipeline.bf16.s0" in names
    assert "serve_forward_b8.fused@pipeline.bf16.s1" not in names
    assert "serve_forward_b8@pipeline.bf16.s1" in names


def test_unfused_engine_is_byte_identical_reference():
    """The --no-fuse contract at engine level: an unfused engine (the
    default) answers byte-identically to the fused engine — on float
    input both run the split programs; on raw uint8 the unfused engine
    normalizes host-side, which the fused in-XLA twin is pinned to."""
    model, params = _trained_params("linear")
    plain = InferenceEngine(model.apply, params, buckets=(1, 8))
    fused = InferenceEngine(model.apply, params, buckets=(1, 8), fuse=True)
    assert plain.fuse is False  # engines default to the split plane
    plain.warmup()
    fused.warmup()
    raw = _raw_images(8, seed=5)
    norm = normalize_images(raw)
    np.testing.assert_array_equal(
        plain.logits(norm).view(np.uint32),
        fused.logits(norm).view(np.uint32))
    np.testing.assert_array_equal(
        plain.logits(raw).view(np.uint32),
        fused.logits(raw).view(np.uint32))


# -- donation lifecycle ------------------------------------------------------


def test_fused_donation_retires_staging_buffers():
    """A donated buffer is handed to XLA at dispatch: it is counted
    retired, the free-list never sees it again (acquire always
    allocates fresh on the fused plane), and the split plane's staging
    pool is untouched by fused traffic."""
    model, params = _trained_params("linear")
    engine = InferenceEngine(model.apply, params, buckets=(8,), fuse=True)
    engine.warmup()
    raw = _raw_images(8, seed=2)
    split_alloc = engine.staging_allocated()
    for i in range(6):
        engine.logits(raw)
        assert engine.fused_staging_retired() == {8: i + 1}
        # Retired means GONE: the fused free-list must stay empty.
        assert engine._fused_staging._free == {8: []}
    # Every fused dispatch allocated a fresh buffer (donated-never-reused
    # is the lifecycle, the opposite of the split plane's free-list).
    assert engine._fused_staging.allocated() == {8: 6}
    assert engine.staging_allocated() == split_alloc
    # The unfused engine reports no fused retirement at all.
    plain = InferenceEngine(model.apply, params, buckets=(8,))
    assert plain.fused_staging_retired() == {}


def test_staging_pool_retire_never_returns_to_free_list():
    """Unit pin on StagingPool itself: retire() drops, release() reuses
    — the two must never be interchangeable for one buffer."""
    from pytorch_distributed_mnist_tpu.serve.engine import StagingPool

    pool = StagingPool((4,), (28, 28), dtype=np.uint8)
    a = pool.acquire(4)
    pool.retire([(4, a)])
    assert pool.retired() == {4: 1}
    b = pool.acquire(4)  # must be a FRESH allocation, not `a`
    assert b is not a
    assert pool.allocated() == {4: 2}
    pool.release([(4, b)])
    assert pool.acquire(4) is b  # released buffers do come back


def test_fused_dispatch_under_reload_hammering():
    """Donation + hot reload: under a hammering swap thread, every fused
    batch's logits are BITWISE one publish's output or the other's, and
    the retirement count tracks every dispatch (no buffer leaks back)."""
    model, params_a = _trained_params("linear")
    params_b = jax.tree_util.tree_map(lambda x: x * 1.5, params_a)
    engine = InferenceEngine(model.apply, params_a, buckets=(8,),
                             fuse=True, params_epoch=1)
    engine.warmup()
    raw = _raw_images(8, seed=4)
    want_a = engine.logits(raw)
    engine.swap_params(params_b, epoch=2)
    want_b = engine.logits(raw)
    assert not np.array_equal(want_a, want_b)
    base = engine.fused_staging_retired()[8]

    stop = threading.Event()

    def hammer():
        flip = False
        while not stop.is_set():
            engine.swap_params(params_b if flip else params_a)
            flip = not flip

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        for i in range(60):
            got = engine.logits(raw)
            assert np.array_equal(got, want_a) \
                or np.array_equal(got, want_b), \
                "fused batch mixed two publishes"
            assert engine.fused_staging_retired()[8] == base + i + 1
    finally:
        stop.set()
        t.join(5.0)


def test_pool_failover_redispatch_safe_with_fused(monkeypatch):
    """The fused plane always COPIES into staging (never donates the
    request's own array), so the pool's failover redispatch — which
    re-sends the SAME handle rows to a sibling replica — still holds
    valid bytes after the first replica donated its staged copy."""
    model, params = _trained_params("linear")
    pool = EnginePool(model.apply, params, devices=jax.local_devices()[:2],
                      buckets=(1, 8), fuse=True)
    pool.warmup()
    raw = _raw_images(8, seed=6)
    want = pool.complete(pool.dispatch(pool.preprocess(raw)))[0]

    # Break replica 0's fused dispatch AFTER staging so completion
    # fails and the pool redispatches the handle's rows elsewhere.
    victim = pool.replicas[0].engine
    calls = {"n": 0}

    def boom(inflight):
        calls["n"] += 1
        raise RuntimeError("injected completion failure")

    # Least-loaded dispatch picks index 0 on an idle pool, so the very
    # next batch stages on the victim, fails at completion, and fails
    # over whole to replica 1.
    monkeypatch.setattr(victim, "complete", boom)
    got = pool.complete(pool.dispatch(pool.preprocess(raw)))[0]
    assert calls["n"] > 0, "injected failure never exercised"
    np.testing.assert_array_equal(got.view(np.uint32),
                                  want.view(np.uint32))
