"""End-to-end integration via the CLI driver (SURVEY.md section 4):
overfit synthetic data, checkpoint -> resume continuity, --evaluate from
checkpoint reproducing best_acc (BASELINE configs 1, 3, 4)."""

import os

import pytest

from pytorch_distributed_mnist_tpu.cli import build_parser, run


def make_args(tmp_path, **overrides):
    argv = [
        "--dataset", "synthetic",
        "--synthetic-train-size", "512",
        "--synthetic-test-size", "256",
        "--batch-size", "128",
        "--epochs", "2",
        "--model", "linear",
        "--lr", "0.01",
        "--seed", "0",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


def test_train_and_improve(tmp_path):
    out = run(make_args(tmp_path, epochs=3))
    assert out["epochs_run"] == 3
    assert out["best_acc"] > 0.5  # synthetic digits are easy; must beat chance 0.1
    losses = [h["train_loss"] for h in out["history"]]
    assert losses[-1] < losses[0]
    assert os.path.isfile(tmp_path / "ckpt" / "checkpoint_2.npz")
    assert os.path.isfile(tmp_path / "ckpt" / "model_best.npz")


def test_resume_continues_at_next_epoch(tmp_path):
    run(make_args(tmp_path, epochs=2))
    out = run(make_args(tmp_path, epochs=4,
                        resume=str(tmp_path / "ckpt" / "checkpoint_1.npz")))
    epochs = [h["epoch"] for h in out["history"]]
    assert epochs == [2, 3]  # resumed at saved epoch+1 (:204, :251)


def test_evaluate_short_circuit_reproduces_best_acc(tmp_path):
    trained = run(make_args(tmp_path, epochs=2))
    out = run(make_args(tmp_path, evaluate=True,
                        resume=str(tmp_path / "ckpt" / "model_best.npz")))
    assert out["epochs_run"] == 0
    assert abs(out["test_acc"] - trained["best_acc"]) < 1e-6


@pytest.mark.parametrize("mode", ["stepwise", "explicit"])
def test_trainer_modes_run(tmp_path, mode):
    out = run(make_args(tmp_path, epochs=1, trainer_mode=mode))
    assert out["epochs_run"] == 1


@pytest.mark.slow
def test_cnn_overfits_synthetic(tmp_path):
    out = run(make_args(tmp_path, model="cnn", epochs=8, batch_size=64, lr=1e-3,
                        synthetic_train_size=256, synthetic_test_size=128))
    assert out["best_acc"] > 0.6  # CNN learns noised glyph digits in 32 steps


def test_fashion_mnist_dataset_flag(tmp_path):
    # No real FashionMNIST on disk -> --allow-synthetic opts into the
    # labelled fallback (BASELINE config 5's dataset swap-in is a flag,
    # not a code edit).
    out = run(make_args(tmp_path, dataset="fashion_mnist", epochs=1,
                        allow_synthetic=True))
    assert out["epochs_run"] == 1
    assert out["dataset_synthesized"]


def test_workers_noop_note_when_native_absent(tmp_path, capsys, monkeypatch):
    """The reference's --workers feeds real DataLoader processes (:156);
    when our native backend isn't built the flag must SAY it's a no-op
    at startup, not silently swallow it (round-3 VERDICT missing #3)."""
    from pytorch_distributed_mnist_tpu.data import native

    monkeypatch.setattr(native, "available", lambda: False)
    run(make_args(tmp_path, epochs=1))
    assert "-j/--workers 4 is a no-op" in capsys.readouterr().out


def test_missing_dataset_fails_fast(tmp_path):
    # The reference ALWAYS downloads a missing dataset (:137-138); a
    # missing dataset here without --download/--allow-synthetic must be
    # a hard error, never a silent synthetic run with fake accuracy.
    with pytest.raises(SystemExit, match="allow-synthetic"):
        run(make_args(tmp_path, dataset="fashion_mnist", epochs=1))


def test_multihost_presence_decision_is_agreed_without_download(
        tmp_path, monkeypatch):
    """Round-4 advisor (medium): the dataset-presence decision must be
    agreed across hosts in EVERY multi-host path, not only under
    --download — otherwise a host missing the IDX files either falls back
    to synthetic alone (silent cross-host data divergence) or raises
    SystemExit alone while its peers hang at the next collective.
    Hermetic twin: process_count/allgather stubbed (on the supervision
    record channel the agreement now rides) to simulate a 2-host job
    where the peer host lacks the files."""
    import numpy as np

    from pytorch_distributed_mnist_tpu import cli
    from pytorch_distributed_mnist_tpu.runtime import supervision as sup

    monkeypatch.setattr(cli, "process_count", lambda: 2)
    monkeypatch.setattr(sup, "process_count", lambda: 2)
    monkeypatch.setattr(sup, "process_index", lambda: 0)
    calls = []

    def fake_allgather(x):
        calls.append(np.asarray(x))
        peer = np.frombuffer(
            sup._encode_record(sup._ERR, "files missing on host 1"),
            np.uint8)
        return np.stack([np.asarray(x), peer])

    monkeypatch.setattr(sup, "_raw_allgather", fake_allgather)

    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",))

    # Without --allow-synthetic: every host raises the same fail-fast —
    # and the agreement allgather really ran (no --download given).
    with pytest.raises(SystemExit, match="not present on every host"):
        cli._build_loaders(
            make_args(tmp_path, dataset="fashion_mnist"), seed=0, mesh=mesh)
    assert calls, "presence agreement must run even without --download"

    # With --allow-synthetic: all hosts take the synthetic fallback
    # together instead of deciding per host inside load_split.
    _, _, used_synth = cli._build_loaders(
        make_args(tmp_path, dataset="fashion_mnist", allow_synthetic=True),
        seed=0, mesh=mesh)
    assert used_synth


def test_synthetic_tag_on_epoch_lines_and_metrics(tmp_path, capsys):
    mf = tmp_path / "metrics.jsonl"
    out = run(make_args(tmp_path, dataset="fashion_mnist", epochs=1,
                        allow_synthetic=True, metrics_file=str(mf)))
    assert out["dataset_synthesized"]
    printed = capsys.readouterr().out
    epoch_lines = [l for l in printed.splitlines() if l.startswith("Epoch:")]
    assert epoch_lines and all(
        "dataset: synthetic" in l for l in epoch_lines)
    import json

    rows = [json.loads(l) for l in mf.read_text().splitlines()]
    assert rows and all(r["dataset"] == "synthetic" for r in rows)


def test_explicit_synthetic_needs_no_flag_and_is_tagged(tmp_path, capsys):
    out = run(make_args(tmp_path, epochs=1))  # --dataset synthetic
    assert out["dataset_synthesized"]
    printed = capsys.readouterr().out
    epoch_lines = [l for l in printed.splitlines() if l.startswith("Epoch:")]
    assert epoch_lines and all(
        "dataset: synthetic" in l for l in epoch_lines)


def test_debug_nans_flag(tmp_path):
    """--debug-nans wires jax_debug_nans: a healthy run still passes, and a
    poisoned loss raises FloatingPointError at the producing op (SURVEY.md
    section 5's NaN-debug subsystem)."""
    import jax
    import jax.numpy as jnp
    import pytest

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--debug-nans",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    try:
        summary = run(args)
        assert jnp.isfinite(summary["history"][0]["train_loss"])
        # the flag is active process-wide: a NaN-producing jitted op raises
        with pytest.raises(FloatingPointError):
            jax.jit(lambda x: jnp.log(x))(jnp.zeros(4) - 1.0).block_until_ready()
    finally:
        jax.config.update("jax_debug_nans", False)


def test_metrics_file(tmp_path):
    """--metrics-file appends one JSON line per epoch (SURVEY section 5)."""
    import json

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    mf = tmp_path / "metrics.jsonl"
    run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--epochs", "2",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0",
        "--metrics-file", str(mf),
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ]))
    lines = [json.loads(l) for l in mf.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["epoch"] == 0 and lines[1]["epoch"] == 1
    for row in lines:
        for key in ("train_loss", "test_acc", "lr", "best_acc",
                    "images_per_sec"):
            assert key in row


def test_compile_cache_populated(tmp_path):
    """--compile-cache DIR: the persistent XLA cache receives entries, and
    a second identical run still trains correctly while reading from it."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    cache = tmp_path / "xla_cache"
    common = [
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0", "--epochs", "1",
        "--trainer-mode", "stepwise", "--compile-cache", str(cache),
    ]
    s1 = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "a")]))
    assert cache.is_dir() and len(list(cache.iterdir())) > 0
    s2 = run(build_parser().parse_args(
        common + ["--checkpoint-dir", str(tmp_path / "b")]))
    assert s2["history"][0]["train_loss"] == s1["history"][0]["train_loss"]


def test_profile_dir_writes_trace(tmp_path):
    """--profile-dir: a jax.profiler trace capture lands on disk, with the
    per-phase annotations active inside it (smoke: capture dir non-empty)."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    trace = tmp_path / "trace"
    run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "128",
        "--synthetic-test-size", "64", "--seed", "0", "--epochs", "1",
        "--trainer-mode", "stepwise", "--profile-dir", str(trace),
        "--checkpoint-dir", str(tmp_path / "ck"),
    ]))
    assert trace.is_dir()
    files = [p for p in trace.rglob("*") if p.is_file()]
    assert files, "profiler trace directory is empty"
