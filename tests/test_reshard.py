"""Cross-world checkpoint resharding: the elastic runtime's enabling
contract (ISSUE 10, ROADMAP item 5).

A checkpoint saved at world size W must load at ANY world size W' —
npz and sharded layouts, plain DP / zero1 / zero3 state layouts — with
the resumed state bit-identical to a fresh shard of the gathered
arrays. Worlds are simulated as device-subset meshes (the same
in-process strategy the mesh suites use; the REAL multi-process twins
live in tests/test_elastic_chaos.py): the property under test is that
neither the saving mesh nor the saving process count constrains the
loading template, because restore always stitches full host arrays and
re-places them with the template's own shardings.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
from pytorch_distributed_mnist_tpu.train import checkpoint as ck
from pytorch_distributed_mnist_tpu.train.checkpoint import (
    checkpoint_world,
    load_checkpoint,
    save_checkpoint,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.elastic


def _mesh(n: int) -> Mesh:
    """A 'world' of n chips: the first n of the suite's 8 CPU devices."""
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _fresh(seed: int = 0):
    model = get_model("linear", compute_dtype=jnp.float32)
    return create_train_state(model, jax.random.key(seed))


def _place(state, mesh: Mesh, level):
    """State placed on ``mesh`` in the requested layout: replicated DP
    (level None) or ZeRO level 1/3 (the zero_state_sharding spec
    tables — exactly what a resumed run shards the loaded arrays with).
    """
    if level is None:
        return jax.device_put(state, NamedSharding(mesh, P()))
    placed, _ = shard_state_zero(state, mesh, level=level)
    return placed


def _gathered(state):
    return [np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(ck._state_tree(state))]


WORLD_PAIRS = [(8, 4), (4, 8), (8, 1), (1, 8)]


@pytest.mark.parametrize("level", [None, 1, 3],
                         ids=["plain", "zero1", "zero3"])
@pytest.mark.parametrize("layout", ["npz", "sharded"])
@pytest.mark.parametrize("w_save,w_load", WORLD_PAIRS)
def test_cross_world_round_trip(tmp_path, level, layout, w_save, w_load):
    """Save at world W, load at world W': gathered state equal bitwise,
    and the loaded leaves land exactly on the template's shardings (a
    fresh shard of the gathered arrays — nothing about the saving world
    leaks into the loaded placement)."""
    saved_state = _place(_fresh(seed=0), _mesh(w_save), level)
    path = save_checkpoint(saved_state, epoch=3, best_acc=0.25,
                           is_best=False, directory=str(tmp_path),
                           layout=layout)
    template = _place(_fresh(seed=1), _mesh(w_load), level)
    loaded, start_epoch, best_acc = load_checkpoint(path, template)
    assert start_epoch == 4 and best_acc == 0.25
    for want, got in zip(_gathered(saved_state), _gathered(loaded)):
        np.testing.assert_array_equal(want, got)
    for tmpl_leaf, got_leaf in zip(
            jax.tree_util.tree_leaves(ck._state_tree(template)),
            jax.tree_util.tree_leaves(ck._state_tree(loaded))):
        assert got_leaf.sharding == tmpl_leaf.sharding


@pytest.mark.parametrize("layout", ["npz", "sharded"])
def test_cross_world_equals_same_world_resume(tmp_path, layout):
    """The acceptance identity: a W -> W' load is bit-identical to a
    same-world (W' -> W') resume of the gathered state."""
    w_save, w_load = 8, 2
    saved_state = _place(_fresh(seed=0), _mesh(w_save), 1)
    path = save_checkpoint(saved_state, epoch=0, best_acc=0.0,
                           is_best=False, directory=str(tmp_path),
                           layout=layout)
    cross, _, _ = load_checkpoint(path, _place(_fresh(seed=1),
                                               _mesh(w_load), 1))
    # Same-world twin: re-save the cross-loaded state AT W' and load it
    # back at W'.
    twin_dir = tmp_path / "same_world"
    twin = save_checkpoint(cross, epoch=0, best_acc=0.0, is_best=False,
                           directory=str(twin_dir), layout=layout)
    same, _, _ = load_checkpoint(twin, _place(_fresh(seed=2),
                                              _mesh(w_load), 1))
    for a, b in zip(_gathered(cross), _gathered(same)):
        np.testing.assert_array_equal(a, b)
    for la, lb in zip(jax.tree_util.tree_leaves(ck._state_tree(cross)),
                      jax.tree_util.tree_leaves(ck._state_tree(same))):
        assert la.sharding == lb.sharding
        for sa, sb in zip(la.addressable_shards, lb.addressable_shards):
            np.testing.assert_array_equal(np.asarray(sa.data),
                                          np.asarray(sb.data))


def test_world_stamp_round_trip(tmp_path):
    """Both layouts stamp the saving world into meta, readable without
    touching array bytes (the inspection surface the elastic resume
    path and serve boot use)."""
    state = _place(_fresh(), _mesh(8), None)
    for layout in ("npz", "sharded"):
        path = save_checkpoint(state, epoch=0, best_acc=0.0,
                               is_best=False,
                               directory=str(tmp_path / layout),
                               layout=layout)
        world = checkpoint_world(path)
        assert world == {"processes": 1, "devices": 8}


def test_pre_stamp_checkpoint_has_no_world(tmp_path):
    """Checkpoints saved before the stamp existed read as None — no
    provenance, and the restore path reshards regardless."""
    state = _fresh()
    path = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                           directory=str(tmp_path), process_index=0)
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        payload = {k: z[k] for k in z.files if k != "__meta__"}
    del meta["world"]
    with open(path, "wb") as f:
        np.savez(f, __meta__=np.frombuffer(
            json.dumps(meta).encode(), np.uint8), **payload)
    assert checkpoint_world(path) is None
    loaded, epoch, _ = load_checkpoint(path, _fresh(seed=1))
    assert epoch == 1
    for a, b in zip(_gathered(state), _gathered(loaded)):
        np.testing.assert_array_equal(a, b)


def test_missing_shards_error_names_saving_world(tmp_path):
    """A shard-coverage gap on a world-stamped directory is reported as
    the incomplete filesystem view it is: the error names how many
    index files the saving world wrote vs how many are visible."""
    state = _place(_fresh(), _mesh(8), 1)
    path = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                           directory=str(tmp_path), layout="sharded")
    meta_path = os.path.join(path, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["world"]["processes"] = 4  # as if 3 peers' files never synced
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    os.remove(os.path.join(path, "shards_p00000.npz"))
    with pytest.raises(ValueError, match="4-process world"):
        load_checkpoint(path, _place(_fresh(seed=1), _mesh(8), 1))


def _resume_args(ckpt_dir):
    from pytorch_distributed_mnist_tpu.cli import build_parser

    return build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear",
        "--epochs", "1", "--batch-size", "64",
        "--synthetic-train-size", "256", "--synthetic-test-size", "128",
        "--trainer-mode", "stepwise", "--seed", "0",
        "--optimizer-sharding", "zero1",
        "--checkpoint-dir", str(ckpt_dir), "--resume", "auto",
    ])


def test_corrupt_latest_cross_world_falls_back(tmp_path):
    """The elastic resume path composed with PR 2's quarantine: the
    latest checkpoint (saved at a DIFFERENT world, sharded layout) is
    corrupt; --resume auto quarantines it and falls back to the
    next-older epoch — which is ALSO a cross-world file — and the run
    proceeds from there."""
    from pytorch_distributed_mnist_tpu.cli import run

    old_world = _place(_fresh(seed=0), _mesh(4), 1)
    older = save_checkpoint(old_world, epoch=0, best_acc=0.1,
                            is_best=False, directory=str(tmp_path),
                            layout="sharded")
    latest = save_checkpoint(old_world, epoch=1, best_acc=0.2,
                             is_best=False, directory=str(tmp_path),
                             layout="sharded")
    # The in-process 'other world' is a device-subset mesh, so the meta
    # stamp records THIS process's world; rewrite it to what a real
    # 4-host save would have stamped, so the resume sees a cross-world
    # file by inspection.
    meta_path = os.path.join(older, "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["world"] = {"processes": 4, "devices": 4}
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    shard = os.path.join(latest, "shards_p00000.npz")
    with open(shard, "wb") as f:
        f.write(b"this is not a zip file")
    summary = run(_resume_args(tmp_path))
    # Fell back past the quarantined epoch 1 to epoch 0 (resume at 1).
    assert summary["start_epoch"] == 1
    assert os.path.isdir(str(latest) + ".corrupt")
    kinds = [ev["kind"] for ev in summary["failure_events"]]
    assert "checkpoint_quarantined" in kinds
    assert "checkpoint_reshard" in kinds  # 4-device save, 8-device world
