"""Fleet federation acceptance twin (ISSUE 17): a real router over
three real loopback backends (in-process ThreadingHTTPServers — no
subprocess jax boots), driven by the real loadgen open-loop client.

Pins the four fleet contracts end to end:
- backend death under live traffic: 100% of requests answered (zero
  dropped), the dead backend quarantined, then re-admitted through
  probation after a restart on the same port;
- aggregated /stats: per-backend rows + merged fleet quantiles;
- rolling reload: a fleet-wide publish lands on every backend with
  zero client-visible drops;
- fleet canary: a corrupt publish auto-rolls-back with the baseline
  epoch serving throughout.

The process-boundary versions (real SIGKILL, real subprocesses) live
in tools/chaos.py --fleet; the pure state machines in
tests/test_serve_router.py."""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import synthetic_dataset
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.router import create_router
from pytorch_distributed_mnist_tpu.serve.router import (
    build_parser as router_parser,
)
from pytorch_distributed_mnist_tpu.serve.server import (
    build_parser,
    create_server,
)
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from tools.loadgen import _make_images, run_open

pytestmark = [pytest.mark.serve, pytest.mark.fleet]


def _publish(ckpt_dir, epoch, seed):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


def _backend_args(ckpt_dir, port=0):
    return build_parser().parse_args([
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", str(port),
        "--buckets", "1,8",
        "--max-wait-ms", "2", "--max-queue", "256",
        "--poll-interval", "0.1",
    ])


class _Server:
    """One in-process HTTP server (backend or router)."""

    def __init__(self, httpd):
        self.httpd = httpd
        host, port = httpd.server_address[:2]
        self.host, self.port = host, port
        self.url = f"http://{host}:{port}"
        self.name = f"{host}:{port}"
        self.thread = threading.Thread(
            target=httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def kill(self):
        """Abrupt death: stop answering NOW, leave ctx teardown for
        later — from the router's side this is exactly a SIGKILL
        (connection refused on the next dispatch/probe)."""
        self.httpd.shutdown()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post(self, path, payload):
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())


def _boot_backend(ckpt_dir, port=0):
    return _Server(create_server(_backend_args(ckpt_dir, port=port)))


def _boot_router(backends, **overrides):
    argv = ["--backends", ",".join(b.name for b in backends),
            "--host", "127.0.0.1", "--port", "0",
            "--health-interval", "0.1",
            "--quarantine-after", "2",
            "--probation-successes", "2",
            "--connect-timeout", "2.0"]
    for k, v in overrides.items():
        argv += ["--" + k.replace("_", "-"), str(v)]
    return _Server(create_router(router_parser().parse_args(argv)))


def _wait(predicate, timeout_s=15.0, what="condition"):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture()
def fleet(tmp_path):
    """Three backends (each its own checkpoint dir at epoch 0) behind
    one router; yields (router, [backends], [ckpt_dirs])."""
    dirs, backends = [], []
    for i in range(3):
        ckpt = tmp_path / f"b{i}"
        _publish(ckpt, epoch=0, seed=10)
        dirs.append(ckpt)
        backends.append(_boot_backend(ckpt))
    router = _boot_router(backends)
    _wait(lambda: router.get("/healthz")["routable"] == 3,
          what="all 3 backends healthy")
    try:
        yield router, backends, dirs
    finally:
        router.close()
        for b in backends:
            try:
                b.close()
            except Exception:  # noqa: BLE001 - some died on purpose
                pass


def test_kill_one_backend_zero_dropped_then_readmit(fleet, tmp_path):
    """The acceptance run: open-loop loadgen through the router, one
    backend dies mid-traffic -> every request still answered (router
    failover + bounded client retry = zero transport drops), the dead
    backend quarantines, and a restart on the SAME port walks
    probation back to healthy."""
    router, backends, dirs = fleet
    bodies = _make_images(n_templates=4, images_per_request=1, seed=0,
                          extra_fields={"client_id": "acceptance"})

    victim = backends[1]
    result = {}

    def drive():
        result["collector"] = run_open(
            router.url, rate=120.0, duration=3.0, bodies=bodies,
            timeout=30.0, retries=2)

    driver = threading.Thread(target=drive, daemon=True)
    driver.start()
    time.sleep(1.0)  # traffic established across the fleet
    victim.kill()
    driver.join(60.0)
    assert not driver.is_alive()

    collector = result["collector"]
    sent = sum(collector.status.values())
    # Zero DROPPED: after the router's one-failover and the client's
    # bounded retries, no request ended in a transport error — and
    # with two healthy backends absorbing, none was shed either.
    assert sent > 100
    assert collector.errors == 0, collector.status
    assert collector.conn_refused == 0
    assert collector.status.get(200, 0) == sent, collector.status

    # The dead backend is quarantined (poller or dispatch noticed) and
    # the router kept serving: /stats shows the per-backend rows and
    # the merged fleet quantiles over the survivors' windows. Rows are
    # sorted by NAME (ephemeral ports don't sort in creation order) —
    # always look the victim up, never index positionally.
    def _victim_row():
        for r in router.get("/stats")["backends"]:
            if r["name"] == victim.name:
                return r
        raise AssertionError(f"no row for {victim.name}")

    _wait(lambda: _victim_row()["state"] == "quarantined",
          what="victim quarantine")
    stats = router.get("/stats")
    rows = {r["name"]: r for r in stats["backends"]}
    assert set(rows) == {b.name for b in backends}
    assert rows[victim.name]["quarantines"] >= 1
    assert not rows[victim.name]["routable"]
    assert stats["fleet"]["routable"] == 2
    merged = stats["fleet"]["window"]
    assert merged["count"] > 0 and merged["backends"] >= 1
    assert merged["p99_ms"] >= merged["p50_ms"] > 0
    assert stats["router"]["by_code"].get("200", 0) > 100
    survivors = [r for n, r in rows.items() if n != victim.name]
    assert sum(r["requests"] for r in survivors) > 0

    # Restart on the same port: the health poller walks it
    # quarantined -> probation -> healthy (2 successes) with no
    # operator action, and it serves traffic again.
    revived = _boot_backend(dirs[1], port=victim.port)
    try:
        assert revived.name == victim.name
        _wait(lambda: _victim_row()["state"] == "healthy",
              what="victim re-admission")
        row = _victim_row()
        assert row["readmissions"] >= 1 and row["routable"]
        assert router.get("/healthz")["routable"] == 3
    finally:
        revived.close()


def test_rolling_reload_zero_drops(fleet, tmp_path):
    """POST /rollout under live traffic: every backend flips to the new
    epoch one at a time, and no client request fails — the drained
    backend's refusals are retried by the router (proof-of-non-
    execution), never surfaced."""
    router, backends, dirs = fleet
    staging = tmp_path / "staging"
    _publish(staging, epoch=1, seed=77)
    source = str(staging / "checkpoint_1.npz")

    images, _ = synthetic_dataset(2, seed=3)
    payload = {"images": images.tolist(), "client_id": "roller"}
    failures = []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                reply = router.post("/predict", payload)
                if len(reply["predictions"]) != 2:
                    failures.append(("malformed", reply))
            except Exception as exc:  # noqa: BLE001
                failures.append(("error", repr(exc)))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    try:
        result = router.post("/rollout", {"source": source})
    finally:
        time.sleep(0.3)  # keep hammering past the last rejoin
        stop.set()
        for t in threads:
            t.join(10.0)

    assert result["ok"], result
    assert sorted(result["updated"]) == sorted(b.name for b in backends)
    assert result["target_epoch"] == 1
    assert not failures, failures[:5]
    for b in backends:
        health = b.get("/healthz")
        assert health["model_epoch"] == 1 and health["draining"] is False
    stats = router.get("/stats")
    assert stats["last_rollout"]["ok"]
    assert all(r["epoch"] == 1 for r in stats["backends"])
    # A second rollout to the same epoch is fine; a concurrent one
    # would 409 (pinned in the unit suite's sequencer tests).


def test_fleet_canary_bad_publish_rolls_back(fleet, tmp_path):
    """A corrupt publish behind a fleet canary: the canary backend's
    watcher refuses the file, install-verify times out, the router
    auto-rolls-back (removes the bad file) — and the baseline epoch
    served every request throughout."""
    router, backends, dirs = fleet
    staging = tmp_path / "staging"
    staging.mkdir()
    bad = staging / "checkpoint_2.npz"
    bad.write_bytes(b"definitely not an npz")

    images, _ = synthetic_dataset(1, seed=5)
    payload = {"images": images.tolist()}
    try:
        router.post("/rollout", {
            "source": str(bad),
            "canary": {"fraction": 0.5,
                       "backends": [backends[0].name]},
            "verify_timeout_s": 1.5,
        })
        code, body = 200, {}
    except urllib.error.HTTPError as exc:
        code = exc.code
        body = json.loads(exc.read())
    assert code == 502
    assert body["ok"] is False
    assert body["canary"]["state"] == "rolled_back"
    assert body["rollout"]["ok"] is False

    # The bad file is gone from the canary backend's publish dir and
    # the whole fleet still serves the baseline epoch.
    assert not (dirs[0] / "checkpoint_2.npz").exists()
    for b in backends:
        assert b.get("/healthz")["model_epoch"] == 0
        assert b.get("/healthz")["draining"] is False
    reply = router.post("/predict", payload)
    assert reply["model_epoch"] == 0
    stats = router.get("/stats")
    assert stats["fleet_canary"]["state"] == "rolled_back"
    assert stats["fleet_canary"]["rollbacks"] == 1


def test_zero_backends_is_a_loud_fleet_503(tmp_path):
    """The whole fleet dead: /predict answers a LOUD 503 naming every
    backend's state, with Retry-After — and /healthz goes unhealthy
    (the signal a front-of-router load balancer needs)."""
    ckpt = tmp_path / "only"
    _publish(ckpt, epoch=0, seed=10)
    backend = _boot_backend(ckpt)
    router = _boot_router([backend])
    try:
        _wait(lambda: router.get("/healthz")["routable"] == 1,
              what="backend healthy")
        backend.kill()
        _wait(lambda: router.get("/stats")["backends"][0]["state"]
              == "quarantined", what="quarantine")
        images, _ = synthetic_dataset(1, seed=0)
        try:
            router.post("/predict", {"images": images.tolist()})
            code, headers, body = 200, {}, {}
        except urllib.error.HTTPError as exc:
            code = exc.code
            headers = exc.headers
            body = json.loads(exc.read())
        assert code == 503
        assert body["error"] == "no routable backends in the fleet"
        assert body["fleet"][backend.name] == "quarantined"
        assert int(headers["Retry-After"]) >= 1
        try:
            health_code = 200
            router.get("/healthz")
        except urllib.error.HTTPError as exc:
            health_code = exc.code
            exc.read()
        assert health_code == 503
        assert router.get("/stats")["fleet"]["fleet_503s"] >= 1
    finally:
        router.close()
