"""Request-path economics (ISSUE 19): the epoch-stamped response
cache, in-flight collapsing, and cost-priced admission.

Three layers of pins:

- **unit**: ``request_key`` framing, ``ResponseCache`` LRU/byte-budget/
  generation semantics, ``CostModel`` seed geometry + first-observation
  calibration + EWMA, collapse error fan-out on a bare MicroBatcher;
- **swap seams**: every path that changes the answering params —
  engine hot reload, canary publish-reset and PROMOTE — bumps the cache
  generation exactly when it should (and a rejected stale swap does
  not);
- **loopback HTTP**: bitwise hit==miss replies, ``--no-cache``
  byte-identical bodies, zero stale replies across a live reload,
  cost-priced quotas rejecting an expensive-bucket flood while
  admitting cached duplicates, and the router cache invalidating on a
  backend epoch change observed by the health poller.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import synthetic_dataset
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.batcher import MicroBatcher
from pytorch_distributed_mnist_tpu.serve.canary import ShadowCanary
from pytorch_distributed_mnist_tpu.serve.economics import (
    HIT_COST,
    CostModel,
    ResponseCache,
    request_key,
)
from pytorch_distributed_mnist_tpu.serve.engine import InferenceEngine
from pytorch_distributed_mnist_tpu.serve.router import (
    build_parser as router_parser,
)
from pytorch_distributed_mnist_tpu.serve.router import create_router
from pytorch_distributed_mnist_tpu.serve.server import (
    build_parser,
    create_server,
)
from pytorch_distributed_mnist_tpu.train.checkpoint import save_checkpoint
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.economics


# -- unit: key derivation -----------------------------------------------------


def test_request_key_varies_with_every_component():
    base = request_key(b"body", "m", "replicated", "f32")
    assert base == request_key(b"body", "m", "replicated", "f32")
    assert base != request_key(b"bodY", "m", "replicated", "f32")
    assert base != request_key(b"body", "m2", "replicated", "f32")
    assert base != request_key(b"body", "m", "tensor", "f32")
    assert base != request_key(b"body", "m", "replicated", "bf16")


def test_request_key_length_framing_prevents_concat_collisions():
    # Without per-part length framing, raw=b"ab" + model="c" and
    # raw=b"a" + model="bc" would hash the same concatenation.
    assert (request_key(b"ab", "c", "x", "y")
            != request_key(b"a", "bc", "x", "y"))


# -- unit: ResponseCache ------------------------------------------------------


def test_cache_lru_eviction_honors_byte_budget():
    cache = ResponseCache(max_bytes=300)
    for i in range(3):
        assert cache.put(f"k{i}", i, nbytes=100, epoch=0, generation=0)
    # Touch k0 so k1 is the LRU victim when k3 arrives.
    assert cache.get("k0")[0] == 0
    assert cache.put("k3", 3, nbytes=100, epoch=0, generation=0)
    snap = cache.snapshot()
    assert snap["bytes"] <= 300 and snap["evictions"] == 1
    assert cache.get("k1")[0] is None  # evicted
    assert cache.get("k0")[0] == 0  # kept: recently used
    # An entry bigger than the whole budget is refused outright.
    assert not cache.put("huge", 9, nbytes=301, epoch=0, generation=0)


def test_cache_generation_invalidates_without_scanning():
    cache = ResponseCache(max_bytes=1 << 20)
    assert cache.put("k", "v", nbytes=10, epoch=0,
                     generation=cache.generation)
    cache.bump_generation()
    # Old-generation entry reads as a MISS (and is dropped lazily).
    assert cache.get("k")[0] is None
    # An insert stamped with the pre-bump generation is refused.
    assert not cache.put("k2", "v", nbytes=10, epoch=0, generation=0)
    snap = cache.snapshot()
    assert snap["generation"] == 1 and snap["stale_drops"] == 1
    # Current-generation traffic proceeds normally.
    assert cache.put("k3", "w", nbytes=10, epoch=1,
                     generation=cache.generation)
    assert cache.get("k3")[0] == "w"


def test_disabled_cache_is_inert():
    cache = ResponseCache(max_bytes=0)
    assert not cache.enabled
    assert not cache.put("k", "v", nbytes=1, epoch=0, generation=0)
    assert cache.get("k")[0] is None


# -- unit: CostModel ----------------------------------------------------------


def test_cost_model_seed_geometry_then_calibrated_measurement():
    m = CostModel([1, 8, 32])
    # Seeded: cost proportional to bucket rows, normalized to smallest.
    assert m.price(1) == 1.0
    assert m.price(8) == 8.0
    assert m.price(9) == 32.0  # rides the 32 bucket
    # First observation calibrates the still-seeded buckets onto the
    # measured unit: the 8-bucket measures 4ms, so relative prices are
    # unchanged until other buckets get their own measurements.
    m.observe(8, 0.004)
    assert m.price(1) == 1.0 and m.price(8) == 8.0
    # The 1-bucket then measures 2ms: an 8-row batch is only 2x the
    # 1-row batch on this box, whatever the geometry claimed.
    m.observe(1, 0.002)
    assert m.price(8) == 2.0
    # EWMA refresh (alpha=0.2): 0.8*0.004 + 0.2*0.008 = 0.0048.
    m.observe(8, 0.008)
    assert m.price(8) == pytest.approx(2.4)
    snap = m.snapshot()
    assert snap["observed_batches"] == {"1": 1, "8": 2, "32": 0}


def test_cost_model_price_floor_is_hit_cost():
    m = CostModel([1, 8])
    m.observe(1, 1.0)
    m.observe(8, 1e-9)  # degenerate measurement
    assert m.price(8) == HIT_COST


# -- unit: collapse error fan-out --------------------------------------------


def test_collapsed_follower_error_fanout_exactly_once():
    """One failing dispatch, five joined clients: the error reaches
    every joiner exactly once (one raise per result() call), the infer
    ran once, and the collapse key is retired so the NEXT identical
    request gets a fresh pending."""
    calls = []

    def failing_infer(images):
        calls.append(images.shape[0])
        raise RuntimeError("injected batch death")

    rows = np.zeros((1, 4), np.float32)
    with MicroBatcher(failing_infer, max_batch=64,
                      max_wait_s=0.3) as b:
        leader = b.submit(rows, collapse_key="k")
        followers = [b.submit(rows, collapse_key="k") for _ in range(4)]
        assert all(f is leader for f in followers)
        assert b.collapsed == 4

        raises = []
        lock = threading.Lock()

        def wait_one():
            try:
                b.result(leader, timeout=10.0)
            except RuntimeError as exc:
                with lock:
                    raises.append(str(exc))

        threads = [threading.Thread(target=wait_one) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(15.0)
        assert len(raises) == 5
        assert all("injected batch death" in r for r in raises)
        assert calls == [1]  # ONE dispatch for five clients

        # The key was retired at dispatch: a new identical request is a
        # fresh pending, not a join onto the dead leader.
        fresh = b.submit(rows, collapse_key="k")
        assert fresh is not leader
        with pytest.raises(RuntimeError):
            b.result(fresh, timeout=10.0)


def test_collapse_key_retired_at_dispatch_then_recomputes():
    """A duplicate arriving AFTER its leader dispatched queues normally
    (the response cache, not the collapser, handles post-completion
    duplicates)."""
    done = threading.Event()

    def slow_infer(images):
        done.wait(5.0)
        return images

    rows = np.zeros((1, 4), np.float32)
    with MicroBatcher(slow_infer, max_batch=1, max_wait_s=0.01) as b:
        leader = b.submit(rows, collapse_key="k")
        # max_batch=1 dispatches the leader immediately; wait until it
        # leaves the queue (the worker is now blocked inside infer).
        deadline = time.perf_counter() + 5.0
        while b.queue_depth() and time.perf_counter() < deadline:
            time.sleep(0.005)
        late = b.submit(rows, collapse_key="k")
        assert late is not leader
        done.set()
        assert b.result(leader, timeout=10.0) is not None
        assert b.result(late, timeout=10.0) is not None


# -- swap seams: who bumps the generation ------------------------------------


@pytest.fixture(scope="module")
def linear_setup():
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    return model, state


def test_engine_reload_bumps_generation_stale_swap_does_not(linear_setup):
    model, state = linear_setup
    engine = InferenceEngine(model.apply, state.params, buckets=(4,))
    cache = ResponseCache(max_bytes=1 << 20)
    engine.add_swap_hook(cache.bump_generation)
    assert engine.swap_params(state.params, epoch=3)
    assert cache.generation == 1
    # A STALE publish is rejected by the swap-ordering rule and must
    # not invalidate anything: nothing changed.
    assert not engine.swap_params(state.params, epoch=1)
    assert cache.generation == 1


class _StubPlane:
    """Minimal canary plane: logits_fn drives agree/disagree."""

    def __init__(self, logits_fn):
        self.logits_fn = logits_fn
        self.epoch = 0

    @property
    def params_epoch(self):
        return self.epoch

    def preprocess(self, images):
        return np.asarray(images, np.float32)

    def warmup(self):
        pass

    def dispatch(self, images):
        return np.asarray(images, np.float32)

    def complete(self, handle):
        return self.logits_fn(handle), self.epoch

    def swap_params(self, params, epoch=None, path=None):
        self.epoch = epoch
        return 1


def _spiked(x):
    out = np.zeros((x.shape[0], 10), np.float32)
    out[:, 0] = 5.0
    return out


def test_canary_promote_and_publish_reset_bump_generation():
    canary = ShadowCanary(_StubPlane(_spiked), _StubPlane(_spiked),
                          "bf16", fraction=1.0, promote_after=8,
                          budget=0.1)
    cache = ResponseCache(max_bytes=1 << 20)
    canary.add_swap_hook(cache.bump_generation)
    # Clean shadowed rows walk the canary to PROMOTE: the answering
    # plane changes, so cached baseline answers must die with it.
    while canary.snapshot()["state"] != "primary":
        canary.complete(canary.dispatch(np.zeros((4, 4), np.float32)))
    assert cache.generation == 1
    # A fresh publish resets the cycle — and bumps again.
    canary.swap_params(None, epoch=9)
    assert cache.generation == 2


# -- loopback HTTP ------------------------------------------------------------


def _publish(ckpt_dir, epoch, seed):
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(seed))
    save_checkpoint(state, epoch=epoch, best_acc=0.5, is_best=False,
                    directory=str(ckpt_dir), process_index=0)
    return state


def _serve_args(ckpt_dir, **overrides):
    argv = [
        "--checkpoint-dir", str(ckpt_dir),
        "--model", "linear", "--dtype", "f32",
        "--host", "127.0.0.1", "--port", "0",
        "--buckets", "1,8,32",
        "--max-wait-ms", "2", "--max-queue", "128",
        "--poll-interval", "0.1",
    ]
    for k, v in overrides.items():
        flag = "--" + k.replace("_", "-")
        if v is True:
            argv.append(flag)
        else:
            argv += [flag, str(v)]
    return build_parser().parse_args(argv)


class _Httpd:
    def __init__(self, httpd, ready_attr="ctx"):
        self.httpd = httpd
        host, port = httpd.server_address[:2]
        self.url = f"http://{host}:{port}"
        self.name = f"{host}:{port}"
        self.thread = threading.Thread(
            target=httpd.serve_forever, daemon=True)
        self.thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.close()
        self.httpd.server_close()
        self.thread.join(10.0)

    def get(self, path):
        with urllib.request.urlopen(self.url + path, timeout=30) as r:
            return json.loads(r.read())

    def post_raw(self, body):
        """POST pre-serialized bytes; returns (reply_dict, x_cache)."""
        req = urllib.request.Request(
            self.url + "/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read()), r.headers.get("X-Cache")


def _dup_body(seed=5, n=3, client_id=None, rows28=True):
    rng = np.random.RandomState(seed)
    shape = (n, 28, 28)
    payload = {"images": rng.randint(0, 256, shape).tolist()}
    if client_id:
        payload["client_id"] = client_id
    return json.dumps(payload).encode()


@pytest.fixture()
def cached_server(tmp_path):
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Httpd(create_server(_serve_args(ckpt)))
    try:
        yield srv, ckpt
    finally:
        srv.close()


def test_bitwise_hit_equals_miss(cached_server):
    srv, _ = cached_server
    body = _dup_body()
    miss, miss_verdict = srv.post_raw(body)
    hit, hit_verdict = srv.post_raw(body)
    assert (miss_verdict, hit_verdict) == ("miss", "hit")
    assert hit["predictions"] == miss["predictions"]
    assert hit["model_epoch"] == miss["model_epoch"] == 0
    stats = srv.get("/stats")
    assert stats["cache"]["hits"] >= 1
    assert stats["cache"]["generation"] == 0
    # A hit is a SERVED request: totals stay honest.
    assert stats["requests"] >= 2


def test_no_cache_serves_byte_identical_body(tmp_path):
    """--no-cache must serve the same BYTES (modulo the per-request
    latency_ms) as the cached path — the cache is a pure accelerator,
    never a behavior change."""
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    body = _dup_body()
    cached = _Httpd(create_server(_serve_args(ckpt)))
    try:
        cached_replies = [srv_reply for srv_reply, _ in
                          (cached.post_raw(body), cached.post_raw(body))]
    finally:
        cached.close()
    plain = _Httpd(create_server(_serve_args(ckpt, no_cache=True)))
    try:
        plain_reply, verdict = plain.post_raw(body)
        assert verdict is None  # no cache, no X-Cache header
        assert "cache" not in plain.get("/stats")
    finally:
        plain.close()
    for reply in cached_replies + [plain_reply]:
        reply.pop("latency_ms")
    assert cached_replies[0] == cached_replies[1] == plain_reply


def test_reload_invalidates_zero_stale_replies(cached_server):
    srv, ckpt = cached_server
    body = _dup_body()
    warm, verdict = srv.post_raw(body)
    assert srv.post_raw(body)[1] == "hit"
    assert warm["model_epoch"] == 0

    _publish(ckpt, epoch=2, seed=99)  # different params entirely
    deadline = time.perf_counter() + 30.0
    while time.perf_counter() < deadline:
        if srv.get("/healthz").get("model_epoch") == 2:
            break
        time.sleep(0.05)
    assert srv.get("/healthz")["model_epoch"] == 2

    # EVERY post-swap reply carries the new epoch — the first recomputes
    # (the generation bump made the old entry unreachable), the repeats
    # hit the re-cached entry; none may replay epoch 0.
    verdicts = []
    for _ in range(4):
        reply, verdict = srv.post_raw(body)
        verdicts.append(verdict)
        assert reply["model_epoch"] == 2
    assert verdicts[0] == "miss" and "hit" in verdicts[1:]
    assert srv.get("/stats")["cache"]["generation"] >= 1


def test_cost_priced_quota_rejects_expensive_flood_admits_hits(tmp_path):
    """With --price-admission, a client's token bucket drains in COST
    units: 32-row requests price at the seeded 32x (never observed —
    they are rejected before computing), which can NEVER fit a 4-token
    burst, while cached duplicates (priced HIT_COST) keep flowing on
    the same bucket. A plain request-counted quota would treat both
    identically."""
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    srv = _Httpd(create_server(_serve_args(
        ckpt, price_admission=True, quota_rps="2")))
    try:
        dup = _dup_body(seed=1, n=1, client_id="spender")
        first, _ = srv.post_raw(dup)  # compute once, cache it (cost ~1)

        statuses = []
        for i in range(4):
            rng = np.random.RandomState(100 + i)
            big = json.dumps({
                "images": rng.randint(0, 256, (32, 28, 28)).tolist(),
                "client_id": "spender"}).encode()
            try:
                srv.post_raw(big)
                statuses.append(200)
            except urllib.error.HTTPError as exc:
                statuses.append(exc.code)
                if exc.code == 429:
                    assert exc.headers.get("Retry-After") is not None
                exc.read()
        # 32 units a pop against a 4-token burst: every flood request
        # is clipped (and, never having computed, the 32-bucket keeps
        # its seeded price — the assertion is deterministic).
        assert statuses == [429, 429, 429, 429]

        # The SAME drained client keeps its cached duplicates: each
        # costs HIT_COST, not a full unit.
        for _ in range(10):
            reply, verdict = srv.post_raw(dup)
            assert reply["predictions"] == first["predictions"]
        assert verdict == "hit"
        assert srv.get("/stats")["cost_model"]["buckets"] == [1, 8, 32]
    finally:
        srv.close()


def test_router_cache_invalidated_on_backend_epoch_change(tmp_path):
    ckpt = tmp_path / "ckpt"
    _publish(ckpt, epoch=0, seed=10)
    backend = _Httpd(create_server(_serve_args(ckpt)))
    router = None
    try:
        router = _Httpd(create_router(router_parser().parse_args([
            "--backends", backend.name,
            "--host", "127.0.0.1", "--port", "0",
            "--health-interval", "0.1", "--connect-timeout", "2.0",
            "--cache-mb", "16"])))
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            try:
                if router.get("/healthz").get("routable") == 1:
                    break
            except OSError:
                pass
            time.sleep(0.05)

        body = _dup_body()
        warm, _ = router.post_raw(body)
        assert warm["model_epoch"] == 0
        reply, verdict = router.post_raw(body)
        assert verdict == "hit" and reply == warm

        _publish(ckpt, epoch=2, seed=99)
        # The backend reloads; the router's health poller observes the
        # epoch change and bumps the router cache generation.
        while time.perf_counter() < deadline:
            stats = router.get("/stats")
            rows = stats.get("backends", [])
            if rows and rows[0].get("epoch") == 2:
                break
            time.sleep(0.05)
        for _ in range(3):
            reply, _ = router.post_raw(body)
            assert reply["model_epoch"] == 2  # never the cached epoch-0
        assert router.get("/stats")["cache"]["generation"] >= 1
    finally:
        if router is not None:
            router.close()
        backend.close()


def test_stats_cache_block_schema_and_collapse_counter(cached_server):
    srv, _ = cached_server
    images, _ = synthetic_dataset(2, seed=1)
    srv.post_raw(json.dumps({"images": images.tolist()}).encode())
    block = srv.get("/stats")["cache"]
    assert {"hits", "misses", "hit_rate", "hit_bytes", "evictions",
            "stale_drops", "generation", "entries", "bytes",
            "capacity_bytes", "collapsed"} <= set(block)
