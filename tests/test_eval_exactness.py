"""Regression tests for review findings: eval padding must not double-count,
empty loaders must not crash, start-epoch precedence, sampler pad masks."""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_mnist_tpu.data.loader import MNISTDataLoader
from pytorch_distributed_mnist_tpu.data.sampler import DistributedShardSampler
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer


def _loader(images, labels, bs, **kw):
    return MNISTDataLoader(images, labels, batch_size=bs, **kw)


def test_eval_counts_each_sample_exactly_once():
    """110 samples, batch 20 -> 6 padded batches, but count must be 110."""
    rng = np.random.default_rng(0)
    images = rng.normal(size=(110, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(110) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    test_loader = _loader(images, labels, 20, train=False)
    train_loader = _loader(images, labels, 20, train=True)
    for mode in ("scan", "stepwise"):
        trainer = Trainer(state, train_loader, test_loader, mode=mode)
        loss, acc = trainer.evaluate()
        assert acc.count == 110, mode  # not 120
        assert loss.count == 110, mode


def test_eval_metrics_match_unpadded_truth():
    """Masked padded eval == direct computation over exactly the 110 samples."""
    rng = np.random.default_rng(1)
    images = rng.normal(size=(110, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(110) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    loader = _loader(images, labels, 20, train=False)
    trainer = Trainer(state, loader, loader, mode="scan")
    loss, acc = trainer.evaluate()

    logits = model.apply(state.params, jnp.asarray(images))
    pred = np.argmax(np.asarray(logits), axis=-1)
    true_acc = float((pred == labels).mean())
    np.testing.assert_allclose(acc.accuracy, true_acc, atol=1e-9)


def test_sharded_eval_pad_not_counted():
    """10 samples over 4 replicas: 12 slots, 2 pads -> global count == 10."""
    total = 0
    for r in range(4):
        s = DistributedShardSampler(10, 4, r, shuffle=False)
        _, valid = s.indices_and_mask()
        total += int(valid.sum())
    assert total == 10


def test_empty_loader_returns_empty_meters():
    images = np.zeros((8, 28, 28, 1), np.float32)
    labels = np.zeros(8, np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    # batch 16 > 8 samples with drop_last -> zero steps
    loader = _loader(images, labels, 16, train=True)
    assert loader.steps_per_epoch == 0
    trainer = Trainer(state, loader, loader, mode="stepwise")
    loss, acc = trainer.train()
    assert loss.average == 0.0 and acc.count == 0  # no crash


def test_start_epoch_flag_vs_resume_precedence(tmp_path):
    from tests.test_integration import make_args
    from pytorch_distributed_mnist_tpu.cli import run

    run(make_args(tmp_path, epochs=2))
    # Checkpoint epoch (2) must win over --start-epoch 0/1.
    out = run(make_args(tmp_path, epochs=3, start_epoch=1,
                        resume=str(tmp_path / "ckpt" / "checkpoint_1.npz")))
    assert [h["epoch"] for h in out["history"]] == [2]
    # Fresh run: the flag applies.
    out2 = run(make_args(tmp_path, epochs=3, start_epoch=2,
                         checkpoint_dir=str(tmp_path / "ckpt2")))
    assert [h["epoch"] for h in out2["history"]] == [2]
