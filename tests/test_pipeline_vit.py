"""Pipeline-parallel ViT (parallel/pipeline_vit.py): a REAL model through
the GPipe machinery — forward parity vs the sequential flax module,
train-step parity vs the non-pipelined step, the CLI path, and the
layout's error surface.

The reference has no PP at all (SURVEY.md section 2c); the bar here is
self-consistency: the pipelined program must be numerically the same model
as ``VisionTransformer.apply``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
    create_pipelined_vit_state,
    make_pipelined_vit_apply,
    merge_vit_params,
    pipelined_state_sharding,
    split_vit_params,
)


def _model(depth=4):
    return get_model("vit", compute_dtype=jnp.float32, depth=depth)


def _params(model, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, 28, 28, 1)))


def test_split_merge_round_trip():
    model = _model()
    params = _params(model)
    merged = merge_vit_params(split_vit_params(params))
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(merged)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "mesh_axes,shape,data_axis,depth",
    [
        (("data", "stage"), (2, 4), "data", 4),   # DP x PP, 1 block/stage
        (("data", "stage"), (4, 2), "data", 4),   # DP x PP, 2 blocks/stage
        (("stage",), (8,), None, 8),              # pure PP
    ],
)
def test_pipelined_forward_matches_sequential(mesh_axes, shape, data_axis,
                                              depth):
    model = _model(depth)
    params = _params(model)
    x = jax.random.normal(jax.random.key(1), (16, 28, 28, 1))
    ref = model.apply(params, x)
    mesh = make_mesh(mesh_axes, shape=shape)
    apply_fn = make_pipelined_vit_apply(model, mesh, data_axis=data_axis)
    out = apply_fn(split_vit_params(params), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pipelined_train_step_matches_unpipelined(tiny_data):
    """One optimizer step through the pipeline == one step of the plain
    model (same init, same batch): gradients flow correctly through
    scan + ppermute + the replicated embed/head."""
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    model = _model(depth=4)
    images, labels = tiny_data
    batch = {"image": jnp.asarray(images[:32]),
             "label": jnp.asarray(labels[:32])}

    ref_state = create_train_state(model, jax.random.key(0))
    ref_step = make_train_step()
    ref_state, ref_m = ref_step(ref_state, batch)

    mesh = make_mesh(("data", "stage"), shape=(2, 4))
    pp_state, pp_sharding = create_pipelined_vit_state(
        model, jax.random.key(0), mesh, data_axis="data"
    )
    pp_step = make_train_step(mesh, state_sharding=pp_sharding)
    from pytorch_distributed_mnist_tpu.data.loader import make_global_batch

    pp_state, pp_m = pp_step(pp_state, make_global_batch(
        {k: np.asarray(v) for k, v in batch.items()}, mesh))

    assert float(pp_m.loss_sum) == pytest.approx(float(ref_m.loss_sum),
                                                 rel=1e-5)
    # Compare GRADIENTS, not post-Adam params: leaves whose true gradient
    # is ~0 (e.g. the k-bias inside qkv — softmax is shift-invariant) get
    # an Adam update of sign(noise) * lr, so the params would differ by a
    # full lr from microbatch-summation noise while the model is exact.
    from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy

    def grads_of(apply_fn, params):
        def loss_fn(p):
            return cross_entropy(apply_fn(p, batch["image"], train=True),
                                 batch["label"])
        return jax.grad(loss_fn)(params)

    ref_grads = grads_of(model.apply, create_train_state(
        model, jax.random.key(0)).params)
    pp_grads = merge_vit_params(grads_of(
        pp_state.apply_fn,
        create_pipelined_vit_state(model, jax.random.key(0), mesh,
                                   data_axis="data")[0].params,
    ))
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(ref_grads)[0],
        jax.tree_util.tree_flatten_with_path(pp_grads)[0],
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(pa),
        )


def test_blocks_actually_sharded_on_stage(mesh8):
    model = _model(depth=4)
    mesh = make_mesh(("data", "stage"), shape=(2, 4))
    state, _ = create_pipelined_vit_state(model, jax.random.key(0), mesh,
                                          data_axis="data")
    qkv = state.params["blocks"]["attn"]["qkv"]["kernel"]
    assert qkv.shape[0] == 4  # leading depth dim
    assert qkv.sharding.spec == jax.sharding.PartitionSpec("stage")
    # moments mirror the layout
    mu = jax.tree.leaves(state.opt_state.inner_state[0].mu["blocks"])[0]
    assert mu.sharding.spec == jax.sharding.PartitionSpec("stage")


def test_depth_not_divisible_raises():
    model = _model(depth=3)
    mesh = make_mesh(("data", "stage"), shape=(4, 2))
    with pytest.raises(ValueError, match="not divisible"):
        make_pipelined_vit_apply(model, mesh)


@pytest.mark.slow
def test_cli_pipeline_end_to_end(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit",
        "--pipeline-stages", "2", "--epochs", "1", "--batch-size", "64",
        "--synthetic-train-size", "256", "--synthetic-test-size", "128",
        "--seed", "0",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    summary = run(args)
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


def test_cli_pipeline_rejects_non_vit(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "cnn",
        "--pipeline-stages", "2", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="requires --model vit"):
        run(args)


@pytest.mark.slow
def test_pipelined_remat_same_loss_and_grads():
    """--remat through the pipeline: jax.checkpoint around each block in
    the stage scan must not change loss or gradients."""
    import numpy as np

    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.ops.loss import cross_entropy
    from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
        create_pipelined_vit_state,
    )

    mesh_dp_pp = make_mesh(("data", "stage"), shape=(4, 2))
    x = jax.random.normal(jax.random.key(0), (8, 28, 28, 1), jnp.float32)
    y = jnp.arange(8, dtype=jnp.int32) % 10

    outs = []
    for remat in (False, True):
        model = get_model("vit", compute_dtype=jnp.float32, depth=2,
                          remat=remat)
        state, _ = create_pipelined_vit_state(
            model, jax.random.key(1), mesh_dp_pp, data_axis="data")

        def loss_fn(p, apply=state.apply_fn):
            return cross_entropy(apply(p, x), y)

        l, g = jax.value_and_grad(loss_fn)(state.params)
        outs.append((float(l), g))
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[0][1]), jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_pipeline_zero1_matches_pipeline_only():
    """PP x ZeRO-1: stage-sharded block moments gain a data axis; the
    training trajectory must equal the pipeline-only step."""
    from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step

    mesh = make_mesh(("data", "stage"), shape=(4, 2))
    x = jax.random.normal(jax.random.key(0), (8, 28, 28, 1), jnp.float32)
    y = jnp.arange(8, dtype=jnp.int32) % 10
    batch = {"image": x, "label": y}

    model = get_model("vit", compute_dtype=jnp.float32, depth=2)

    def run_steps(with_zero):
        state, sharding = create_pipelined_vit_state(
            model, jax.random.key(1), mesh, data_axis="data")
        if with_zero:
            state, sharding = shard_state_zero(
                state, mesh, base_sharding=sharding, level=1)
        step = make_train_step(mesh, state_sharding=sharding)
        for _ in range(2):
            state, m = step(state, batch)
        return state, m, sharding

    s0, m0, _ = run_steps(False)
    s1, m1, sh1 = run_steps(True)
    np.testing.assert_allclose(float(m0.loss_sum), float(m1.loss_sum),
                               rtol=1e-6)
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # the ZeRO layout actually sharded a stage-sharded block moment over
    # data as well (stage x data), not just the replicated embed/head
    specs = [s.spec for s in jax.tree.leaves(sh1.opt_state)]
    assert any("stage" in str(sp) and "data" in str(sp) for sp in specs)


@pytest.mark.slow
def test_pipeline_zero1_cli(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    s = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "vit",
        "--pipeline-stages", "2", "--optimizer-sharding", "zero1",
        "--batch-size", "32", "--synthetic-train-size", "64",
        "--synthetic-test-size", "32", "--seed", "0", "--epochs", "1",
        "--checkpoint-dir", str(tmp_path), "--trainer-mode", "stepwise",
    ]))
    assert s["epochs_run"] == 1


def test_pipeline_zero3_rejected(tmp_path):
    import pytest

    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    with pytest.raises(SystemExit, match="zero1"):
        run(build_parser().parse_args([
            "--dataset", "synthetic", "--model", "vit",
            "--pipeline-stages", "2", "--optimizer-sharding", "zero3",
            "--checkpoint-dir", str(tmp_path),
        ]))
