"""Fixture suite: the lock-discipline checker + the real lock graph."""

import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from tools.analyzer import analyze_snippet, run_analysis  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src):
    return analyze_snippet(src, checkers=["lock-discipline"])


# -- firing ------------------------------------------------------------------


def test_fires_on_device_put_under_lock():
    src = """
import threading, jax

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def swap_params(self, params):
        with self._lock:
            self._params = jax.device_put(params)
"""
    (f,) = _findings(src)
    assert "device_put" in f.message and "Engine._lock" in f.message


def test_fires_on_file_io_under_lock():
    src = """
import threading

class Sink:
    def __init__(self):
        self._lock = threading.Lock()

    def write(self, line):
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line)
"""
    (f,) = _findings(src)
    assert "file IO" in f.message


def test_fires_on_collective_under_module_lock():
    src = """
import threading

_lock = threading.Lock()

def agreed_update(ok):
    with _lock:
        return allgather_records("phase", ok)
"""
    (f,) = _findings(src)
    assert "collective" in f.message


def test_fires_on_queue_get_and_thread_join_under_lock():
    src = """
import threading

class Batcher:
    def __init__(self):
        self._lock = threading.Lock()

    def drain(self):
        with self._lock:
            item = self._queue.get()
            self._thread.join()
            return item
"""
    assert len(_findings(src)) == 2


def test_fires_on_inconsistent_lock_order():
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._staging_lock = threading.Lock()

    def dispatch(self):
        with self._lock:
            with self._staging_lock:
                return self.free.pop()

    def release(self):
        with self._staging_lock:
            with self._lock:
                self.free.append(None)
"""
    (f,) = _findings(src)
    assert "inconsistent lock order" in f.message
    assert "Pool._lock" in f.message and "Pool._staging_lock" in f.message


def test_fires_on_module_level_with_lock():
    """Init-time code in scripts runs at module scope — blocking work
    under a module-level lock must be checked like function bodies."""
    src = """
import threading

_lock = threading.Lock()

with _lock:
    DATA = open("state.json").read()
"""
    (f,) = _findings(src)
    assert "file IO" in f.message and f.symbol == "<module>"


def test_fires_on_bare_name_collective_under_lock():
    """from-imported collectives call as bare names (the checkpoint.py
    style) — they must be flagged exactly like the attribute form."""
    src = """
import threading
from pytorch_distributed_mnist_tpu.runtime.supervision import _agree_phase_ok

class Writer:
    def __init__(self):
        self._lock = threading.Lock()

    def publish(self, err, epoch):
        with self._lock:
            return _agree_phase_ok(err, epoch, "write", "x")
"""
    (f,) = _findings(src)
    assert "collective" in f.message and "Writer._lock" in f.message


def test_fires_on_blocking_second_with_item_under_lock():
    """``with self._lock, open(...)``: items enter left to right, so the
    open() runs while the lock is held."""
    src = """
import threading

class Sink:
    def __init__(self):
        self._lock = threading.Lock()

    def append(self, path):
        with self._lock, open(path, "a") as f:
            f.write("x")
"""
    (f,) = _findings(src)
    assert "file IO" in f.message


def test_fires_on_three_lock_cycle():
    """A 3-lock ring (A->B, B->C, C->A) deadlocks just as hard as a
    direct inversion — the order graph must be acyclic, not merely free
    of 2-cycles."""
    src = """
import threading

class Trio:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def bc(self):
        with self._b:
            with self._c:
                pass

    def ca(self):
        with self._c:
            with self._a:
                pass
"""
    (f,) = _findings(src)
    assert "acquisition cycle" in f.message
    assert all(name in f.message
               for name in ("Trio._a", "Trio._b", "Trio._c"))


def test_fires_on_nested_same_lock_reacquisition():
    """``with self._lock:`` inside itself is a self-deadlock on a plain
    Lock — reported as a 1-node cycle, not an analyzer crash."""
    src = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            with self._lock:
                pass
"""
    (f,) = _findings(src)
    assert "acquisition cycle" in f.message
    assert "C._lock -> C._lock" in f.message


# -- non-firing --------------------------------------------------------------


def test_silent_on_blocking_with_item_before_lock():
    """``with open(...), self._lock``: the open() completes BEFORE the
    lock is acquired — flagging it would force a bogus baseline entry."""
    src = """
import threading

class Sink:
    def __init__(self):
        self._lock = threading.Lock()

    def append(self, path):
        with open(path, "a") as f, self._lock:
            f.write("x")
"""
    assert _findings(src) == []


def test_silent_on_snapshot_then_operate_after_release():
    """The engine swap_params idiom: slow work outside, reference swap
    under the lock."""
    src = """
import threading, jax

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def swap_params(self, params, epoch):
        placed = jax.device_put(params)
        with self._lock:
            if self._epoch is not None and epoch < self._epoch:
                return False
            self._params = placed
            return True
"""
    assert _findings(src) == []


def test_silent_on_condition_variable_wait():
    src = """
import threading

class Batcher:
    def __init__(self):
        self._cv = threading.Condition()

    def take(self):
        with self._cv:
            while not self._queue:
                self._cv.wait()
            self._cv.notify_all()
            return self._queue.pop(0)
"""
    assert _findings(src) == []


def test_silent_on_str_join_and_dict_get_under_lock():
    """join/get heuristics must not flag strings and dicts."""
    src = """
import threading

class Log:
    def __init__(self):
        self._lock = threading.Lock()

    def snapshot(self, sep):
        with self._lock:
            rec = self._programs.get("name")
            return ", ".join(self._lines) + sep.join(self._lines) + str(rec)
"""
    assert _findings(src) == []


def test_silent_on_consistent_nested_order():
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self._staging_lock = threading.Lock()

    def a(self):
        with self._lock:
            with self._staging_lock:
                pass

    def b(self):
        with self._lock:
            with self._staging_lock:
                pass
"""
    assert _findings(src) == []


# -- the input staging plane (ISSUE 6) ---------------------------------------


def test_fires_on_staging_under_feeder_cv():
    """The exact mistake data/staging.py avoids: running the H2D stage
    (device_put) INSIDE the conduit's condition variable serializes the
    consumer behind the transfer — the feeder must stage outside and
    only append under the lock."""
    src = """
import threading, jax

class EpochRun:
    def __init__(self):
        self._cv = threading.Condition()

    def feed(self, rows):
        for row in rows:
            with self._cv:
                while len(self._staged) >= self.window:
                    self._cv.wait()
                self._staged.append(jax.device_put(row))
                self._cv.notify_all()
"""
    (f,) = _findings(src)
    assert "device_put" in f.message and "EpochRun._cv" in f.message


def test_fires_on_collective_on_feeder_under_cv():
    """A cross-host collective under the feeder's cv is the
    no-concurrent-collectives worst case: the main thread (which owns
    collectives) can be inside its own agreement while the feeder
    blocks peers."""
    src = """
import threading
from pytorch_distributed_mnist_tpu.runtime.supervision import allgather_records

class Feeder:
    def __init__(self):
        self._cv = threading.Condition()

    def feed(self, batch):
        with self._cv:
            allgather_records("stage", batch)
"""
    (f,) = _findings(src)
    assert "collective" in f.message and "Feeder._cv" in f.message


def test_silent_on_stage_outside_append_under_cv():
    """The real feeder shape (data/staging.py::_EpochRun._feed): gather
    and device_put OUTSIDE the lock, bounded-append under it with the
    cv wait/notify exemption."""
    src = """
import threading, jax

class EpochRun:
    def __init__(self):
        self._cv = threading.Condition()

    def feed(self, rows):
        for row in rows:
            staged = jax.device_put(self.gather(row))
            with self._cv:
                while len(self._staged) >= self.window:
                    self._cv.wait()
                self._staged.append(staged)
                self._cv.notify_all()
"""
    assert _findings(src) == []


def test_silent_on_consumer_pop_under_cv():
    """The consumer side (next_batch): wait for a staged batch, pop,
    notify — nothing blocking beyond the cv protocol itself."""
    src = """
import threading

class EpochRun:
    def __init__(self):
        self._cv = threading.Condition()

    def next_batch(self):
        with self._cv:
            while not self._staged and not self._done:
                self._cv.wait()
            batch = self._staged.popleft() if self._staged else None
            self._cv.notify_all()
        return batch
"""
    assert _findings(src) == []


def test_staging_module_clean_and_in_lock_graph():
    """ISSUE 6 acceptance: the staging module's cv is a lock-graph node,
    and the module is clean under lock-discipline AND the thread-facing
    checkers (trace-purity sees the feeder's code; collective-symmetry
    sees no process_index-conditioned work on it)."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "data",
                      "staging.py")],
        checkers=["lock-discipline", "trace-purity", "collective-symmetry"],
        baseline=None)
    assert result.findings == []
    graph = result.reports["lock-discipline"]["lock_graph"]
    staging = graph["pytorch_distributed_mnist_tpu/data/staging.py"]
    assert staging["locks"] == ["_EpochRun._cv"]
    # The conduit cv never nests with another lock — that IS the rule.
    assert staging["order_edges"] == []


# -- the real lock graph -----------------------------------------------------


def test_reports_engine_and_pool_lock_graph():
    """ISSUE 5 acceptance: the engine/pool lock graph is reported."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve")],
        checkers=["lock-discipline"], baseline=None)
    assert result.findings == []  # the serve plane is lock-clean
    graph = result.reports["lock-discipline"]["lock_graph"]
    engine = graph["pytorch_distributed_mnist_tpu/serve/engine.py"]
    # The staging free-list lock moved into the shared StagingPool
    # (ISSUE 12: the MPMD plane reuses the same lifecycle); the params
    # lock stays on the engine.
    assert set(engine["locks"]) == {"InferenceEngine._lock",
                                    "StagingPool._lock"}
    # The two locks are never nested — that IS the discipline.
    assert engine["order_edges"] == []
    pool = graph["pytorch_distributed_mnist_tpu/serve/pool.py"]
    assert pool["locks"] == ["EnginePool._lock"]
    batcher = graph["pytorch_distributed_mnist_tpu/serve/batcher.py"]
    assert batcher["locks"] == ["MicroBatcher._cv"]


# -- the serving-mesh placement shape (ISSUE 8, serve/programs.py) -----------


def test_fires_on_mesh_place_params_under_engine_lock():
    """The sharded swap gone wrong: committing the checkpoint to the
    mesh (device_put with the NamedSharding tree — the slow part)
    while holding the engine lock stalls every dispatch for the full
    H2D wall."""
    src = """
import threading, jax

class ShardedEngine:
    def __init__(self, placement):
        self._lock = threading.Lock()
        self.placement = placement

    def swap_params(self, params, epoch):
        with self._lock:
            self._params = jax.device_put(params,
                                          self.placement.param_shardings)
            self._params_epoch = epoch
"""
    (f,) = _findings(src)
    assert "device_put" in f.message and "ShardedEngine._lock" in f.message


def test_fires_on_group_fanout_device_put_under_pool_lock():
    """A pool fan-out that walks mesh groups UNDER the pool lock while
    re-placing params per group serializes the whole fleet behind N
    device_puts."""
    src = """
import threading, jax

class Pool:
    def __init__(self, groups):
        self._lock = threading.Lock()
        self.groups = groups

    def swap_params(self, params):
        with self._lock:
            for group in self.groups:
                group.params = jax.device_put(params, group.shardings)
"""
    (f,) = _findings(src)
    assert "device_put" in f.message and "Pool._lock" in f.message


def test_silent_on_place_outside_install_under_lock():
    """The sanctioned sharded swap (the engine's rule, unchanged by the
    mesh plane): the NamedSharding device_put runs OUTSIDE the lock;
    only the reference install + epoch compare happen under it."""
    src = """
import threading, jax

class ShardedEngine:
    def __init__(self, placement):
        self._lock = threading.Lock()
        self.placement = placement

    def swap_params(self, params, epoch):
        placed = jax.device_put(params, self.placement.param_shardings)
        with self._lock:
            if self._params_epoch is not None and epoch < self._params_epoch:
                return False
            self._params = placed
            self._params_epoch = epoch
            return True
"""
    assert _findings(src) == []


def test_silent_on_lock_free_mesh_group_build():
    """Building mesh groups (mesh construction, sharding derivation,
    pjit lowering) is lock-free by design — nothing here may ever need
    a lock-graph node."""
    src = """
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

def build_groups(devices, mesh_size, axis, params):
    groups = []
    for i in range(0, len(devices), mesh_size):
        mesh = Mesh(devices[i:i + mesh_size], (axis,))
        groups.append(jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P()), params))
    return groups
"""
    assert _findings(src) == []


# -- the elastic supervisor shape (ISSUE 10, runtime/elastic.py) -------------


def test_fires_on_worker_join_under_membership_lock():
    """The elastic supervisor shape gone wrong: holding the membership
    lock while joining a worker's exit — a stalled worker (the exact
    mid-rebuild failure the settle deadline exists for) would wedge
    every reader of the membership."""
    src = """
import threading

_members_lock = threading.Lock()
_members = [0, 1, 2]

def collect_generation(threads):
    with _members_lock:
        for t in threads:
            t.join()
        return list(_members)
"""
    assert len(_findings(src)) >= 1


def test_fires_on_survivor_record_io_under_membership_lock():
    """Record file I/O under the membership lock: a slow shared
    filesystem write (the rendezvous dir is exactly that) blocks every
    membership reader for the duration."""
    src = """
import json
import threading

_members_lock = threading.Lock()

def persist_vote(path, record):
    with _members_lock:
        with open(path, "w") as f:
            json.dump(record, f)
"""
    assert len(_findings(src)) >= 1


def test_silent_on_snapshot_members_then_write_record():
    """The sanctioned shape: snapshot the membership under the lock,
    do the file I/O after release (the survivor-record write in
    runtime/elastic.py is lock-free end to end — atomic tmp+replace,
    one writer per rank by construction)."""
    src = """
import json
import threading

_members_lock = threading.Lock()
_members = [0, 1, 2]

def persist_vote(path):
    with _members_lock:
        snapshot = list(_members)
    with open(path + ".tmp", "w") as f:
        json.dump({"members": snapshot}, f)
"""
    assert _findings(src) == []


def test_silent_on_membership_mutation_under_lock():
    """Pure membership bookkeeping under the lock — list mutation and
    arithmetic only — is what the lock is FOR."""
    src = """
import threading

_members_lock = threading.Lock()
_members = [0, 1, 2]

def shrink(dead):
    with _members_lock:
        for host in dead:
            if host in _members:
                _members.remove(host)
        return len(_members)
"""
    assert _findings(src) == []


# -- the self-healing/regroup shape (ISSUE 11, serve/pool.py grow) -----------


def test_fires_on_regroup_warm_join_under_pool_lock():
    """The regroup gone wrong: holding the pool lock across the rebuilt
    engine's parallel warm (thread joins — the whole AOT compile wall)
    wedges every dispatcher and /stats reader for the rebuild's
    duration. The sanctioned shape builds + warms outside and installs
    the reference under the lock."""
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def regroup(self, replica, build_engine):
        with self._lock:
            engine = build_engine(replica.devices)
            threads = [threading.Thread(target=engine.warmup)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            replica.engine = engine
"""
    assert len(_findings(src)) >= 1


def test_fires_on_join_record_io_under_supervisor_lock():
    """Join-record IO under a lock: reading the rendezvous dir's
    announcements (shared-filesystem file I/O) while holding a
    membership lock blocks every reader for the listing's duration —
    the same shape as the survivor-record firing twin, on the grow
    path."""
    src = """
import json
import threading

_members_lock = threading.Lock()

def admit_joiners(directory, members):
    with _members_lock:
        with open(f"{directory}/join_h00001.json") as f:
            record = json.load(f)
        members.append(record["host"])
        return members
"""
    assert len(_findings(src)) >= 1


def test_silent_on_regroup_warm_outside_install_under():
    """The sanctioned regroup (serve/pool.py::_regroup): snapshot the
    latest params under the lock, build + warm the replacement engine
    with no lock held, install the reference (and clear quarantine)
    under it."""
    src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def regroup(self, replica, build_engine):
        with self._lock:
            params = self._params_host
        engine = build_engine(replica.devices, params)
        engine.warmup()
        with self._lock:
            replica.engine = engine
            replica.quarantined = False
            replica.generation += 1
"""
    assert _findings(src) == []


def test_elastic_module_clean_and_lock_free():
    """ISSUE 10/11 acceptance pin: runtime/elastic.py stays clean under
    the collective-symmetry, lock-discipline, and trace-purity
    checkers — the worker-side unwind path runs NO collectives (votes
    are files), the grow rendezvous runs its ONE agreement collective
    unconditionally on every rank (only the dir listing is
    rank-0-gated), the supervisor holds no locks (one thread, poll
    loop), and nothing traces."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "runtime",
                      "elastic.py")],
        checkers=["lock-discipline", "trace-purity",
                  "collective-symmetry"],
        baseline=None)
    assert result.findings == []
    graph = result.reports["lock-discipline"]["lock_graph"]
    elastic_graph = graph.get(
        "pytorch_distributed_mnist_tpu/runtime/elastic.py", {})
    assert elastic_graph.get("locks", []) == []


# -- MPMD pipeline-serving shapes (serve/pipeline.py, ISSUE 12) --------------


def test_fires_on_stage_stream_dispatch_under_engine_lock():
    """FIRING: streaming a micro-batch to the next stage (the D2D
    device_put hop + the stage program call) while still holding the
    engine lock — the whole chain's device work would serialize behind
    every params capture and swap."""
    src = """
import threading, jax

class PipelineEngine:
    def __init__(self):
        self._lock = threading.Lock()

    def dispatch(self, x):
        with self._lock:
            for stage in self._stages:
                x = jax.device_put(x, stage.sharding)
                x = stage.run(self._stage_params[stage.index], x)
        return x
"""
    findings = _findings(src)
    assert findings and any("device_put" in f.message
                            and "PipelineEngine._lock" in f.message
                            for f in findings)


def test_silent_on_stage_params_snapshot_then_stream():
    """NON-FIRING twin: the shipped shape — capture the per-stage params
    list (the cross-stage swap-atomicity boundary) under the lock, then
    stream the chain entirely outside it."""
    src = """
import threading, jax

class PipelineEngine:
    def __init__(self):
        self._lock = threading.Lock()

    def dispatch(self, x):
        with self._lock:
            stage_params = list(self._stage_params)
        for stage, params in zip(self._stages, stage_params):
            x = jax.device_put(x, stage.sharding)
            x = stage.run(params, x)
        return x
"""
    assert _findings(src) == []


def test_pipeline_module_clean_and_in_lock_graph():
    """serve/pipeline.py itself: its engine lock shows up in the lock
    graph (it IS a lock-holding module) with zero findings — the
    snapshot-then-stream discipline the fixtures above pin."""
    path = os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                        "pipeline.py")
    result = run_analysis([path], checkers=["lock-discipline"],
                          baseline=None)
    assert result.findings == []
    graph = result.reports["lock-discipline"]["lock_graph"]
    module = graph["pytorch_distributed_mnist_tpu/serve/pipeline.py"]
    assert "PipelineEngine._lock" in module["locks"]


# -- the quantize plane (ISSUE 14) -------------------------------------------


def test_fires_on_quantize_and_place_under_engine_lock():
    """Install-time quantization is the SLOW part of a quantized swap
    (per-leaf max reductions + the device_put that follows): doing it
    under the engine lock stalls every dispatch's params capture for
    the whole quantize+transfer."""
    src = """
import threading, jax

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def swap_params(self, params):
        with self._lock:
            quantized = self.spec.quantize(params)
            self._params = jax.device_put(quantized)
"""
    (f,) = _findings(src)
    assert "device_put" in f.message and "Engine._lock" in f.message


def test_silent_on_quantize_then_install_under_lock():
    """The shipped shape (serve/engine.py::_place from swap_params):
    quantize + device_put OUTSIDE the lock, the reference swap alone
    under it."""
    src = """
import threading, jax

class Engine:
    def __init__(self):
        self._lock = threading.Lock()

    def swap_params(self, params):
        quantized = self.spec.quantize(params)
        placed = jax.device_put(quantized)
        with self._lock:
            self._params = placed
"""
    assert _findings(src) == []


def test_canary_module_clean_and_in_lock_graph():
    """ISSUE 14: the shadow canary mutates its counters/state under one
    lock with every dispatch enqueue, completion fetch, and event
    emission OUTSIDE it — clean under lock-discipline, and its lock is
    a graph node that never nests with the engine/pool locks."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                      "canary.py")],
        checkers=["lock-discipline", "trace-purity"],
        baseline=None)
    assert result.findings == []
    graph = result.reports["lock-discipline"]["lock_graph"]
    canary = graph["pytorch_distributed_mnist_tpu/serve/canary.py"]
    assert canary["locks"] == ["ShadowCanary._lock"]
    assert canary["order_edges"] == []


# -- ISSUE 15: the serving control plane (serve/control.py) ------------------


def test_fires_on_resize_actuation_under_controller_lock():
    """The autoscaler's actuation is a pool topology rebuild — seconds
    of build + AOT warm. Holding the controller (or stats, or pool)
    lock across it stalls every /stats read and dispatch behind the
    rebuild."""
    src = """
import threading

class AutoScaler:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self.pool = pool

    def tick(self, decision):
        with self._lock:
            self.pool.resize(n_devices=decision["to_devices"])
            self._decisions.append(decision)
"""
    (f,) = _findings(src)
    assert "resize" in f.message and "AutoScaler._lock" in f.message


def test_fires_on_token_bucket_sleep_under_quota_lock():
    """A quota layer that SLEEPS a refused client under its lock makes
    every other client's admission wait behind the abuser's back-off —
    the quota consuming the capacity it exists to protect. Refusal must
    be arithmetic (429 + Retry-After), never a sleep."""
    src = """
import threading, time

class ClientQuotas:
    def __init__(self):
        self._lock = threading.Lock()

    def admit(self, client, cost):
        with self._lock:
            bucket = self._buckets[client]
            if bucket.tokens < cost:
                time.sleep((cost - bucket.tokens) / bucket.rate)
            bucket.tokens -= cost
"""
    (f,) = _findings(src)
    assert "sleep" in f.message and "ClientQuotas._lock" in f.message


def test_silent_on_snapshot_then_actuate_after_release():
    """The shipped shape (serve/control.py::AutoScaler.tick): decide
    and mutate counters under the lock, snapshot the target, actuate
    the resize strictly AFTER release."""
    src = """
import threading

class AutoScaler:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self.pool = pool

    def tick(self, decision):
        with self._lock:
            self._decisions.append(decision)
            target = decision["to_devices"]
        self.pool.resize(n_devices=target)
"""
    assert _findings(src) == []


def test_control_module_clean_and_in_lock_graph():
    """ISSUE 15: the control plane holds its locks for arithmetic only
    — quota admits, drain-rate sums, controller decisions, fair-gate
    virtual time — with every actuation (resize) and event emission
    outside them. Clean under lock-discipline, and its locks are graph
    nodes with no nesting edges (none of them may ever nest with the
    batcher cv or pool lock)."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                      "control.py")],
        checkers=["lock-discipline", "trace-purity"],
        baseline=None)
    assert result.findings == []
    graph = result.reports["lock-discipline"]["lock_graph"]
    control = graph["pytorch_distributed_mnist_tpu/serve/control.py"]
    assert control["locks"] == [
        "AutoScaler._lock", "ClientQuotas._lock", "DrainRate._lock",
        "WeightedFairGate._cv"]
    assert control["order_edges"] == []


# -- donation discipline (ISSUE 16) ------------------------------------------


def test_fires_on_donated_buffer_re_release():
    """The whole-program bug signature: one function retires a donated
    staging buffer AND releases the same buffer back to the free-list —
    a future batch would stage into memory XLA already owns."""
    src = """
class Engine:
    def dispatch_fused(self, raw):
        buf = self._fused_staging.acquire(8)
        out = self._program(self._params, buf)
        self._fused_staging.retire([(8, buf)])
        self._fused_staging.release([(8, buf)])
        return out
"""
    (f,) = _findings(src)
    assert "donation discipline" in f.message
    assert "'buf'" in f.message
    assert "use-after-free" in f.message
    assert "_retire_fused_staging/_release_staging" in f.hint


def test_fires_on_shared_buffers_list_routed_both_ways():
    """Same identity through a shared list variable: routing one
    ``buffers`` list to both lifecycles fires even without the
    per-buffer tuple shape."""
    src = """
class Engine:
    def _finish(self, buffers):
        self._fused_staging.retire(buffers)
        self._staging.release(buffers)
"""
    (f,) = _findings(src)
    assert "donation discipline" in f.message and "'buffers'" in f.message


def test_clean_on_separate_lifecycle_helpers():
    """The shipped engine shape: retire and release live in separate
    dedicated helpers, so neither path can reach the other's pool."""
    src = """
class Engine:
    def _release_staging(self, buffers):
        self._staging.release(buffers)

    def _retire_fused_staging(self, buffers):
        self._fused_staging.retire(buffers)
"""
    assert _findings(src) == []


def test_clean_on_distinct_buffers_and_argless_release():
    """Distinct buffers may take distinct lifecycles in one function,
    and an argless ``release()`` (semaphores, window tokens) is not a
    buffer routing."""
    src = """
class Engine:
    def step(self):
        fused_buf = self._fused_staging.acquire(8)
        self._fused_staging.retire([(8, fused_buf)])
        split_buf = self._staging.acquire(8)
        self._staging.release([(8, split_buf)])
        self._window.release()
"""
    assert _findings(src) == []

# -- ISSUE 17: the fleet router (serve/router.py) ----------------------------


def test_fires_on_dispatch_under_routing_table_lock():
    """The federation bug signature: holding the routing-table lock
    across the backend HTTP exchange serializes the WHOLE fleet behind
    one slow backend — every concurrent /predict waits on the read
    timeout of whichever dispatch went first. Routing decisions are
    arithmetic; the wire is not."""
    src = """
import threading
import urllib.request

class Fleet:
    def __init__(self, backends):
        self._lock = threading.Lock()
        self._backends = backends

    def dispatch(self, name, body):
        with self._lock:
            backend = self._backends[name]
            backend.total_inflight += 1
            return urllib.request.urlopen(backend.url, body)
"""
    (f,) = _findings(src)
    assert "network IO" in f.message and "Fleet._lock" in f.message


def test_silent_on_routing_snapshot_then_dispatch():
    """The shipped shape (serve/router.py::Fleet.acquire + the predict
    handler): the complete routing decision AND the in-flight
    reservation happen under the lock, the HTTP exchange strictly after
    release."""
    src = """
import threading
import urllib.request

class Fleet:
    def __init__(self, backends):
        self._lock = threading.Lock()
        self._backends = backends

    def acquire(self, name):
        with self._lock:
            backend = self._backends[name]
            backend.total_inflight += 1
            return backend.url

    def dispatch(self, name, body):
        url = self.acquire(name)
        return urllib.request.urlopen(url, body)
"""
    assert _findings(src) == []


def test_router_module_clean_and_in_lock_graph():
    """ISSUE 17: the router holds its locks for routing arithmetic and
    sweep bookkeeping only — every backend HTTP exchange, health probe,
    and rollout step runs outside them. Clean under EVERY checker (the
    module is also stdlib-pure, so trace-purity has nothing to flag),
    and its locks are graph nodes with no nesting edges: the routing
    table lock must never nest with the poller's or the canary's."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                      "router.py")],
        baseline=None)
    assert result.findings == []
    graph = result.reports["lock-discipline"]["lock_graph"]
    router = graph["pytorch_distributed_mnist_tpu/serve/router.py"]
    assert router["locks"] == [
        "Fleet._lock", "FleetCanary._lock", "HealthPoller._lock",
        "RouterContext._rollout_lock", "RouterLog._lock"]
    assert router["order_edges"] == []


# -- ISSUE 18: the delta distribution plane (distrib/) -----------------------


def test_fires_on_chunk_store_io_under_watcher_lock():
    """FIRING twin: pulling chunk bytes (file IO — and over gossip it
    is a network round-trip) inside the watcher's poll lock would stall
    every concurrent poller for a whole fetch; the checker must flag
    the IO under the lock."""
    src = """
import threading

class Watcher:
    def __init__(self):
        self._lock = threading.Lock()

    def install(self, manifest):
        with self._lock:
            for digest in manifest.chunks:
                with open(self.store.path(digest), "rb") as f:
                    self.buf[digest] = f.read()
"""
    (f,) = _findings(src)
    assert "file IO" in f.message and "Watcher._lock" in f.message


def test_silent_on_fetch_hash_assemble_then_install_under_lock():
    """NON-FIRING twin: the shipped shape (DeltaFetcher.load feeding the
    engine's swap) — chunk fetch, digest verification, and leaf assembly
    all run lock-free; only the one reference swap takes the lock."""
    src = """
import threading

class Watcher:
    def __init__(self):
        self._lock = threading.Lock()

    def install(self, path):
        params, epoch = self.fetcher.load(path, self.template)
        with self._lock:
            self._params, self._epoch = params, epoch
"""
    assert _findings(src) == []


def test_distrib_package_clean_and_lock_free():
    """ISSUE 18 acceptance: the delta plane (cas/publish/fetch) does
    every hash, chunk write, and peer fetch WITHOUT holding any lock —
    serialization lives in the watcher's poll lock and the engine's
    params lock, both outside this package. Clean under every behavior
    checker, and the lock graph has no distrib node at all."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "distrib")],
        checkers=["lock-discipline", "trace-purity", "collective-symmetry",
                  "agreement-except-breadth", "recompile-hazard"],
        baseline=None)
    assert result.findings == []
    assert result.reports["lock-discipline"]["lock_graph"] == {}


# -- ISSUE 19: response-cache lock discipline ---------------------------------


def test_fires_on_device_get_under_cache_lock():
    """FIRING: fetching logits off-device while holding the cache lock
    serializes every cache reader behind a D2H transfer. The cache
    contract is arithmetic-only under the lock — payloads arrive
    already built."""
    src = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def insert(self, key, handle):
        with self._lock:
            self._entries[key] = jax.device_get(handle)
"""
    (f,) = _findings(src)
    assert "device-to-host" in f.message and "Cache._lock" in f.message


def test_fires_on_network_fetch_under_cache_lock():
    """FIRING: the router variant — a backend round-trip under the
    router cache lock stalls every concurrent hit probe."""
    src = """
import threading

class RouterCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}

    def fill(self, key, url):
        with self._lock:
            self._entries[key] = urllib.request.urlopen(url).read()
"""
    (f,) = _findings(src)
    assert "network IO" in f.message and "RouterCache._lock" in f.message


def test_silent_on_snapshot_then_insert():
    """NON-FIRING twin: the shipped economics shape — probe under the
    lock capturing the generation, compute/serialize OUTSIDE it, then a
    generation-checked arithmetic-only insert."""
    src = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}
        self._generation = 0

    def probe(self, key):
        with self._lock:
            return self._entries.get(key), self._generation

    def fill(self, key, handle, generation):
        payload = jax.device_get(handle)
        with self._lock:
            if generation != self._generation:
                return False
            self._entries[key] = payload
            return True
"""
    assert _findings(src) == []


def test_economics_module_clean_and_arithmetic_only():
    """ISSUE 19 acceptance: serve/economics.py holds its lock for
    dict/counter arithmetic only — clean under every behavior checker
    (and jax-import-free, which trace-purity would flag instantly if a
    device call snuck in)."""
    result = run_analysis(
        [os.path.join(_REPO, "pytorch_distributed_mnist_tpu", "serve",
                      "economics.py")],
        checkers=["lock-discipline", "trace-purity", "collective-symmetry",
                  "agreement-except-breadth", "recompile-hazard"],
        baseline=None)
    assert result.findings == []
