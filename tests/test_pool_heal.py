"""Serve-pool self-healing unit tests: per-group failure attribution,
the quarantine threshold, failover-never-drops (dispatch AND completion
failures answered by healthy replicas), background regroup, the resize
state machine, and the topology observability block. Stub engines
injected per replica drive the failure paths deterministically; the
regroup path rebuilds REAL engines from the pool's stored config."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.mnist import (
    normalize_images,
    synthetic_dataset,
)
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.serve.pool import (
    SERVE_FAULT_ENV,
    EnginePool,
    _parse_serve_fault,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def linear_setup():
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    images, labels = synthetic_dataset(64, seed=3)
    return model, state, images, labels


def _direct_labels(model, state, raw_images):
    logits = model.apply(state.params, jnp.asarray(
        normalize_images(raw_images)), train=False)
    return np.argmax(np.asarray(logits), axis=-1)


def _pool(model, state, n=3, **kwargs):
    pool = EnginePool(model.apply, state.params,
                      devices=jax.local_devices()[:n], buckets=(8,),
                      params_epoch=1, **kwargs)
    pool.warmup()
    return pool


class _DeadInflight:
    def __init__(self, inner):
        self.inner = inner

    def complete(self):
        self.inner.complete()  # release the real staging buffers first
        raise RuntimeError("group died between dispatch and fetch")


class _SabotagedEngine:
    """Wraps a real engine; fails at the chosen stage like a group whose
    chips died (RuntimeError — never the input-shaped errors that must
    not count)."""

    def __init__(self, inner, fail_dispatch=False, fail_complete=False):
        self._inner = inner
        self.fail_dispatch = fail_dispatch
        self.fail_complete = fail_complete

    def dispatch_logits(self, images):
        if self.fail_dispatch:
            raise RuntimeError("chips gone (sabotaged)")
        inflight = self._inner.dispatch_logits(images)
        if self.fail_complete:
            return _DeadInflight(inflight)
        return inflight

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _serve_ok(pool, model, state, images):
    labels, _ = pool.predict_complete(pool.dispatch(
        pool.preprocess(images[:8])))
    np.testing.assert_array_equal(
        labels, _direct_labels(model, state, images[:8]))


# -- attribution + failover ---------------------------------------------------


def test_dispatch_failure_fails_over_and_quarantines(linear_setup):
    """Replica 0's dispatch dies persistently: every request still
    answers correctly (failover to healthy replicas — never a drop),
    and after quarantine_after consecutive failures r0 is quarantined
    and skipped entirely."""
    model, state, images, _ = linear_setup
    pool = _pool(model, state, quarantine_after=3, auto_regroup=False)
    r0 = pool.replicas[0]
    r0.engine = _SabotagedEngine(r0.engine, fail_dispatch=True)
    for _ in range(5):
        _serve_ok(pool, model, state, images)
    topo = pool.topology()
    assert topo["quarantined_groups"] == ["r0"]
    assert topo["active_groups"] == 2
    assert topo["failovers"] >= 3
    assert r0.failures == 3  # quarantined after exactly the threshold
    snap = pool.snapshot()
    assert snap["r0"]["quarantined"] is True
    assert "quarantined" not in snap["r1"]  # healthy rows keep their schema
    # Once quarantined, r0 is never dispatched to again.
    dispatched_before = r0.dispatched
    _serve_ok(pool, model, state, images)
    assert r0.dispatched == dispatched_before


def test_completion_failure_fails_over_in_flight_batch(linear_setup):
    """The in-flight case the ISSUE names: the batch is already
    dispatched when the group dies — the fetch fails, and the SAME rows
    re-dispatch on a healthy replica. The caller sees the right answer,
    not an error."""
    model, state, images, _ = linear_setup
    pool = _pool(model, state, quarantine_after=2, auto_regroup=False)
    r0 = pool.replicas[0]
    r0.engine = _SabotagedEngine(r0.engine, fail_complete=True)
    for _ in range(3):
        _serve_ok(pool, model, state, images)
    topo = pool.topology()
    assert topo["quarantined_groups"] == ["r0"]
    assert topo["failovers"] >= 2


def test_input_errors_never_count_toward_quarantine(linear_setup):
    """Malformed requests (ValueError out of preprocess/shape checks)
    are the REQUEST's fault: no attribution, no failover, no quarantine
    — three bad payloads must not condemn a healthy group."""
    model, state, images, _ = linear_setup
    pool = _pool(model, state, quarantine_after=2, auto_regroup=False)
    for _ in range(4):
        with pytest.raises(ValueError):
            pool.dispatch(np.zeros((3, 5, 5, 1), np.float32))
    topo = pool.topology()
    assert topo["quarantined_groups"] == []
    assert topo["failovers"] == 0
    assert all(r.failures == 0 for r in pool.replicas)


def test_success_resets_the_consecutive_counter(linear_setup):
    """quarantine_after counts CONSECUTIVE failures: a success between
    them resets the clock, so a flaky-but-recovering group is not
    condemned."""
    model, state, images, _ = linear_setup
    pool = _pool(model, state, n=2, quarantine_after=3,
                 auto_regroup=False)
    r0 = pool.replicas[0]
    real = r0.engine
    for _ in range(3):
        r0.engine = _SabotagedEngine(real, fail_dispatch=True)
        _serve_ok(pool, model, state, images)  # one failure + failover
        r0.engine = real
        _serve_ok(pool, model, state, images)  # success on r0: reset
    assert pool.topology()["quarantined_groups"] == []
    assert r0.failures == 3 and r0.consecutive_failures == 0


def test_no_healthy_replica_raises_never_hangs(linear_setup):
    model, state, images, _ = linear_setup
    pool = _pool(model, state, n=2, quarantine_after=1,
                 auto_regroup=False)
    for r in pool.replicas:
        r.engine = _SabotagedEngine(r.engine, fail_dispatch=True)
    with pytest.raises(RuntimeError, match="no healthy replica"):
        pool.dispatch(pool.preprocess(images[:8]))
    assert pool.topology()["quarantined_groups"] == ["r0", "r1"]


# -- regroup ------------------------------------------------------------------


def _wait_healed(pool, deadline_s=30.0, regroups=1):
    """Block until the full quarantine->regroup cycle ran: at least
    ``regroups`` rebuilds AND no group left quarantined (polling for an
    empty quarantine list alone would return before the failure
    accumulates)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        topo = pool.topology()
        if (topo["regroups"] >= regroups
                and not topo["quarantined_groups"]):
            return
        time.sleep(0.05)
    raise AssertionError(
        f"pool never healed: {pool.topology()}")


def test_regroup_rebuilds_quarantined_group_under_traffic(linear_setup):
    """The self-healing acceptance at pool level: a sabotaged group is
    quarantined, the background regroup rebuilds a REAL engine from its
    chips, and the group returns to dispatch serving the same answers —
    with traffic flowing on the healthy replicas throughout."""
    model, state, images, _ = linear_setup
    pool = _pool(model, state, quarantine_after=2)  # auto_regroup on
    r0 = pool.replicas[0]
    r0.engine = _SabotagedEngine(r0.engine, fail_dispatch=True)
    stop = threading.Event()
    failures = []

    def hammer():
        while not stop.is_set():
            try:
                _serve_ok(pool, model, state, images)
            except Exception as exc:  # noqa: BLE001
                failures.append(repr(exc))

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        _wait_healed(pool)
    finally:
        stop.set()
        for t in threads:
            t.join(10.0)
    assert not failures, failures[:3]
    topo = pool.topology()
    assert topo["regroups"] == 1 and topo["active_groups"] == 3
    assert r0.generation == 1
    assert not isinstance(r0.engine, _SabotagedEngine)  # a real rebuild
    assert pool.snapshot()["r0"]["generation"] == 1
    # The rebuilt group serves, correctly, on its own chips.
    dispatched_before = r0.dispatched
    for _ in range(4):
        _serve_ok(pool, model, state, images)
    assert r0.dispatched > dispatched_before


def test_regroup_catches_up_to_params_swapped_during_rebuild(
        linear_setup):
    """A hot reload landing while a group rebuilds: the rebuilt engine
    must serve the LATEST params (the pool tracks the newest host-side
    fan-out and the regroup installs it), not the boot checkpoint."""
    model, state, images, _ = linear_setup
    newer = create_train_state(model, jax.random.key(42))
    pool = _pool(model, state, quarantine_after=1)
    r0 = pool.replicas[0]
    r0.engine = _SabotagedEngine(r0.engine, fail_dispatch=True)
    _serve_ok(pool, model, state, images)  # one failure -> quarantine
    # The reload fans out while r0 is quarantined (and skipped).
    assert pool.swap_params(newer.params, epoch=9) == 2
    _wait_healed(pool)
    assert r0.engine.params_epoch == 9
    labels, epoch = pool.predict_complete(pool.dispatch(
        pool.preprocess(images[:8])))
    assert epoch == 9


# -- resize -------------------------------------------------------------------


def test_resize_up_and_down_serves_identically(linear_setup):
    model, state, images, _ = linear_setup
    pool = _pool(model, state, n=2)
    assert pool.topology()["topology_generation"] == 0
    result = pool.resize(n_devices=4)
    assert result["old"]["groups"] == 2 and result["new"]["groups"] == 4
    assert pool.n_replicas == 4 and pool.n_devices == 4
    assert pool.topology()["topology_generation"] == 1
    _serve_ok(pool, model, state, images)
    pool.resize(n_devices=1)
    assert pool.n_replicas == 1
    assert pool.topology()["topology_generation"] == 2
    _serve_ok(pool, model, state, images)


def test_resize_swap_is_atomic_for_in_flight_batches(linear_setup):
    """A batch dispatched on the OLD layout completes on the old engine
    its handle holds — a resize mid-flight loses nothing."""
    model, state, images, _ = linear_setup
    pool = _pool(model, state, n=2)
    handle = pool.dispatch(pool.preprocess(images[:8]))
    old_replica = handle.replica
    pool.resize(n_devices=3)
    assert handle.replica is old_replica
    assert old_replica not in pool.replicas
    labels, _ = pool.predict_complete(handle)
    np.testing.assert_array_equal(
        labels, _direct_labels(model, state, images[:8]))
    assert old_replica.pending == 0  # accounting drained on the old object


def test_resize_carries_latest_params(linear_setup):
    model, state, images, _ = linear_setup
    newer = create_train_state(model, jax.random.key(7))
    pool = _pool(model, state, n=2)
    pool.swap_params(newer.params, epoch=5)
    pool.resize(n_devices=3)
    assert [r.engine.params_epoch for r in pool.replicas] == [5, 5, 5]
    labels, _ = pool.predict_complete(pool.dispatch(
        pool.preprocess(images[:8])))
    np.testing.assert_array_equal(
        labels, _direct_labels(model, newer, images[:8]))


def test_resize_validation_and_serialization(linear_setup):
    model, state, _, _ = linear_setup
    pool = _pool(model, state, n=2)
    with pytest.raises(ValueError, match="local device"):
        pool.resize(n_devices=99)
    with pytest.raises(ValueError, match="no mesh to resize"):
        pool.resize(mesh_size=2)
    # One resize at a time: a concurrent call backs off loudly.
    with pool._lock:
        pool._resizing = True
    try:
        with pytest.raises(RuntimeError, match="already in progress"):
            pool.resize(n_devices=1)
    finally:
        with pool._lock:
            pool._resizing = False
    assert pool.n_replicas == 2  # nothing changed


def test_resize_zero_means_all_local_devices(linear_setup):
    model, state, _, _ = linear_setup
    pool = _pool(model, state, n=1)
    pool.resize(n_devices=0)
    assert pool.n_devices == len(jax.local_devices())


# -- the injection hook -------------------------------------------------------


def test_serve_fault_spec_parsing():
    assert _parse_serve_fault("") is None
    assert _parse_serve_fault("2") == (2, 0)
    assert _parse_serve_fault("1:5") == (1, 5)
    with pytest.raises(ValueError, match=SERVE_FAULT_ENV):
        _parse_serve_fault("a:b")
    with pytest.raises(ValueError, match=SERVE_FAULT_ENV):
        _parse_serve_fault("1:2:3")


def test_injected_fault_fires_quarantines_and_heals(
        linear_setup, monkeypatch):
    """The chaos-harness injection end to end at pool level: group 0
    'dies' after 2 batches, requests fail over (all answered), the
    group quarantines, and the regroup brings it back (generation 1
    clears the injection: the rebuilt group's chips are healthy)."""
    model, state, images, _ = linear_setup
    monkeypatch.setenv(SERVE_FAULT_ENV, "0:2")
    pool = _pool(model, state, n=2, quarantine_after=2)
    for _ in range(8):
        _serve_ok(pool, model, state, images)
    _wait_healed(pool)
    topo = pool.topology()
    assert topo["regroups"] == 1 and topo["failovers"] >= 2
    assert pool.replicas[0].generation == 1
    # Post-regroup, group 0 serves cleanly (generation gates the fault).
    dispatched = pool.replicas[0].dispatched
    for _ in range(4):
        _serve_ok(pool, model, state, images)
    assert pool.replicas[0].dispatched > dispatched
    assert pool.topology()["quarantined_groups"] == []


# -- MPMD pipeline chains (ISSUE 12): a dead stage condemns the chain --------


@pytest.fixture(scope="module")
def pipeline_setup():
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        make_pipeline_template,
    )

    model = get_model("vit", compute_dtype=jnp.float32)
    template = make_pipeline_template(model, jax.random.key(0))
    images, _ = synthetic_dataset(64, seed=6)
    return model, template, images


def _pipeline_pool(model, template, **kwargs):
    pool = EnginePool(model.apply, template.params,
                      devices=jax.local_devices()[:4], buckets=(8,),
                      params_epoch=1, serve_mode="pipeline", mesh_size=2,
                      model_name="vit", model=model, **kwargs)
    pool.warmup()
    return pool


def _pipeline_serve_ok(pool, model, template, images):
    from pytorch_distributed_mnist_tpu.parallel.pipeline_vit import (
        merge_vit_params,
    )

    labels, _ = pool.predict_complete(pool.dispatch(
        pool.preprocess(images[:8])))
    want = np.argmax(np.asarray(model.apply(
        merge_vit_params(template.params),
        jnp.asarray(normalize_images(images[:8])), train=False)), axis=-1)
    np.testing.assert_array_equal(labels, want)


def test_dead_stage_quarantines_whole_pipeline_chain(pipeline_setup):
    """FIRING: one stage dying mid-chain fails the whole chain's
    dispatch — a pipeline with a missing stage can serve nothing, so
    the quarantine takes ALL of the chain's chips out of dispatch at
    once (both stage chips idle, not just the dead one), while requests
    fail over whole to the healthy chain."""
    model, template, images = pipeline_setup
    pool = _pipeline_pool(model, template, quarantine_after=2,
                          auto_regroup=False)
    g0 = pool.replicas[0]
    assert len(g0.devices) == 2  # the chain spans both stage chips
    g0.engine = _SabotagedEngine(g0.engine, fail_dispatch=True)
    for _ in range(4):
        _pipeline_serve_ok(pool, model, template, images)
    topo = pool.topology()
    assert topo["quarantined_groups"] == ["pipeline.g0"]
    assert topo["active_groups"] == 1 and topo["pipeline_stages"] == 2
    # The WHOLE chain is out: no dispatch touches either of its chips.
    dispatched_before = g0.dispatched
    _pipeline_serve_ok(pool, model, template, images)
    assert g0.dispatched == dispatched_before
    snap = pool.snapshot()
    assert snap["pipeline.g0"]["quarantined"] is True
    assert snap["pipeline.g0"]["stages"] == 2
    assert "quarantined" not in snap["pipeline.g1"]


def test_input_error_does_not_quarantine_pipeline_chain(pipeline_setup):
    """NON-FIRING twin: request-shaped errors (ValueError off a
    malformed stack) are the request's fault — they neither count
    toward the chain's quarantine threshold nor fail over."""
    model, template, images = pipeline_setup
    pool = _pipeline_pool(model, template, quarantine_after=1,
                          auto_regroup=False)
    for _ in range(3):
        with pytest.raises(ValueError):
            pool.dispatch(np.zeros((4, 3, 3, 1), np.float32))
    topo = pool.topology()
    assert topo["quarantined_groups"] == [] and topo["active_groups"] == 2
    assert all(r.failures == 0 for r in pool.replicas)
    _pipeline_serve_ok(pool, model, template, images)


def test_regroup_rebuilds_all_stages_of_pipeline_chain(pipeline_setup):
    """The heal path end to end on the MPMD plane: the quarantined
    chain's background regroup rebuilds EVERY stage program from the
    chain's own chips (a fresh PipelineEngine, generation bumped), the
    rebuilt chain rejoins dispatch serving exact answers, and a reload
    that landed mid-rebuild is caught up on every stage."""
    from pytorch_distributed_mnist_tpu.serve.pipeline import (
        PipelineEngine,
        make_pipeline_template,
    )

    model, template, images = pipeline_setup
    newer = make_pipeline_template(model, jax.random.key(9))
    pool = _pipeline_pool(model, template, quarantine_after=1)
    g0 = pool.replicas[0]
    g0.engine = _SabotagedEngine(g0.engine, fail_dispatch=True)
    _pipeline_serve_ok(pool, model, template, images)  # -> quarantine
    # A fleet reload lands while the chain rebuilds (skips quarantined).
    assert pool.swap_params(newer.params, epoch=5) == 1
    _wait_healed(pool)
    assert g0.generation == 1
    assert isinstance(g0.engine, PipelineEngine)  # a real all-stage rebuild
    assert g0.engine.stage_names() == ["pipeline.g0.s0", "pipeline.g0.s1"]
    # The mid-rebuild reload catches up AFTER the install (the regroup's
    # stale-rejecting swap runs post-install, so topology reads healed a
    # beat before the epoch lands): poll, don't race it.
    deadline = time.monotonic() + 30.0
    while g0.engine.params_epoch != 5 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert g0.engine.params_epoch == 5  # the mid-rebuild reload caught up
    labels, epoch = pool.predict_complete(pool.dispatch(
        pool.preprocess(images[:8])))
    assert epoch == 5
    _pipeline_serve_ok(pool, model, newer, images)
