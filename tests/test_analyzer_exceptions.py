"""Fixture suite: the agreement-except-breadth checker (zlib-strand class)."""


import pytest


from tools.analyzer import analyze_snippet  # noqa: E402

pytestmark = pytest.mark.lint


def _findings(src):
    return analyze_snippet(src, checkers=["agreement-except-breadth"])


# -- firing ------------------------------------------------------------------


def test_fires_on_the_zlib_strand_shape():
    """The historical bug, minimized: a narrow funnel in a nested helper
    whose outcome feeds the agreement."""
    src = """
import zlib

def build_loaders(args):
    def _try_load(train):
        try:
            return load_dataset(args.root, train=train)
        except (FileNotFoundError, ValueError, OSError, EOFError):
            return None
    loaded = (_try_load(True), _try_load(False))
    ok = all(s is not None for s in loaded)
    allgather_records("dataset_load", ok)
"""
    (f,) = _findings(src)
    assert f.symbol == "build_loaders"
    assert "zlib.error strand" in f.message
    assert "OSError" in f.message


def test_fires_on_narrow_single_type_at_agreement_level():
    src = """
def save(epoch):
    err = None
    try:
        write_files(epoch)
    except OSError as exc:
        err = exc
    _agree_phase_ok(err, epoch, "write", "dropping tmp")
"""
    (f,) = _findings(src)
    assert "(OSError)" in f.message


def test_fires_even_when_agreement_is_in_a_sibling_nested_def():
    """The funnel and the collective may live in different nested defs of
    one orchestrating scope — the scope is what agrees."""
    src = """
def orchestrate():
    def stage():
        try:
            return fetch()
        except (OSError, ValueError):
            return None
    def vote(ok):
        return agree("stage", None if ok else RuntimeError("x"))
    return vote(stage() is not None)
"""
    assert len(_findings(src)) == 1


# -- non-firing --------------------------------------------------------------


def test_silent_on_broad_exception_funnel():
    src = """
def build_loaders(args):
    def _try_load(train):
        try:
            return load_dataset(args.root, train=train)
        except Exception:
            return None
    ok = _try_load(True) is not None
    allgather_records("dataset_load", ok)
"""
    assert _findings(src) == []


def test_silent_on_narrow_special_case_before_broad_funnel():
    """special-case-then-funnel is safe: the broad sibling catches every
    type the narrow handler misses, so nothing can leak the try."""
    src = """
def agreed(path):
    detail = ""
    try:
        do_work(path)
    except FileNotFoundError:
        detail = "missing"
    except Exception as exc:
        detail = str(exc)
    return allgather_records("phase", not detail, detail)
"""
    assert _findings(src) == []


def test_fires_on_narrow_tuple_without_any_broad_sibling():
    """The sibling exemption needs a broad handler somewhere in the same
    try — a lone narrow tuple still leaks."""
    src = """
def agreed(path):
    try:
        do_work(path)
    except (OSError, ValueError):
        pass
    return allgather_records("phase", True, "")
"""
    (f,) = _findings(src)
    assert "OSError, ValueError" in f.message


def test_silent_on_narrow_sibling_after_a_broad_one():
    """Broad-first means the narrow handler is dead code — a ruff
    problem, not a strand hazard: nothing can leak this try."""
    src = """
def agreed(path):
    try:
        do_work(path)
    except Exception:
        pass
    except ValueError:
        pass
    return allgather_records("phase", True, "")
"""
    assert _findings(src) == []


def test_silent_on_narrow_translator_that_reraises():
    src = """
def collective(payload):
    try:
        return raw_allgather(payload)
    except WatchdogTimeout as exc:
        raise PeerFailure("peers silent") from exc
    finally:
        allgather_records("accounting", True)
"""
    assert _findings(src) == []


def test_silent_on_callless_attribute_poke():
    """supervision.deliver_poison's try: there is no *call* in the try
    body, so no exception type can leak a fallible operation past the
    funnel — narrowness is fine."""
    src = """
def deliver(error):
    try:
        error._poison_delivered = True
    except AttributeError:
        pass
    allgather_records("poison_exit", False, fatal=True)
"""
    assert _findings(src) == []


def test_silent_when_no_agreement_in_scope():
    """Narrow swallows are only an invariant violation on agreement
    paths; ordinary code keeps its idioms."""
    src = """
def probe(path):
    try:
        return read_header(path)
    except (OSError, EOFError):
        return None
"""
    assert _findings(src) == []
