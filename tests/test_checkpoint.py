"""Checkpoint save/restore: schema parity (epoch+1, best_acc), atomicity,
resharding restore, missing-file policy (reference ``:197-214, 249-271``)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import replicated_sharding
from pytorch_distributed_mnist_tpu.train.checkpoint import (
    load_checkpoint,
    save_checkpoint,
    try_resume,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_step


def fresh_state(seed=0):
    model = get_model("linear", compute_dtype=jnp.float32)
    return create_train_state(model, jax.random.key(seed))


def test_round_trip_bitwise(tmp_path, tiny_data):
    state = fresh_state()
    step = make_train_step()
    images, labels = tiny_data
    batch = {"image": jnp.asarray(images[:32]), "label": jnp.asarray(labels[:32])}
    for _ in range(3):
        state, _ = step(state, batch)
    path = save_checkpoint(state, epoch=2, best_acc=0.5, is_best=True,
                           directory=str(tmp_path), process_index=0)
    assert path and os.path.isfile(path)

    template = fresh_state(seed=1)  # different init; must be fully overwritten
    restored, start_epoch, best_acc = load_checkpoint(path, template)
    assert start_epoch == 3  # saved as epoch+1 (:251), resume at next (:204)
    assert best_acc == 0.5
    assert int(restored.step) == 3
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state.opt_state), jax.tree.leaves(restored.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_best_copy_written_only_when_best(tmp_path):
    state = fresh_state()
    save_checkpoint(state, epoch=0, best_acc=0.1, is_best=False,
                    directory=str(tmp_path), process_index=0)
    assert not os.path.exists(tmp_path / "model_best.npz")
    save_checkpoint(state, epoch=1, best_acc=0.2, is_best=True,
                    directory=str(tmp_path), process_index=0)
    assert os.path.exists(tmp_path / "model_best.npz")
    assert os.path.exists(tmp_path / "checkpoint_0.npz")  # per-epoch files kept


def test_nonzero_process_does_not_write(tmp_path):
    state = fresh_state()
    out = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=True,
                          directory=str(tmp_path / "p1"), process_index=1)
    assert out is None
    assert not os.path.exists(tmp_path / "p1")


def test_try_resume_missing_file_continues_fresh(capsys):
    state = fresh_state()
    s2, epoch, best = try_resume("/nonexistent/ckpt.npz", state)
    assert epoch == 0 and best == 0.0 and s2 is state
    assert "no checkpoint found" in capsys.readouterr().out


def test_restore_onto_mesh_resharding(tmp_path, mesh8):
    """Train-on-N -> restore replicated on a mesh (BASELINE configs 3-4)."""
    state = fresh_state()
    path = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                           directory=str(tmp_path), process_index=0)
    template = fresh_state(seed=1)
    repl = replicated_sharding(mesh8)
    template = template.replace(
        params=jax.device_put(template.params, repl),
        opt_state=jax.device_put(template.opt_state, repl),
    )
    restored, _, _ = load_checkpoint(path, template)
    leaf = jax.tree.leaves(restored.params)[0]
    assert leaf.sharding.is_equivalent_to(repl, leaf.ndim)


def test_shape_mismatch_raises(tmp_path):
    state = fresh_state()
    path = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                           directory=str(tmp_path), process_index=0)
    model = get_model("cnn")
    cnn_state = create_train_state(model, jax.random.key(0))
    with pytest.raises(ValueError):
        load_checkpoint(path, cnn_state)


# ---------------------------------------------------------------------------
# Sharded directory layout (multi-host TP/EP/ZeRO states; VERDICT item 8)
# ---------------------------------------------------------------------------


def _zero1_state_on(mesh):
    from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero1

    state = fresh_state()
    state, _ = shard_state_zero1(state, mesh)
    return state


def test_sharded_round_trip_across_mesh_shapes(tmp_path, mesh8):
    """ZeRO-sharded state -> .ckpt dir -> restore on a DIFFERENT mesh,
    bitwise equal. This is the save path a multi-host non-addressable
    state takes (here forced via layout='sharded' since a single-process
    suite is always fully addressable)."""
    from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

    state = _zero1_state_on(mesh8)
    path = save_checkpoint(state, epoch=4, best_acc=0.7, is_best=True,
                           directory=str(tmp_path), process_index=0,
                           layout="sharded")
    assert path.endswith("checkpoint_4.ckpt") and os.path.isdir(path)
    assert os.path.isdir(tmp_path / "model_best.ckpt")
    assert not os.path.exists(path + ".tmp")  # atomically published

    mesh42 = make_mesh(("data", "model"), shape=(4, 2))
    template = _zero1_state_on(mesh42)
    restored, start_epoch, best_acc = load_checkpoint(path, template)
    assert (start_epoch, best_acc) == (5, 0.7)
    for a, b in zip(jax.tree.leaves(state.opt_state),
                    jax.tree.leaves(restored.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored leaves live on the TEMPLATE's (4,2)-mesh shardings
    leaf = jax.tree.leaves(restored.opt_state)[0]
    assert dict(leaf.sharding.mesh.shape) == {"data": 4, "model": 2}


def test_sharded_try_resume_accepts_directory(tmp_path, mesh8):
    state = _zero1_state_on(mesh8)
    path = save_checkpoint(state, epoch=0, best_acc=0.3, is_best=False,
                           directory=str(tmp_path), process_index=0,
                           layout="sharded")
    _, epoch, best = try_resume(path, _zero1_state_on(mesh8))
    assert (epoch, best) == (1, 0.3)


def test_sharded_missing_shard_raises(tmp_path, mesh8):
    state = _zero1_state_on(mesh8)
    path = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                           directory=str(tmp_path), process_index=0,
                           layout="sharded")
    # simulate a lost per-process shard file
    for name in os.listdir(path):
        if name.startswith("shards_"):
            os.unlink(os.path.join(path, name))
    with pytest.raises(ValueError, match="missing shards"):
        load_checkpoint(path, _zero1_state_on(mesh8))


def test_sharded_and_npz_round_trips_agree(tmp_path, mesh8):
    """The two layouts must restore identical states from the same save."""
    state = _zero1_state_on(mesh8)
    p_npz = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                            directory=str(tmp_path / "a"), process_index=0,
                            layout="npz")
    p_dir = save_checkpoint(state, epoch=0, best_acc=0.0, is_best=False,
                            directory=str(tmp_path / "b"), process_index=0,
                            layout="sharded")
    ra, _, _ = load_checkpoint(p_npz, fresh_state(seed=1))
    rb, _, _ = load_checkpoint(p_dir, fresh_state(seed=2))
    for a, b in zip(jax.tree.leaves(ra.params), jax.tree.leaves(rb.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_checkpoint_and_prune(tmp_path):
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        latest_checkpoint,
        prune_checkpoints,
    )

    assert latest_checkpoint(str(tmp_path / "nope")) is None
    state = fresh_state()
    for e in range(4):
        save_checkpoint(state, epoch=e, best_acc=0.1, is_best=(e == 1),
                        directory=str(tmp_path), process_index=0)
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint_3.npz")
    # in-flight tmp names are never eligible
    open(tmp_path / "checkpoint_9.npz.tmp", "w").close()
    assert latest_checkpoint(str(tmp_path)).endswith("checkpoint_3.npz")

    # Window semantics (the serve-reload ordering guarantee): keep every
    # epoch in [latest - N, latest] = [1, 3], delete strictly older.
    prune_checkpoints(str(tmp_path), keep_last=2)
    kept = sorted(os.listdir(tmp_path))
    assert {"checkpoint_1.npz", "checkpoint_2.npz",
            "checkpoint_3.npz"} <= set(kept)
    assert "checkpoint_0.npz" not in kept
    assert "model_best.npz" in kept  # never pruned
    # keep_last=0 is the reference's keep-everything default
    prune_checkpoints(str(tmp_path), keep_last=0)
    assert "checkpoint_1.npz" in os.listdir(tmp_path)


def test_save_checkpoint_keep_last_inline(tmp_path):
    state = fresh_state()
    for e in range(3):
        save_checkpoint(state, epoch=e, best_acc=0.1, is_best=False,
                        directory=str(tmp_path), process_index=0,
                        keep_last=1)
    names = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("checkpoint_"))
    # keep_last=1 keeps the window [latest-1, latest]: the previous
    # latest survives each publish so a serve watcher mid-load on it can
    # never lose the file (train/checkpoint.py ordering guarantee).
    assert names == ["checkpoint_1.npz", "checkpoint_2.npz"]


def test_async_checkpointer_matches_sync(tmp_path, tiny_data):
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        AsyncCheckpointer,
    )

    state = fresh_state()
    step = make_train_step()
    images, labels = tiny_data
    batch = {"image": jnp.asarray(images[:32]), "label": jnp.asarray(labels[:32])}
    state, _ = step(state, batch)

    sync_path = save_checkpoint(state, epoch=0, best_acc=0.2, is_best=True,
                                directory=str(tmp_path / "sync"),
                                process_index=0)
    with AsyncCheckpointer() as saver:
        saver.save(state, epoch=0, best_acc=0.2, is_best=True,
                   directory=str(tmp_path / "async"), process_index=0)
        async_path = saver.wait()
    assert os.path.basename(async_path) == os.path.basename(sync_path)
    # byte-identical files: the host snapshot is the same state
    ra, ea, ba = load_checkpoint(async_path, fresh_state(seed=1))
    rs, es, bs = load_checkpoint(sync_path, fresh_state(seed=2))
    assert (ea, ba) == (es, bs)
    for a, b in zip(jax.tree.leaves(ra.params), jax.tree.leaves(rs.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert os.path.exists(tmp_path / "async" / "model_best.npz")


def test_async_checkpointer_sharded_deferred_publish(tmp_path, mesh8):
    """Async + sharded layout (round-4): the shard snapshot happens in
    save(), the file writes on the worker thread, and the PUBLISH (the
    collective barrier + atomic rename) at the next main-thread drain.
    The published directory must be bitwise identical to a sync save."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        AsyncCheckpointer,
    )

    state = _zero1_state_on(mesh8)
    sync_path = save_checkpoint(state, epoch=0, best_acc=0.4, is_best=True,
                                directory=str(tmp_path / "sync"),
                                process_index=0, layout="sharded")
    adir = tmp_path / "async"
    with AsyncCheckpointer() as saver:
        saver.save(state, epoch=0, best_acc=0.4, is_best=True,
                   directory=str(adir), process_index=0, layout="sharded")
        # Not published yet: only the tmp dir may exist until the drain.
        assert not os.path.isdir(adir / "checkpoint_0.ckpt")
        # Next save drains epoch 0 (join + publish) before snapshotting.
        saver.save(state, epoch=1, best_acc=0.4, is_best=False,
                   directory=str(adir), process_index=0, layout="sharded")
        assert os.path.isdir(adir / "checkpoint_0.ckpt")
        assert not os.path.isdir(adir / "checkpoint_1.ckpt")
        path1 = saver.wait()  # context exit would drain too; explicit here
    assert path1.endswith("checkpoint_1.ckpt") and os.path.isdir(path1)
    assert not os.path.exists(str(adir / "checkpoint_1.ckpt") + ".tmp")
    assert os.path.isdir(adir / "model_best.ckpt")  # epoch 0 was best

    ra, ea, ba = load_checkpoint(str(adir / "checkpoint_0.ckpt"),
                                 _zero1_state_on(mesh8))
    rs, es, bs = load_checkpoint(sync_path, _zero1_state_on(mesh8))
    assert (ea, ba) == (es, bs) == (1, 0.4)
    for a, b in zip(jax.tree.leaves(ra.opt_state),
                    jax.tree.leaves(rs.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_checkpointer_sharded_publish_on_exit(tmp_path, mesh8):
    """A single save followed by context exit still publishes (the drain
    at __exit__), so the last epoch of a run is never lost."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        AsyncCheckpointer,
    )

    state = _zero1_state_on(mesh8)
    with AsyncCheckpointer() as saver:
        saver.save(state, epoch=2, best_acc=0.1, is_best=False,
                   directory=str(tmp_path), process_index=0,
                   layout="sharded")
    assert os.path.isdir(tmp_path / "checkpoint_2.ckpt")
    _, epoch, best = try_resume(str(tmp_path / "checkpoint_2.ckpt"),
                                _zero1_state_on(mesh8))
    assert (epoch, best) == (3, 0.1)


def test_async_checkpointer_surfaces_write_error(tmp_path):
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        AsyncCheckpointer,
    )

    state = fresh_state()
    saver = AsyncCheckpointer()
    # an unwritable target (a path component is a FILE, so makedirs raises
    # regardless of uid): the failure must surface at wait(), not be
    # swallowed on the worker thread
    blocked = tmp_path / "blocked"
    blocked.write_text("not a directory")
    saver.save(state, epoch=0, best_acc=0.0, is_best=False,
               directory=str(blocked / "sub"), process_index=0)
    with pytest.raises(OSError):
        saver.wait()


def test_async_sharded_peer_failure_agreed_before_publish_barrier(
        monkeypatch, tmp_path):
    """Round-4 advisor: when one host's writer thread fails, the hosts
    whose writes succeeded must NOT enter the publish barrier (it has no
    timeout — they would hang forever waiting for the raising host).
    The write outcome is allgathered first; all hosts fail together.
    Hermetic twin: process_count/allgather stubbed (via the supervision
    record channel the agreement now rides) to simulate host 1 failing
    while we (host 0) succeeded."""
    import numpy as np

    from pytorch_distributed_mnist_tpu.runtime import supervision as sup
    from pytorch_distributed_mnist_tpu.train import checkpoint as ckpt

    saver = ckpt.AsyncCheckpointer()
    saver._pending_publish = dict(
        tmp=str(tmp_path / "checkpoint_3.ckpt.tmp"),
        final=str(tmp_path / "checkpoint_3.ckpt"),
        directory=str(tmp_path), epoch=3, is_best=False, keep_last=0,
        pid=0)
    monkeypatch.setattr(ckpt.jax, "process_count", lambda: 2)
    monkeypatch.setattr(sup, "process_count", lambda: 2)
    monkeypatch.setattr(sup, "process_index", lambda: 0)

    def fake_allgather(payload):
        peer = np.frombuffer(
            sup._encode_record(sup._ERR, "OSError('peer write failed')"),
            np.uint8)
        return np.stack([np.asarray(payload), peer])

    monkeypatch.setattr(sup, "_raw_allgather", fake_allgather)
    published = []
    monkeypatch.setattr(ckpt, "_sharded_publish",
                        lambda **kw: published.append(kw))
    with pytest.raises(RuntimeError, match=r"failed on host\(s\) \[1\]"):
        saver.wait()
    assert not published
    assert saver._pending_publish is None

    # Local-failure twin: our own write failed — the local error is what
    # surfaces (after the agreement), and the publish never runs.
    saver = ckpt.AsyncCheckpointer()
    saver._pending_publish = dict(
        tmp=str(tmp_path / "checkpoint_4.ckpt.tmp"),
        final=str(tmp_path / "checkpoint_4.ckpt"),
        directory=str(tmp_path), epoch=4, is_best=False, keep_last=0,
        pid=0)
    saver._error = OSError("disk full on this host")
    with pytest.raises(OSError, match="disk full"):
        saver.wait()
    assert not published


def test_async_exit_logs_swallowed_error_and_dropped_publish(
        tmp_path, capsys):
    """Round-4 advisor: the unwinding __exit__ must not silently discard
    a write failure or an unpublished checkpoint — postmortems need to
    see that epoch N's save was lost."""
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        AsyncCheckpointer,
    )

    blocked = tmp_path / "blocked"
    blocked.write_text("not a directory")
    with pytest.raises(ValueError, match="body exception"):
        with AsyncCheckpointer() as saver:
            saver.save(fresh_state(), epoch=0, best_acc=0.0, is_best=False,
                       directory=str(blocked / "sub"), process_index=0)
            raise ValueError("body exception")
    err = capsys.readouterr().err
    assert "async checkpoint write failed" in err

    with pytest.raises(ValueError, match="body exception"):
        with AsyncCheckpointer() as saver:
            saver.save(fresh_state(), epoch=1, best_acc=0.0, is_best=False,
                       directory=str(tmp_path), process_index=0,
                       layout="sharded")
            raise ValueError("body exception")
    err = capsys.readouterr().err
    assert "unpublished checkpoint" in err
    # The publish barrier was skipped: the directory was never renamed.
    assert not (tmp_path / "checkpoint_1.ckpt").exists()


def test_resume_auto_cli(tmp_path, capsys):
    """--resume auto: fresh when the dir is empty, newest checkpoint after."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    common = [
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--checkpoint-dir", str(tmp_path), "--resume", "auto",
        "--trainer-mode", "stepwise",
    ]
    run(build_parser().parse_args(common + ["--epochs", "2"]))
    out1 = capsys.readouterr().out
    assert "training fresh" in out1
    first = {n for n in os.listdir(tmp_path) if n.startswith("checkpoint_")}
    assert first == {"checkpoint_0.npz", "checkpoint_1.npz"}

    summary = run(build_parser().parse_args(common + ["--epochs", "3"]))
    out2 = capsys.readouterr().out
    assert "loaded checkpoint" in out2 and "checkpoint_1.npz" in out2
    # resumed at epoch 2: exactly one new epoch ran
    assert summary["epochs_run"] == 1
    assert "checkpoint_2.npz" in os.listdir(tmp_path)


def test_async_and_keep_last_cli(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0", "--epochs", "3",
        "--checkpoint-dir", str(tmp_path), "--trainer-mode", "stepwise",
        "--async-checkpoint", "--keep-last", "1",
    ]))
    names = sorted(os.listdir(tmp_path))
    # keep_last=1 retains the window [latest-1, latest] (the serve-reload
    # ordering guarantee, train/checkpoint.py).
    assert [n for n in names if n.startswith("checkpoint_")] == [
        "checkpoint_1.npz", "checkpoint_2.npz"]
    assert "model_best.npz" in names
    # the retained file is complete and loadable (async write landed)
    _, epoch, _ = load_checkpoint(str(tmp_path / "checkpoint_2.npz"),
                                  fresh_state())
    assert epoch == 3


# -- corrupt-checkpoint quarantine at resume (run-supervision satellite) ----


def _resume_args(ckpt_dir, resume="auto"):
    import argparse

    return argparse.Namespace(resume=resume, checkpoint_dir=str(ckpt_dir))


def test_corrupt_latest_quarantined_falls_back(tmp_path, capsys):
    """A truncated latest checkpoint is renamed *.corrupt and --resume
    auto continues from the next-older epoch instead of aborting — the
    crash-mid-write postmortem no longer needs a human to move a file."""
    from pytorch_distributed_mnist_tpu.cli import _resume_supervised

    state = fresh_state()
    save_checkpoint(state, epoch=0, best_acc=0.5, is_best=True,
                    directory=str(tmp_path))
    save_checkpoint(state, epoch=1, best_acc=0.6, is_best=True,
                    directory=str(tmp_path))
    # torn write: valid zip prefix, garbage tail
    good = (tmp_path / "checkpoint_1.npz").read_bytes()
    (tmp_path / "checkpoint_1.npz").write_bytes(good[: len(good) // 3])

    new_state, start_epoch, best_acc, path = _resume_supervised(
        _resume_args(tmp_path), state)
    assert start_epoch == 1  # fell back to epoch 0's file (epoch+1 == 1)
    assert best_acc == 0.5
    assert path.endswith("checkpoint_0.npz")
    assert (tmp_path / "checkpoint_1.npz.corrupt").exists()
    assert not (tmp_path / "checkpoint_1.npz").exists()
    assert "quarantined corrupt checkpoint" in capsys.readouterr().out


def test_all_checkpoints_corrupt_trains_fresh(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import _resume_supervised

    state = fresh_state()
    for e in range(2):
        save_checkpoint(state, epoch=e, best_acc=0.1, is_best=False,
                        directory=str(tmp_path))
        (tmp_path / f"checkpoint_{e}.npz").write_bytes(b"not a zip at all")
    _, start_epoch, best_acc, path = _resume_supervised(
        _resume_args(tmp_path), state)
    assert (start_epoch, best_acc, path) == (0, 0.0, "")
    names = sorted(os.listdir(tmp_path))
    assert names == ["checkpoint_0.npz.corrupt", "checkpoint_1.npz.corrupt"]


def test_corrupt_sharded_directory_quarantined(tmp_path):
    """The .ckpt directory layout quarantines too (torn meta.json)."""
    from pytorch_distributed_mnist_tpu.cli import _resume_supervised

    state = fresh_state()
    save_checkpoint(state, epoch=0, best_acc=0.3, is_best=False,
                    directory=str(tmp_path))
    save_checkpoint(state, epoch=1, best_acc=0.4, is_best=False,
                    directory=str(tmp_path), layout="sharded")
    meta = tmp_path / "checkpoint_1.ckpt" / "meta.json"
    meta.write_text(meta.read_text()[:10])  # torn JSON

    _, start_epoch, _, path = _resume_supervised(
        _resume_args(tmp_path), state)
    assert start_epoch == 1 and path.endswith("checkpoint_0.npz")
    assert (tmp_path / "checkpoint_1.ckpt.corrupt").is_dir()


def test_explicit_resume_path_never_quarantined(tmp_path):
    """Quarantine is an auto-mode policy: an explicitly named corrupt
    checkpoint must abort loudly and stay on disk for the postmortem."""
    from pytorch_distributed_mnist_tpu.cli import _resume_supervised

    state = fresh_state()
    save_checkpoint(state, epoch=0, best_acc=0.1, is_best=False,
                    directory=str(tmp_path))
    target = tmp_path / "checkpoint_0.npz"
    target.write_bytes(b"garbage")
    with pytest.raises(Exception):
        _resume_supervised(_resume_args(tmp_path, resume=str(target)),
                           state)
    assert target.exists()  # evidence untouched
    assert not (tmp_path / "checkpoint_0.npz.corrupt").exists()


def test_model_mismatch_is_not_corruption(tmp_path):
    """A checkpoint that loads but does not FIT (leaf-count mismatch —
    the user changed --model) must abort, not be quarantined: renaming a
    good checkpoint would destroy training history."""
    from pytorch_distributed_mnist_tpu.cli import _resume_supervised

    state = fresh_state()
    save_checkpoint(state, epoch=0, best_acc=0.1, is_best=False,
                    directory=str(tmp_path))
    other = create_train_state(get_model("cnn"), jax.random.key(0))
    with pytest.raises(ValueError, match="mismatch"):
        _resume_supervised(_resume_args(tmp_path), other)
    assert (tmp_path / "checkpoint_0.npz").exists()


def test_is_corrupt_checkpoint_error_classification():
    import json as _json
    import zipfile
    import zlib

    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        is_corrupt_checkpoint_error,
    )

    assert is_corrupt_checkpoint_error(zipfile.BadZipFile("x"))
    assert is_corrupt_checkpoint_error(zlib.error("x"))
    assert is_corrupt_checkpoint_error(EOFError())
    assert is_corrupt_checkpoint_error(KeyError("__meta__"))
    assert is_corrupt_checkpoint_error(
        _json.JSONDecodeError("x", "doc", 0))
    # NOT corruption: the caller is wrong, the file is fine.
    assert not is_corrupt_checkpoint_error(
        ValueError("checkpoint has 4 leaves, current state has 8 — "
                   "model/optimizer mismatch"))
    assert not is_corrupt_checkpoint_error(
        ValueError("leaf x shape (3,) != expected (4,)"))
    assert not is_corrupt_checkpoint_error(RuntimeError("unrelated"))
    # NOT corruption: absence-level signals — a published directory was
    # complete at publish time, so a missing member at resume time is
    # far more likely a stale NFS view than damage; quarantining on it
    # would destroy the newest good checkpoint (review finding).
    assert not is_corrupt_checkpoint_error(FileNotFoundError("meta.json"))
    assert not is_corrupt_checkpoint_error(
        ValueError("leaf params is missing shards (3/9 elements present)"))


def test_quarantine_checkpoint_numbered_on_collision(tmp_path):
    from pytorch_distributed_mnist_tpu.train.checkpoint import (
        latest_checkpoint,
        quarantine_checkpoint,
    )

    for _ in range(2):
        p = tmp_path / "checkpoint_3.npz"
        p.write_bytes(b"bad")
        quarantine_checkpoint(str(p))
    names = sorted(os.listdir(tmp_path))
    assert names == ["checkpoint_3.npz.corrupt", "checkpoint_3.npz.corrupt2"]
    # quarantined names are invisible to resolution and pruning
    assert latest_checkpoint(str(tmp_path)) is None
