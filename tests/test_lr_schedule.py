"""LR schedule parity: lr = base * 0.1**(epoch // 10)
(``/root/reference/multi_proc_single_gpu.py:257-261``)."""

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.train.lr_schedule import step_decay_schedule


@pytest.mark.parametrize(
    "epoch,expected",
    [(0, 1e-3), (9, 1e-3), (10, 1e-4), (19, 1e-4), (20, 1e-5), (35, 1e-6)],
)
def test_step_decay_reference_values(epoch, expected):
    lr = step_decay_schedule(1e-3)
    np.testing.assert_allclose(lr(epoch), expected, rtol=1e-12)


def test_custom_decay():
    lr = step_decay_schedule(0.1, decay_factor=0.5, decay_every=2)
    assert lr(0) == 0.1
    assert lr(2) == 0.05
    assert lr(4) == 0.025
