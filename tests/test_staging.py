"""Pipelined per-batch input staging (data/staging.py): the feeder is a
latency optimization, never a semantics change. Window 1 must reproduce
today's synchronous gather->put->step alternation bit-for-bit (the
``prefetch_enabled`` rule, extended to the per-batch modes), the conduit
must respect its window bound, and abandoning an epoch must never leak
a blocked feeder thread."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data import staging as staging_mod
from pytorch_distributed_mnist_tpu.data.loader import (
    MNISTDataLoader,
    make_global_batch,
)
from pytorch_distributed_mnist_tpu.data.staging import BatchFeeder, _EpochRun
from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.trainer import Trainer
from pytorch_distributed_mnist_tpu.utils.profiling import StagingLog


def _setup(seed=0, n=128, bs=32):
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    labels = (np.arange(n) % 10).astype(np.int32)
    model = get_model("linear", compute_dtype=jnp.float32)
    state = create_train_state(model, jax.random.key(0))
    train = MNISTDataLoader(images, labels, batch_size=bs, train=True, seed=7)
    test = MNISTDataLoader(images, labels, batch_size=bs, train=False, seed=7)
    return state, train, test


def _run_epochs(mode, window, epochs=3):
    state, train, test = _setup()
    trainer = Trainer(state, train, test, mesh=make_mesh(("data",)),
                      mode=mode, feed_window=window)
    history = []
    for epoch in range(epochs):
        train.set_sample_epoch(epoch)
        loss, acc = trainer.train()
        tloss, tacc = trainer.evaluate()
        history.append((loss.average, acc.accuracy,
                        tloss.average, tacc.accuracy))
    return trainer.state, history


# -- the acceptance pin ------------------------------------------------------


@pytest.mark.parametrize("mode", ["stepwise", "explicit"])
def test_pipelined_trajectory_bitwise_equals_synchronous(mode):
    """Window 2 (feeder thread) vs window 1 (inline, today's strict
    alternation): identical metrics AND bitwise-identical params."""
    s_pipe, h_pipe = _run_epochs(mode, window=2)
    s_sync, h_sync = _run_epochs(mode, window=1)
    assert h_pipe == h_sync  # exact float equality: same programs, same data
    for a, b in zip(jax.tree.leaves(s_pipe.params),
                    jax.tree.leaves(s_sync.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_deep_window_trajectory_bitwise_equals_synchronous():
    """A deeper conduit changes overlap, not order: window 4 matches
    window 1 bitwise too."""
    s_deep, h_deep = _run_epochs("stepwise", window=4, epochs=2)
    s_sync, h_sync = _run_epochs("stepwise", window=1, epochs=2)
    assert h_deep == h_sync
    for a, b in zip(jax.tree.leaves(s_deep.params),
                    jax.tree.leaves(s_sync.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- feeder semantics --------------------------------------------------------


def test_feeder_yields_same_batches_in_order():
    """The staged global batches are the synchronous loop's batches —
    same values, same order."""
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    train.set_sample_epoch(1)
    want = [make_global_batch(b, mesh) for b in train]
    feeder = BatchFeeder(train, mesh, window=2)
    got = list(feeder.epoch())
    assert len(got) == len(want)
    for g, w in zip(got, want):
        for key in ("image", "label", "mask"):
            np.testing.assert_array_equal(np.asarray(g[key]),
                                          np.asarray(w[key]))


def test_window_validation_and_pipelined_property():
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    with pytest.raises(ValueError):
        BatchFeeder(train, mesh, window=0)
    assert not BatchFeeder(train, mesh, window=1).pipelined
    assert BatchFeeder(train, mesh, window=2).pipelined


def test_multi_process_world_degrades_to_inline(monkeypatch):
    """No array assembly off the main thread in multi-process worlds
    (supervision's no-concurrent-collectives rule): the feeder reports
    itself inline regardless of window."""
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    feeder = BatchFeeder(train, mesh, window=4)
    monkeypatch.setattr(staging_mod.jax, "process_count", lambda: 2)
    assert not feeder.pipelined
    # And the epoch still delivers every batch, inline.
    train.set_sample_epoch(0)
    assert len(list(feeder.epoch())) == len(train)


class _StubFeeder:
    """Drives _EpochRun directly: stages are the row values themselves."""

    def __init__(self, window, stage_error_at=None):
        self.window = window
        self.staging_log = None
        self.stage_error_at = stage_error_at
        self.stage_calls = 0

    def _stage(self, row, mrow, pipelined):
        self.stage_calls += 1
        if self.stage_error_at is not None and row == self.stage_error_at:
            raise RuntimeError(f"boom at {row}")
        return row


def test_conduit_respects_window_bound():
    """The feeder keeps at most window-1 staged batches beyond the one
    the consumer holds — counting the batch it is staging in-hand, not
    just the conduit entries: with a stalled consumer, _stage runs
    exactly window-1 times (a stage-then-wait loop would silently hold
    one extra full global batch resident in device memory)."""
    feeder = _StubFeeder(window=3)
    run = _EpochRun(feeder, list(range(8)), list(range(8)))
    try:
        time.sleep(0.2)  # give the feeder every chance to overfill
        with run._cv:
            assert len(run._staged) <= feeder.window - 1
        assert feeder.stage_calls == feeder.window - 1
        got = [run.next_batch() for _ in range(8)]
        assert got == list(range(8))
        with pytest.raises(StopIteration):
            run.next_batch()
    finally:
        run.close()


def test_feeder_error_reraised_at_consumer():
    """A staging failure (bad row, OOM, device error) surfaces on the
    consumer thread as the original exception, after the batches staged
    before it were consumed."""
    feeder = _StubFeeder(window=2, stage_error_at=2)
    run = _EpochRun(feeder, list(range(5)), list(range(5)))
    try:
        assert run.next_batch() == 0
        assert run.next_batch() == 1
        with pytest.raises(RuntimeError, match="boom at 2"):
            run.next_batch()
    finally:
        run.close()


def test_cross_thread_close_unblocks_parked_consumer():
    """close() from ANOTHER thread (teardown hooks) must unblock a
    consumer parked in next_batch's cv.wait — a cancelled run reads as
    end-of-epoch (StopIteration), never a permanent wait: cancellation
    sets neither _done nor _error, so the wait predicate must also
    check _cancelled."""
    gate = threading.Event()

    class _SlowFeeder(_StubFeeder):
        def _stage(self, row, mrow, pipelined):
            gate.wait(5)
            return super()._stage(row, mrow, pipelined)

    feeder = _SlowFeeder(window=2)
    run = _EpochRun(feeder, [0], [0])
    out = {}

    def consume():
        try:
            out["batch"] = run.next_batch()
        except StopIteration:
            out["stopped"] = True

    consumer = threading.Thread(target=consume)
    consumer.start()
    time.sleep(0.1)  # consumer parked on the cv (nothing staged yet)
    closer = threading.Thread(target=run.close)
    closer.start()
    # The consumer must unblock on the cancel itself — promptly, while
    # the feeder is still stuck staging (the gate is not set yet).
    consumer.join(2)
    assert not consumer.is_alive()
    assert out.get("stopped") is True
    gate.set()
    closer.join(5)
    assert not closer.is_alive()


def test_abandoned_epoch_joins_feeder_thread():
    """A consumer that abandons the epoch mid-way (raise in the step)
    must not strand the feeder blocked on a full conduit."""
    feeder = _StubFeeder(window=2)
    run = _EpochRun(feeder, list(range(64)), list(range(64)))
    assert run.next_batch() == 0
    run.close()
    assert not run._thread.is_alive()
    run.close()  # idempotent


def test_generator_close_joins_feeder_thread():
    """The BatchFeeder.epoch() generator path: dropping the iterator
    triggers the finally that cancels and joins the feeder."""
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    feeder = BatchFeeder(train, mesh, window=2)
    before = {t.ident for t in threading.enumerate()}
    it = feeder.epoch()
    next(it)
    it.close()  # abandon mid-epoch
    time.sleep(0.05)
    leaked = [t for t in threading.enumerate()
              if t.ident not in before and t.name == "input-feeder"]
    assert leaked == []


def test_feeder_close_joins_abandoned_epoch_without_gc():
    """An exception out of the step loop does NOT finalize the epoch()
    generator promptly (the traceback keeps the frame alive), so
    teardown must be able to join the feeder WITHOUT dropping the
    iterator: BatchFeeder.close() — reached via Trainer.close() and
    cli's closing(trainer) — joins the in-flight run directly."""
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    feeder = BatchFeeder(train, mesh, window=2)
    it = feeder.epoch()
    next(it)
    run = feeder._active_run
    assert run is not None and run._thread.is_alive()
    feeder.close()  # iterator still referenced — no GC finalization
    assert not run._thread.is_alive()
    assert feeder._active_run is None
    feeder.close()  # idempotent
    del it


def test_reentrant_epoch_joins_previous_abandoned_run():
    """Starting a new epoch while a previous abandoned run is still
    live (its generator pinned by an exception traceback) must join the
    old feeder BEFORE reassigning _active_run — reassignment would
    orphan the thread beyond close()'s reach."""
    _, train, _ = _setup()
    feeder = BatchFeeder(train, make_mesh(("data",)), window=2)
    it1 = feeder.epoch()
    next(it1)
    old = feeder._active_run
    assert old is not None and old._thread.is_alive()
    it2 = feeder.epoch()  # re-entrant: previous epoch abandoned, un-GC'd
    assert not old._thread.is_alive()
    next(it2)
    assert feeder._active_run is not old
    # The abandoned iterator, if ever resumed, drains cleanly (its run
    # is cancelled -> end-of-epoch), never crashes or blocks.
    with pytest.raises(StopIteration):
        next(it1)
    feeder.close()
    del it1, it2


def test_trainer_close_joins_per_batch_feeder():
    """Trainer.close() must reach the per-batch feeder, not just the
    scan prefetch: abandon a stepwise epoch via a raising step and
    assert the input-feeder thread is joined by close()."""
    state, train, test = _setup()
    trainer = Trainer(state, train, test, mesh=make_mesh(("data",)),
                      mode="stepwise", feed_window=2)
    it = trainer._feeder.epoch()
    next(it)  # feeder thread live, mid-epoch
    run = trainer._feeder._active_run
    assert run is not None
    trainer.close()
    assert not run._thread.is_alive()
    del it


def test_epoch_snapshot_tracks_sampler_jump():
    """epoch() snapshots the CURRENT sampler epoch on the consumer
    thread: a resume-style jump between epochs feeds the jumped-to
    epoch's permutation, not a stale one."""
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    feeder = BatchFeeder(train, mesh, window=2)
    train.set_sample_epoch(5)
    want = [np.asarray(make_global_batch(b, mesh)["label"]) for b in train]
    got = [np.asarray(b["label"]) for b in feeder.epoch()]
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


# -- staging log -------------------------------------------------------------


def test_staging_log_inline_overlap_is_zero():
    """The inline path records its own wall as consumer wait, so the
    overlap fraction honestly reads 0."""
    log = StagingLog()
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    feeder = BatchFeeder(train, mesh, window=1, staging_log=log)
    list(feeder.epoch())
    s = log.summary()
    assert s["stages"] == len(train) and s["pipelined_stages"] == 0
    assert s["overlap_fraction"] == 0.0
    assert s["images"] == len(train) * train.local_batch_size


def test_staging_log_pipelined_records_feeder_stages():
    log = StagingLog()
    _, train, _ = _setup()
    mesh = make_mesh(("data",))
    feeder = BatchFeeder(train, mesh, window=2, staging_log=log)
    list(feeder.epoch())
    s = log.summary()
    assert s["stages"] == len(train)
    assert s["pipelined_stages"] == len(train)
    assert s["feed_images_per_sec"] > 0


# -- per-batch eval staging cache (satellite) --------------------------------


@pytest.mark.parametrize("mode", ["stepwise", "explicit"])
def test_eval_staging_cached_once_and_metrics_identical(mode, monkeypatch):
    """Trainer.evaluate in the per-batch modes stages the (never
    reshuffled) eval batches exactly once; repeat evaluations reuse the
    staged arrays and report identical metrics."""
    state, train, test = _setup()
    trainer = Trainer(state, train, test, mesh=make_mesh(("data",)),
                      mode=mode)
    calls = {"n": 0}
    import pytorch_distributed_mnist_tpu.train.trainer as trainer_mod
    real = trainer_mod.make_global_batch

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(trainer_mod, "make_global_batch", counting)
    l1, a1 = trainer.evaluate()
    staged = calls["n"]
    assert staged == len(test)  # one stage per eval batch
    cached = trainer._eval_staged_batches
    assert cached is not None
    l2, a2 = trainer.evaluate()
    assert calls["n"] == staged  # only-once staging
    assert trainer._eval_staged_batches is cached
    assert (l1.average, a1.accuracy) == (l2.average, a2.accuracy)


def test_eval_cache_matches_fresh_gather_metrics():
    """The cached staging cannot drift from a fresh per-pass gather."""
    state, train, test = _setup()
    mesh = make_mesh(("data",))
    trainer = Trainer(state, train, test, mesh=mesh, mode="stepwise")
    l_cached, a_cached = trainer.evaluate()

    state2, train2, test2 = _setup()
    t2 = Trainer(state2, train2, test2, mesh=mesh, mode="stepwise")
    t2._eval_staged_batches = [make_global_batch(b, mesh)
                               for b in test2]  # fresh gather, same data
    l_fresh, a_fresh = t2.evaluate()
    assert (l_cached.average, a_cached.accuracy) == \
        (l_fresh.average, a_fresh.accuracy)
