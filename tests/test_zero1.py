"""ZeRO-1 optimizer-state sharding (parallel/zero.py) on the 8-device mesh.

The contract: sharding Adam's moments over the data axis changes WHERE the
optimizer state lives, not WHAT the training computes — the sharded-state
step must match the replicated-state step exactly (the same property the
DP/TP suites pin, extended to the optimizer layout; SURVEY.md section 2c's
closing note promised ZeRO as a PartitionSpec change).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_mnist_tpu.models import get_model
from pytorch_distributed_mnist_tpu.parallel.tensor import vit_tp_rules
from pytorch_distributed_mnist_tpu.parallel.zero import (
    _zero_spec,
    shard_state_zero1,
    zero1_state_sharding,
)
from pytorch_distributed_mnist_tpu.train.state import create_train_state
from pytorch_distributed_mnist_tpu.train.steps import make_train_epoch, make_train_step


def _batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": jnp.asarray(rng.normal(size=(n, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(n,)), jnp.int32),
    }


def test_zero_spec_picks_largest_divisible_dim():
    assert _zero_spec((3, 3, 1, 32), 8, "data", P()) == P(None, None, None, "data")
    assert _zero_spec((12544, 128), 8, "data", P()) == P("data", None)
    assert _zero_spec((10,), 8, "data", P()) == P()  # nothing divisible
    assert _zero_spec((), 8, "data", P()) == P()  # scalar (count)
    # A dim the base layout already claims is not re-used.
    assert _zero_spec((64, 128), 8, "data", P(None, "model")) == P("data", "model")


def test_zero_spec_tie_breaks_to_lowest_dim():
    """Equal largest dims resolve to the LOWEST index, deterministically:
    the dim choice fixes the shard layout (and the overlapped path's
    bucket shapes), so it must be stable across runs and hosts rather
    than an accident of iteration order."""
    assert _zero_spec((64, 64), 8, "data", P()) == P("data", None)
    assert _zero_spec((8, 32, 32), 8, "data", P()) == P(None, "data", None)
    # A tie where the lowest dim is base-claimed falls to the next one.
    assert _zero_spec((64, 64), 8, "data", P("model")) == P("model", "data")


def test_moments_are_sharded_params_replicated(mesh8):
    state = create_train_state(get_model("cnn"), jax.random.key(0))
    sharding = zero1_state_sharding(state, mesh8)
    # Params replicate (the DDP layout the reference uses, :188-189).
    for leaf in jax.tree_util.tree_leaves(sharding.params):
        assert leaf.spec == P()
    # Moment leaves with a divisible dim are sharded on 'data'.
    flat = jax.tree_util.tree_flatten_with_path(sharding.opt_state)[0]
    sharded = [
        (jax.tree_util.keystr(path), s.spec)
        for path, s in flat
        if any(getattr(e, "name", None) in ("mu", "nu") for e in path)
        and s.spec != P()
    ]
    assert sharded, "no moment leaf got sharded"
    for name, spec in sharded:
        assert "data" in tuple(spec), (name, spec)


@pytest.mark.slow
def test_zero1_step_matches_replicated(mesh8):
    """3 sharded-optimizer steps == 3 replicated steps, bitwise-tolerance."""
    model = get_model("cnn")
    ref_state = create_train_state(model, jax.random.key(0))
    z_state = create_train_state(model, jax.random.key(0))
    z_state, z_sharding = shard_state_zero1(z_state, mesh8)

    ref_step = make_train_step(mesh8)
    z_step = make_train_step(mesh8, state_sharding=z_sharding)
    for i in range(3):
        b = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, b)
        z_state, z_m = z_step(z_state, b)
    np.testing.assert_allclose(
        float(ref_m.loss_sum), float(z_m.loss_sum), rtol=1e-6
    )
    for a, b_ in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(z_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-6, atol=1e-6)
    # Moments too: same values, different layout.
    for a, b_ in zip(
        jax.tree_util.tree_leaves(ref_state.opt_state),
        jax.tree_util.tree_leaves(z_state.opt_state),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-6, atol=1e-6)


def test_zero1_scan_epoch_matches_replicated(mesh8):
    """The lax.scan epoch path accepts the ZeRO layout and agrees."""
    model = get_model("linear")
    ref_state = create_train_state(model, jax.random.key(1))
    z_state = create_train_state(model, jax.random.key(1))
    z_state, z_sharding = shard_state_zero1(z_state, mesh8)

    rng = np.random.default_rng(7)
    batches = {
        "image": jnp.asarray(rng.normal(size=(4, 64, 28, 28, 1)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(4, 64)), jnp.int32),
    }
    ref_epoch = make_train_epoch(mesh8)
    z_epoch = make_train_epoch(mesh8, state_sharding=z_sharding)
    ref_state, ref_m = ref_epoch(ref_state, batches)
    z_state, z_m = z_epoch(z_state, jax.tree_util.tree_map(jnp.copy, batches))
    assert float(ref_m.count) == float(z_m.count)
    np.testing.assert_allclose(float(ref_m.loss_sum), float(z_m.loss_sum),
                               rtol=1e-6)
    for a, b_ in zip(
        jax.tree_util.tree_leaves(ref_state.params),
        jax.tree_util.tree_leaves(z_state.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-6, atol=1e-6)


def test_zero1_respects_tp_rules(mesh8):
    """Moment leaves a TP rule lays out keep the TP layout (not re-sharded)."""
    try:
        from pytorch_distributed_mnist_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(("data", "model"), shape=(4, 2))
    except TypeError:
        pytest.skip("make_mesh lacks shape kwarg")
    model = get_model("vit")
    state = create_train_state(model, jax.random.key(0))
    sharding = zero1_state_sharding(state, mesh, rules=vit_tp_rules())
    flat = jax.tree_util.tree_flatten_with_path(sharding.opt_state)[0]
    for path, s in flat:
        keys = [str(getattr(e, "name", getattr(e, "key", ""))) for e in path]
        if "mu" in keys and keys[-2:] == ["qkv", "kernel"]:
            assert s.spec == P(None, "model"), s.spec
            break
    else:
        pytest.fail("no qkv kernel moment found")


def test_cli_zero1_end_to_end(tmp_path):
    """--optimizer-sharding zero1 trains through the full driver."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--optimizer-sharding", "zero1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    summary = run(args)
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])


def test_cli_zero1_rejects_momentless_optimizer(tmp_path):
    """sgd has no mu/nu leaves, so zero1 would be a silent no-op; the CLI
    must reject the combination instead of quietly training replicated."""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    args = build_parser().parse_args([
        "--dataset", "synthetic", "--model", "linear", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--optimizer", "sgd",
        "--optimizer-sharding", "zero1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ])
    with pytest.raises(SystemExit, match="zero1 requires an Adam"):
        run(args)


# ---------------------------------------------------------------------------
# ZeRO-3 (FSDP-style param sharding)
# ---------------------------------------------------------------------------


def test_zero3_step_matches_replicated(mesh8, tiny_data):
    """Params sharded over data (level 3): one train step == the replicated
    step — XLA's AllGather-on-use must be semantically invisible."""
    import jax.numpy as jnp

    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from pytorch_distributed_mnist_tpu.train.steps import make_train_step
    from pytorch_distributed_mnist_tpu.data.loader import make_global_batch

    model = get_model("cnn", compute_dtype=jnp.float32)
    images, labels = tiny_data
    batch = {"image": np.asarray(images[:32]),
             "label": np.asarray(labels[:32])}

    ref_state = create_train_state(model, jax.random.key(0))
    ref_state, ref_m = make_train_step()(ref_state,
                                         {k: jnp.asarray(v) for k, v in batch.items()})

    z_state = create_train_state(model, jax.random.key(0))
    z_state, z_sharding = shard_state_zero(z_state, mesh8, level=3)
    z_step = make_train_step(mesh8, state_sharding=z_sharding)
    z_state, z_m = z_step(z_state, make_global_batch(batch, mesh8))

    assert float(z_m.loss_sum) == pytest.approx(float(ref_m.loss_sum),
                                                rel=1e-6)
    # atol 5e-5: the sharded grad path reduces in ReduceScatter order, not
    # AllReduce order, so single-element f32 rounding deltas are expected
    # (observed up to ~1.7e-5 depending on the XLA version's reduction
    # schedule; params are ~1e-2, so this is still a tight bound).
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(z_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=5e-5)


def test_zero3_actually_shards_params(mesh8):
    from pytorch_distributed_mnist_tpu.models import get_model
    from pytorch_distributed_mnist_tpu.parallel.zero import shard_state_zero
    from pytorch_distributed_mnist_tpu.train.state import create_train_state
    from jax.sharding import PartitionSpec as P

    state = create_train_state(get_model("cnn"), jax.random.key(0))
    state, _ = shard_state_zero(state, mesh8, level=3)
    fc1 = state.params["params"]["fc1"]["kernel"]  # (12544, 128)
    assert "data" in jax.tree_util.tree_leaves(
        [ax for ax in fc1.sharding.spec if ax is not None]
    )
    # moments sharded too
    mu = state.opt_state.inner_state[0].mu["params"]["fc1"]["kernel"]
    assert mu.sharding.spec != P()


@pytest.mark.slow
def test_cli_zero3_end_to_end(tmp_path):
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    summary = run(build_parser().parse_args([
        "--dataset", "synthetic", "--model", "cnn", "--epochs", "1",
        "--batch-size", "64", "--synthetic-train-size", "256",
        "--synthetic-test-size", "128", "--seed", "0",
        "--optimizer-sharding", "zero3",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--root", str(tmp_path / "data"),
    ]))
    assert summary["epochs_run"] == 1
    assert np.isfinite(summary["history"][0]["train_loss"])
