"""Real-MNIST integration: activates only when the actual IDX files exist.

This environment has zero egress (both documented mirrors fail DNS — the
exact error is recorded in BASELINE.md per round), so these tests are
skipped here; in any environment where `data/mnist/` holds the real files
(hand-placed or downloaded), they run automatically and pin the claim the
synthetic proxy cannot: the CNN reaches real-MNIST accuracy.

Ref contrast: the reference's default path downloads and trains on the
real dataset (`/root/reference/multi_proc_single_gpu.py:137-138`,
`README.md:42-48`).

Search order for the dataset root: $TPU_MNIST_DATA_ROOT, then the repo's
`data/` (the CLI's --root default).
"""

import os

import numpy as np
import pytest

from pytorch_distributed_mnist_tpu.data.download import dataset_present

_ROOTS = [r for r in (os.environ.get("TPU_MNIST_DATA_ROOT"),
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), "data"))
          if r]
_REAL_ROOT = next(
    (r for r in _ROOTS if dataset_present(os.path.join(r, "mnist"))), None)

pytestmark = pytest.mark.skipif(
    _REAL_ROOT is None,
    reason="real MNIST IDX files not present (zero-egress environment; "
           "see BASELINE.md for the recorded download failure)",
)


def test_real_mnist_loads_true_shapes():
    from pytorch_distributed_mnist_tpu.data.mnist import load_dataset

    images, labels = load_dataset(_REAL_ROOT, train=True,
                                  synthesize_if_missing=False)
    assert images.shape == (60000, 28, 28)
    assert labels.shape == (60000,)
    assert set(np.unique(labels)) == set(range(10))


@pytest.mark.slow
def test_cnn_reaches_97pct_on_real_mnist(tmp_path):
    """2 epochs of the CNN on real MNIST must clear 97% test accuracy —
    the integration claim the synthetic glyphs cannot make. (The >=99%
    north star uses the full 20-epoch config; this is the fast gate.)"""
    from pytorch_distributed_mnist_tpu.cli import build_parser, run

    summary = run(build_parser().parse_args([
        "--dataset", "mnist", "--root", _REAL_ROOT,
        "--model", "cnn", "--epochs", "2", "--batch-size", "256",
        "--seed", "0", "--checkpoint-dir", str(tmp_path / "ckpt"),
    ]))
    assert not summary.get("dataset_synthesized")
    assert summary["best_acc"] >= 0.97