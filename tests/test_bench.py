"""bench.py degradation-ladder units (hermetic, CPU).

Round-2 postmortem: both live TPU bench attempts timed out against a wedged
chip link and the round's perf artifact degraded to CPU even though a valid
mid-session TPU capture existed. These tests pin the ladder pieces that fix
that: the watcher-capture fallback, the probe child's stepwise path, and
the compile-cache plumbing — all without any accelerator.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import bench  # noqa: E402


def _write_capture(tmp_path, payload):
    path = tmp_path / "bench.json"
    path.write_text(json.dumps(payload) + "\n")
    return str(path)


def test_watcher_capture_accepted(tmp_path, monkeypatch):
    payload = {"metric": "mnist_cnn_train_images_per_sec_per_chip",
               "value": 377686.0, "unit": "images/sec/chip",
               "vs_baseline": 774.0, "backend": "tpu",
               "device_kind": "TPU v5 lite"}
    monkeypatch.setenv("BENCH_CAPTURE_PATH", _write_capture(tmp_path, payload))
    cap = bench._load_watcher_capture()
    assert cap is not None
    assert cap["source"] == "watcher_capture"
    assert cap["value"] == 377686.0
    # Legacy capture without embedded measured_at: file mtime stands in.
    assert cap["capture_timestamp"].endswith("Z")


def test_watcher_capture_prefers_embedded_timestamp(tmp_path, monkeypatch):
    """A capture that embeds measured_at keeps it — a git checkout or
    rewrite restamps mtime, so the embedded time is the real provenance."""
    payload = {"value": 1.0, "backend": "tpu",
               "measured_at": "2026-07-29T12:00:00Z"}
    monkeypatch.setenv("BENCH_CAPTURE_PATH", _write_capture(tmp_path, payload))
    cap = bench._load_watcher_capture()
    assert cap["measured_at"] == "2026-07-29T12:00:00Z"
    assert "capture_timestamp" not in cap


@pytest.mark.parametrize("payload", [
    {"backend": "cpu", "value": 268.6},   # CPU capture is not TPU evidence
    {"backend": "tpu", "value": 0.0},     # zero value means a failed run
    {"backend": "tpu"},                   # no value at all
])
def test_watcher_capture_rejected(tmp_path, monkeypatch, payload):
    monkeypatch.setenv("BENCH_CAPTURE_PATH", _write_capture(tmp_path, payload))
    assert bench._load_watcher_capture() is None


def test_watcher_capture_non_dict_rejected(tmp_path, monkeypatch):
    """'null' is valid JSON but not a capture; must return None, not raise
    (bench_accelerator's contract is 'never raises')."""
    path = tmp_path / "bench.json"
    path.write_text("null\n")
    monkeypatch.setenv("BENCH_CAPTURE_PATH", str(path))
    assert bench._load_watcher_capture() is None


def test_empty_capture_path_disables_fallback(tmp_path, monkeypatch):
    """tpu_watch.sh sets BENCH_CAPTURE_PATH= so bench.py can never re-emit
    the watcher's own prior output as a fresh capture."""
    monkeypatch.setenv("BENCH_CAPTURE_PATH", "")
    assert bench._load_watcher_capture() is None


def test_capture_freshness_bound(tmp_path, monkeypatch):
    """Default-path captures older than the round's driver artifacts
    (VERDICT.md / BENCH_r*.json mtimes) are stale — a git checkout restores
    last round's committed capture with checkout-time mtime, and it must
    not become this round's evidence."""
    import shutil

    fake_repo = tmp_path / "repo"
    (fake_repo / "tools" / "captured").mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "bench.py"), fake_repo / "bench.py")
    monkeypatch.setattr(bench, "__file__", str(fake_repo / "bench.py"))
    monkeypatch.delenv("BENCH_CAPTURE_PATH", raising=False)

    cap_path = fake_repo / "tools" / "captured" / "bench.json"
    cap_path.write_text(json.dumps({"backend": "tpu", "value": 9.0}) + "\n")
    marker = fake_repo / "VERDICT.md"
    marker.write_text("round marker\n")

    now = os.path.getmtime(cap_path)
    # Stale: capture and marker share the checkout mtime.
    os.utime(marker, (now, now))
    assert bench._load_watcher_capture() is None
    # Fresh: watcher wrote the capture well after the round started.
    os.utime(cap_path, (now + 3600, now + 3600))
    cap = bench._load_watcher_capture()
    assert cap is not None and cap["value"] == 9.0


def test_watcher_capture_missing_or_garbage(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_CAPTURE_PATH", str(tmp_path / "absent.json"))
    assert bench._load_watcher_capture() is None
    path = tmp_path / "bench.json"
    path.write_text("not json at all\n")
    monkeypatch.setenv("BENCH_CAPTURE_PATH", str(path))
    assert bench._load_watcher_capture() is None


def test_main_emits_watcher_capture(tmp_path, monkeypatch, capsys):
    """When live attempts fail, main() prints the capture verbatim with the
    live errors attached — the driver's BENCH_r{N}.json then carries the
    TPU evidence automatically."""
    payload = {"metric": "mnist_cnn_train_images_per_sec_per_chip",
               "value": 1234.5, "vs_baseline": 2.5, "backend": "tpu"}
    monkeypatch.setenv("BENCH_CAPTURE_PATH", _write_capture(tmp_path, payload))
    monkeypatch.setattr(
        bench, "_run_child",
        lambda env, steps, reps, timeout: (None, "simulated dead link"))
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 1234.5
    assert out["source"] == "watcher_capture"
    assert "simulated dead link" in out["tpu_error_live"]
    assert out["backend"] == "tpu"


def _fake_run_child_cpu_only(env_extra, steps, reps, timeout):
    """TPU children fail; the CPU-fallback child returns a tiny result."""
    if env_extra.get("BENCH_FORCE_CPU"):
        return ({"ok": True, "images_per_sec_per_chip": 100.0,
                 "steps_per_sec": 1.0, "global_batch": 4, "n_chips": 1,
                 "backend": "cpu", "device_kind": "cpu"}, None)
    return (None, "simulated dead link")


def test_cpu_fallback_line_carries_last_valid_tpu_pointer(
        tmp_path, monkeypatch, capsys):
    """Round-4 VERDICT weak #5: a chip-dead round's artifact must surface
    the evidence trail. The CPU-fallback line carries a non-headline
    last_valid_tpu_capture pointer to the newest real-TPU capture on
    record (any age — the freshness gate rightly keeps it off the
    headline), with value + measured_at provenance."""
    payload = {"value": 375868.0, "unit": "images/sec/chip",
               "backend": "tpu", "measured_at": "2026-07-29T12:00:00Z"}
    path = tmp_path / "old_capture.json"
    path.write_text(json.dumps(payload) + "\n")
    monkeypatch.setenv("BENCH_LAST_CAPTURE_PATH", str(path))
    monkeypatch.setenv("BENCH_CAPTURE_PATH", "")  # no watcher re-emission
    monkeypatch.setattr(bench, "_run_child", _fake_run_child_cpu_only)
    monkeypatch.setattr(bench, "bench_torch_reference", lambda: 50.0)
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert out["backend"] == "cpu"
    ptr = out["last_valid_tpu_capture"]
    assert ptr["value"] == 375868.0
    assert ptr["measured_at"] == "2026-07-29T12:00:00Z"
    assert "NOT this round's measurement" in ptr["note"]
    # Headline fields are untouched by the pointer.
    assert out["value"] == 100.0


def test_tpu_line_never_carries_pointer(tmp_path, monkeypatch, capsys):
    """The pointer is for chip-dead lines only: a line whose own backend
    is tpu (live or watcher capture) must not carry it."""
    payload = {"value": 1.0, "backend": "tpu",
               "measured_at": "2026-07-29T12:00:00Z"}
    path = tmp_path / "old_capture.json"
    path.write_text(json.dumps(payload) + "\n")
    monkeypatch.setenv("BENCH_LAST_CAPTURE_PATH", str(path))

    def fake_tpu_child(env_extra, steps, reps, timeout):
        return ({"ok": True, "images_per_sec_per_chip": 9.0,
                 "steps_per_sec": 1.0, "global_batch": 4, "n_chips": 1,
                 "backend": "tpu", "device_kind": "TPU v5 lite"}, None)

    monkeypatch.setattr(bench, "_run_child", fake_tpu_child)
    monkeypatch.setattr(bench, "bench_torch_reference", lambda: 50.0)
    bench.main()
    out = json.loads(capsys.readouterr().out.strip())
    assert out["backend"] == "tpu"
    assert "last_valid_tpu_capture" not in out


def test_pointer_rejects_cpu_and_garbage_captures(tmp_path, monkeypatch):
    monkeypatch.setenv("BENCH_LAST_CAPTURE_PATH", "")
    assert bench._last_valid_tpu_capture() is None
    path = tmp_path / "cap.json"
    path.write_text(json.dumps({"value": 5.0, "backend": "cpu"}) + "\n")
    monkeypatch.setenv("BENCH_LAST_CAPTURE_PATH", str(path))
    assert bench._last_valid_tpu_capture() is None
    path.write_text("not json\n")
    assert bench._last_valid_tpu_capture() is None
    path.write_text(json.dumps({"value": 5.0, "backend": "tpu"}) + "\n")
    ptr = bench._last_valid_tpu_capture()
    assert ptr is not None
    # No embedded measured_at: mtime stands in, and says so.
    assert ptr["measured_at_source"] == "file_mtime"


def test_capture_readers_tolerate_invalid_utf8(tmp_path, monkeypatch):
    """A truncated/corrupt capture with invalid UTF-8 must degrade to
    None in BOTH readers, never crash the always-emit-JSON contract."""
    path = tmp_path / "cap.json"
    path.write_bytes(b'{"backend": "tpu", "value": \xff\xfe garbage')
    monkeypatch.setenv("BENCH_LAST_CAPTURE_PATH", str(path))
    assert bench._last_valid_tpu_capture() is None
    monkeypatch.setenv("BENCH_CAPTURE_PATH", str(path))
    assert bench._load_watcher_capture() is None


def test_vit_main_exits_nonzero_on_full_failure(monkeypatch, capsys):
    """Round-4 advisor: a fully failed --vit run must not exit 0 — the
    watcher's rc gate (tools/tpu_watch_r5.sh) rejects it without parsing,
    matching the bench_kernels.py / sweep_flash.py convention."""
    monkeypatch.setattr(bench, "bench_vit_accelerator",
                        lambda: {"ok": False, "error": "all children died"})
    with pytest.raises(SystemExit) as exc_info:
        bench.main_vit()
    assert exc_info.value.code == 1
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "all children died" in out["error"]


@pytest.mark.slow
def test_probe_child_stepwise_cpu():
    """The probe path end-to-end in a real child process on CPU: it must
    produce a throughput number with mode=probe in well under the 360s the
    parent allows it on TPU."""
    env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_PROBE="1",
               BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child", "2", "1"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    line = [l for l in proc.stdout.splitlines() if l.strip().startswith("{")][-1]
    result = json.loads(line)
    assert result["ok"], result
    assert result["mode"] == "probe"
    assert result["images_per_sec_per_chip"] > 0


@pytest.mark.slow
def test_secondary_measurements_plumbing_cpu():
    """The fused-kernels and device-gather secondaries end-to-end on CPU
    (BENCH_FORCE_SECONDARIES): a broken secondary otherwise surfaces only
    as a silent *_error field during the chip's rare capture windows —
    exactly how a fused-path TypeError hid through round 2."""
    env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_FORCE_SECONDARIES="1",
               BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child", "1", "1"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    result = json.loads(line)
    assert result["ok"], result
    assert "fused_kernels_error" not in result, result
    assert "device_gather_error" not in result, result
    assert result["images_per_sec_per_chip_fused_kernels"] > 0
    assert result["images_per_sec_per_chip_device_gather"] > 0
    assert result["images_per_sec_per_chip_device_gather_sorted"] > 0


@pytest.mark.slow
def test_vit_child_tpu_branch_smoke_cpu():
    """The --vit child's exact TPU branch (flash attention + remat +
    bf16 + dense-attention secondary) at tiny interpret-mode shapes
    (BENCH_VIT_TPU_SMOKE): a latent bug there must surface here, not in
    a rare chip-recovery window."""
    env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_VIT="1",
               BENCH_VIT_TPU_SMOKE="1", BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child", "2", "1"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    result = json.loads(line)
    assert result["ok"], result
    assert result["attention"] == "flash" and result["remat"]
    assert result["sync"] == "host_read"
    assert "dense_attn_error" not in result, result
    assert result["images_per_sec_per_chip_dense_attn"] > 0
    assert result["flash_over_dense_speedup"] > 0


@pytest.mark.slow
def test_vit_main_line_cpu():
    """bench.py --vit end-to-end on CPU: the parent ladder, JSON-line
    contract, and field pass-through (value/mfu/model_config/sync)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_COMPILE_CACHE="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--vit"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"] == "mnist_vit_train_images_per_sec_per_chip"
    assert out["value"] > 0
    assert out["sync"] == "host_read"
    assert out["model_config"]["embed_dim"] > 0
    assert out["measured_at"].endswith("Z")


def test_refuse_fake_bounds_on_tpu(monkeypatch):
    """A test-only peak override leaking into a real-TPU child must
    refuse the run (an evidence line with fake physical bounds would
    still carry the host_read marker); on other backends it is stamped
    into the output so the line can never pass as evidence."""
    monkeypatch.setenv("BENCH_FAKE_PEAK_FLOPS", "1.0")
    result = {}
    refused = bench._refuse_fakes_on_tpu(result, "tpu")
    assert refused is not None and not refused["ok"]
    assert "BENCH_FAKE_PEAK_FLOPS" in refused["error"]
    result = {}
    assert bench._refuse_fakes_on_tpu(result, "cpu") is None
    assert result["fake_bounds"] == {"BENCH_FAKE_PEAK_FLOPS": "1.0"}
    monkeypatch.delenv("BENCH_FAKE_PEAK_FLOPS")
    result = {}
    assert bench._refuse_fakes_on_tpu(result, "tpu") is None
    assert result == {}


def test_vit_model_flops_count():
    """Pin the analytic ViT FLOPs count against a hand-derived value so a
    future edit can't silently change the MFU denominator: one block at
    T=4, C=8, r=4 is (8+16)*4*64 + 4*16*8 = 6656; embed (p=14: 2*4*196*8
    = 12544) and head (2*8*10 = 160) add, x3 for the step."""
    got = bench._vit_model_flops_per_image(4, 8, 1, 14)
    assert got == 3.0 * (6656 + 12544 + 160)


@pytest.mark.slow
def test_vit_impossible_mfu_rejected(monkeypatch):
    """The ViT child's MFU guard: a fake 1-FLOP/s peak makes any timing
    impossible; the child must return ok=False, never a number."""
    import subprocess as sp
    env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_VIT="1",
               BENCH_VIT_TPU_SMOKE="1", BENCH_COMPILE_CACHE="",
               BENCH_FAKE_PEAK_FLOPS="1.0")
    proc = sp.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--child", "1", "1"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    line = [l for l in proc.stdout.splitlines()
            if l.strip().startswith("{")][-1]
    result = json.loads(line)
    assert not result["ok"]
    assert "impossible ViT MFU" in result["error"]


@pytest.mark.slow
def test_compile_cache_config_plumbing(tmp_path):
    """BENCH_COMPILE_CACHE reaches jax_compilation_cache_dir in the child."""
    env = dict(os.environ, BENCH_FORCE_CPU="1",
               BENCH_COMPILE_CACHE=str(tmp_path / "cache"))
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu'\n"
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import sys; sys.path.insert(0, %r)\n"
        "from bench import child_bench\n"
        "# invoke only the cache-config prologue cheaply: run a 1-step probe\n"
        "r = child_bench(1, 1, probe=True)\n"
        "print('CACHE=' + jax.config.jax_compilation_cache_dir)\n"
        % REPO)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert f"CACHE={tmp_path / 'cache'}" in proc.stdout
